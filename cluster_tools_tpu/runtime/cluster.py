"""Cluster-scheduler targets: ``target='slurm'`` / ``target='lsf'``.

The reference ran every task as cluster jobs — per-job scripts submitted
with ``sbatch``/``bsub``, progress tracked through block markers on the
shared filesystem (SURVEY.md §1 L2', §7).  This framework schedules
*compute* onto the device mesh, so its cluster backend exists for the
ingest side: IO-heavy host tasks (copy_volume, downscaling, ingest
conversions) running on a cluster node that feeds the TPU host.

Design differences from the reference, on purpose:

- The unit of submission is the TASK, not per-block job arrays: blocks
  already parallelize inside one process (device batches + IO threads),
  so one node per task keeps the scheduler interaction minimal while the
  manifests + block markers keep the same resume grain.
- The submitting process stays the DAG owner: ``build()`` resolves
  dependencies and writes success manifests; the remote job only executes
  ``run_impl`` via :mod:`.cluster_runner` and reports its result in a
  JSON file.  A shared filesystem between submitter and nodes is assumed
  (the reference assumed the same).

Scheduler interaction is isolated in :class:`SlurmSubmitter` /
:class:`LSFSubmitter` (submit + liveness probe), so tests drive the full
machinery with stub ``sbatch``/``squeue`` executables and no cluster.

Config keys (per-task JSON, matching the reference's slurm knobs):
``partition``, ``time_limit`` (minutes), ``mem_limit`` (GB), ``qos``,
``poll_interval_s``, ``submit_timeout_s``, ``result_grace_s`` (wait for
the result file after the job leaves the queue — NFS cache lag),
``probe_failure_grace_s`` (continuous scheduler-unreachable stretch
tolerated before declaring the job gone).

Supervision (docs/ROBUSTNESS.md "Silent failures"): the poll loop is a
*supervisor*.  Jobs heartbeat into ``tmp_folder/heartbeats/<uid>.json``
(the batch script writes the first beat before Python even starts, the
remote runner every ``heartbeat_interval_s`` after); the supervisor
declares a job **lost** — and resubmits it, up to ``max_resubmits`` times,
without waiting out ``submit_timeout_s`` — when any of these hold:

- the scheduler stops listing it and no result file appears within
  ``result_grace_s`` (crashed / preempted without trace),
- its heartbeat file has not *changed* for ``heartbeat_timeout_s`` while
  the scheduler still claims it runs (the classic *lost array task*: the
  scheduler lies, the node is gone).  Staleness is judged by content
  change observed on the supervisor's own clock, so worker clock skew
  cannot fake a loss.  Must exceed worst-case queue wait + worker
  startup; ``0`` disables heartbeat supervision,
- the heartbeat's pid is dead on this host (same-host stub/test setups:
  instant detection).

Every loss is appended to ``cluster/supervisor.log`` and recorded in the
run's ``failures.json`` (fault class ``job_loss``, job id, resolution).

Preemption (docs/ROBUSTNESS.md "Graceful degradation"): a gracefully
drained job (SIGTERM → drain latch → ``DrainInterrupt``) leaves a *requeue
marker* (``cluster/<uid>.requeue.json``) instead of a result and exits with
``REQUEUE_EXIT_CODE``.  The supervisor, finding the marker when the job
leaves the queue, resubmits under a **separate** ``max_preempt_resubmits``
budget — an eviction is the scheduler doing its job, and must not burn the
failure-retry budget that guards against genuinely broken jobs.  Each
preemption is recorded in ``failures.json`` with ``sites: {preempt: n}``
and ``resolution: "requeued:preempt"``.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import time
from typing import Any, Dict, Optional

from ..utils import function_utils as fu
from . import faults as faults_mod
from . import trace as trace_mod
from .supervision import heartbeat_path, pid_alive, read_heartbeat


class ClusterSubmitter:
    """Submit a job script and probe whether the job still runs."""

    flavor = "abstract"

    def submit(self, script_path: str, job_name: str, out_path: str,
               cfg: Dict[str, Any]) -> str:
        raise NotImplementedError

    def is_running(self, job_id: str) -> Optional[bool]:
        """True = queued/running, False = gone from the queue, None =
        probe failed (scheduler hiccup — status unknown)."""
        raise NotImplementedError

    def cancel(self, job_id: str) -> None:
        """Best-effort kill — failure paths must not leave a zombie job
        racing a resubmission on the same uid-keyed paths."""
        raise NotImplementedError


class SlurmSubmitter(ClusterSubmitter):
    flavor = "slurm"

    def submit(self, script_path, job_name, out_path, cfg):
        cmd = ["sbatch", "--parsable", "-J", job_name, "-o", out_path]
        if cfg.get("partition"):
            cmd += ["-p", str(cfg["partition"])]
        if cfg.get("time_limit"):
            cmd += ["-t", str(int(cfg["time_limit"]))]
        if cfg.get("mem_limit"):
            cmd += ["--mem", f"{int(float(cfg['mem_limit']) * 1024)}M"]
        if cfg.get("qos"):
            cmd += ["--qos", str(cfg["qos"])]
        cmd.append(script_path)
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"sbatch failed (exit {proc.returncode}): "
                f"{proc.stderr.strip() or proc.stdout.strip()}"
            )
        # --parsable prints "<jobid>[;cluster]"
        return proc.stdout.strip().split(";")[0].strip()

    def is_running(self, job_id):
        # squeue exits 0 with no rows once the job left the queue, but
        # after MinJobAge it exits nonzero with "Invalid job id" — that is
        # a definite finish, while any other nonzero exit is a scheduler
        # hiccup with the status unknown
        probe = subprocess.run(
            ["squeue", "-h", "-j", job_id], capture_output=True, text=True
        )
        if probe.returncode != 0:
            blob = probe.stdout + probe.stderr
            if "Invalid job id" in blob:
                return False
            return None
        return bool(probe.stdout.strip())

    def cancel(self, job_id):
        subprocess.run(["scancel", job_id], capture_output=True, text=True)


class LSFSubmitter(ClusterSubmitter):
    flavor = "lsf"

    def submit(self, script_path, job_name, out_path, cfg):
        cmd = ["bsub", "-J", job_name, "-o", out_path]
        if cfg.get("partition"):
            cmd += ["-q", str(cfg["partition"])]
        if cfg.get("time_limit"):
            cmd += ["-W", str(int(cfg["time_limit"]))]
        if cfg.get("mem_limit"):
            mb = int(float(cfg["mem_limit"]) * 1024)
            cmd += ["-M", str(mb)]
        with open(script_path) as f:
            proc = subprocess.run(cmd, stdin=f, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"bsub failed (exit {proc.returncode}): "
                f"{proc.stderr.strip() or proc.stdout.strip()}"
            )
        out = proc.stdout
        # "Job <123> is submitted to ..."
        try:
            return out.split("<", 1)[1].split(">", 1)[0]
        except IndexError:
            raise RuntimeError(f"cannot parse bsub output: {out!r}")

    def is_running(self, job_id):
        probe = subprocess.run(
            ["bjobs", "-noheader", job_id], capture_output=True, text=True
        )
        blob = probe.stdout + probe.stderr
        if "is not found" in blob:  # purged from history: definite finish
            return False
        if probe.returncode != 0:
            return None
        line = probe.stdout.strip()
        return bool(line) and (" DONE " not in line and " EXIT " not in line)

    def cancel(self, job_id):
        subprocess.run(["bkill", job_id], capture_output=True, text=True)


_SUBMITTERS = {"slurm": SlurmSubmitter, "lsf": LSFSubmitter}


def submit_with_retries(
    submitter: ClusterSubmitter,
    script_path: str,
    job_name: str,
    out_path: str,
    cfg: Dict[str, Any],
    logger=None,
) -> str:
    """Submit, retrying transient scheduler failures (slurmctld restarts,
    comm timeouts — the submit-side twin of the probe-failure grace) with
    capped exponential backoff + jitter.

    Config keys: ``submit_retries`` (default 3), ``submit_backoff_s``
    (base, default 2), ``submit_backoff_max_s`` (cap, default 30).
    """
    retries = int(cfg.get("submit_retries", 3))
    base = float(cfg.get("submit_backoff_s", 2.0))
    cap = float(cfg.get("submit_backoff_max_s", 30.0))
    for attempt in range(retries + 1):
        try:
            faults_mod.get_injector().maybe_fail("submit")
            return submitter.submit(script_path, job_name, out_path, cfg)
        except FileNotFoundError:
            # sbatch/bsub not on PATH: a configuration error, not an
            # outage — retrying only delays the real message
            raise
        except Exception as e:
            if attempt >= retries:
                raise
            delay = fu.backoff_delay(attempt, base, cap)
            if logger is not None:
                logger.warning(
                    f"{submitter.flavor} submit failed (attempt "
                    f"{attempt + 1}/{retries + 1}): {e}; retrying in "
                    f"{delay:.1f}s"
                )
            time.sleep(delay)


def supervisor_log_path(tmp_folder: str) -> str:
    return os.path.join(cluster_dir(tmp_folder), "supervisor.log")


def requeue_marker_path(tmp_folder: str, uid: str) -> str:
    """Where a gracefully-preempted job leaves its requeue marker
    (``runtime/cluster_runner.py``) for the supervisor to find."""
    return os.path.join(cluster_dir(tmp_folder), f"{uid}.requeue.json")


def _sup_log(tmp_folder: str, msg: str) -> None:
    """Append one line to the run's supervisor log (the resubmission audit
    trail `make supervise-demo` prints)."""
    import datetime

    try:
        with open(supervisor_log_path(tmp_folder), "a") as f:
            f.write(f"{datetime.datetime.now().isoformat()} {msg}\n")
    except OSError:
        pass


def supervise_job(
    submitter: ClusterSubmitter,
    *,
    script_path: str,
    job_name: str,
    out_path: str,
    result_path: str,
    tmp_folder: str,
    uid: str,
    cfg: Dict[str, Any],
    logger=None,
    flavor: Optional[str] = None,
) -> Dict[str, Any]:
    """Submit a job and supervise it to a result file, resubmitting lost
    jobs (module docstring).  Returns ``{"job_id", "resubmits", "job_ids"}``
    once ``result_path`` exists; raises when the job is lost more than
    ``max_resubmits`` times or exceeds ``submit_timeout_s``.

    The ``job_loss`` fault class hooks in here: a swallowed submission gets
    a fabricated job id the "scheduler" reports as running forever, so only
    the heartbeat checks can find it — exactly the failure the supervisor
    exists for.
    """
    flavor = flavor or submitter.flavor
    injector = faults_mod.get_injector()
    poll = float(cfg.get("poll_interval_s", 5.0))
    timeout = cfg.get("submit_timeout_s")
    grace = float(cfg.get("result_grace_s", 60.0))
    probe_grace = float(cfg.get("probe_failure_grace_s", 600.0))
    hb_timeout = float(cfg.get("heartbeat_timeout_s") or 0.0)
    max_resubmits = int(cfg.get("max_resubmits", 2))
    max_preempt_resubmits = int(cfg.get("max_preempt_resubmits", 3))
    host = socket.gethostname()
    rq_path = requeue_marker_path(tmp_folder, uid)
    job_ids: list = []
    resubmits = 0
    preempt_resubmits = 0
    # heartbeat liveness is judged by CHANGE observed on the supervisor's
    # own clock, never by the timestamps inside the beat: worker nodes'
    # clocks skew, and a worker behind the supervisor would otherwise have
    # every beat discarded as stale and the healthy job declared lost
    hb_seen: Dict[str, Any] = {"raw": None, "at": 0.0}

    def _submit():
        # snapshot the heartbeat BEFORE submitting: anything the new job
        # writes afterwards registers as a change of this attempt's.
        # A leftover requeue marker must go too — only a marker written by
        # THIS attempt may count as its preemption.
        try:
            os.unlink(rq_path)
        except OSError:
            pass
        submit_t = trace_mod.walltime()
        hb_seen["raw"] = read_heartbeat(tmp_folder, uid)
        hb_seen["at"] = submit_t
        if injector.lose_job():
            job_id = f"lost:{uid}:{len(job_ids)}"
        else:
            job_id = submit_with_retries(
                submitter, script_path, job_name, out_path, cfg, logger
            )
        job_ids.append(job_id)
        return job_id, submit_t

    def _probe(job_id):
        if job_id.startswith("lost:"):
            return True  # the scheduler claims it runs; only heartbeats know
        return submitter.is_running(job_id)

    def _cancel(job_id):
        if not job_id.startswith("lost:"):
            submitter.cancel(job_id)

    def _record_loss(job_id, reason, resolved):
        fu.record_failures(
            fu.failures_path(tmp_folder),
            uid,
            [{
                "block_id": None,
                "sites": {"job_loss": resubmits},
                "error": reason,
                "quarantined": False,
                "resolved": resolved,
                "job_id": job_id,
                # full submission history: records merge by (task, block),
                # so the final resolved record must still name the lost ids
                "job_ids": list(job_ids),
            }],
        )

    def _record_preempt(job_id, reason, resolved):
        # keyed separately from the job_loss record ((task, block_id)
        # merging would otherwise have evictions and losses overwrite each
        # other): preemptions use the task's ".preempt" sub-key
        fu.record_failures(
            fu.failures_path(tmp_folder),
            f"{uid}.preempt",
            [{
                "block_id": None,
                "sites": {"preempt": preempt_resubmits},
                "error": reason,
                "quarantined": False,
                "resolved": resolved,
                "resolution": "requeued:preempt",
                "job_id": job_id,
                "job_ids": list(job_ids),
            }],
        )

    job_id, submit_t = _submit()
    if logger is not None:
        logger.info(f"{flavor} job {job_id} submitted ({script_path})")
    t0 = trace_mod.walltime()
    unknown_since = None
    while not os.path.exists(result_path):
        now = trace_mod.walltime()
        if timeout and now - t0 > float(timeout):
            _cancel(job_id)
            raise RuntimeError(
                f"{flavor} job {job_id} exceeded submit_timeout_s="
                f"{timeout} (job cancelled); see {out_path}"
            )
        running = _probe(job_id)
        unknown_since = (unknown_since or now) if running is None else None
        probe_exhausted = (
            unknown_since is not None and now - unknown_since > probe_grace
        )

        lost = None
        hb = read_heartbeat(tmp_folder, uid)
        if hb != hb_seen["raw"]:
            # the beat's CONTENT changed since we last looked: something is
            # alive out there, clocked on OUR side (skew-immune).  A beat
            # left by a previous, cancelled incarnation never changes, so
            # it cannot keep a lost resubmission looking alive.
            hb_seen["raw"] = hb
            hb_seen["at"] = now
        last_alive = hb_seen["at"]
        beat_this_attempt = hb is not None and last_alive > submit_t
        if (
            beat_this_attempt
            and hb.get("host") == host
            and hb.get("pid") is not None
            and not pid_alive(hb["pid"])
        ):
            lost = f"heartbeat pid {hb['pid']} on {host} is dead"
        if (
            lost is None
            and hb_timeout
            and running is not False
            and now - last_alive > hb_timeout
        ):
            lost = (
                f"no live heartbeat for {now - last_alive:.1f}s "
                f"(heartbeat_timeout_s={hb_timeout:g}) while the scheduler "
                f"reports the job as {'running' if running else 'unknown'}"
            )
        if running is False or probe_exhausted:
            # job left the queue (or scheduler unreachable too long): give
            # the result file an NFS-lag grace window before declaring loss
            t_gone = trace_mod.walltime()
            while (trace_mod.walltime() - t_gone < grace
                   and not os.path.exists(result_path)):
                time.sleep(min(poll, 2.0))
            if os.path.exists(result_path):
                break
            lost = (
                "job left the queue without a result file"
                if running is False
                else f"scheduler unreachable for {probe_grace:.0f}s "
                     "and no result file"
            )

        if lost:
            rq = fu.read_json_if_valid(rq_path)
            if rq is not None:
                # not a loss: the job drained gracefully for a preemption
                # and asked to be requeued.  Separate budget — an eviction
                # is the scheduler doing its job, not a broken task.
                _cancel(job_id)
                if preempt_resubmits >= max_preempt_resubmits:
                    _sup_log(
                        tmp_folder,
                        f"{uid}: job {job_id} preempted again; "
                        f"max_preempt_resubmits={max_preempt_resubmits} "
                        "exhausted, giving up",
                    )
                    raise RuntimeError(
                        f"{flavor} job for {uid} was preempted "
                        f"{preempt_resubmits + 1} times "
                        f"(max_preempt_resubmits={max_preempt_resubmits}) — "
                        "giving up; the partial progress is markered and a "
                        "re-run resumes at block grain"
                    )
                preempt_resubmits += 1
                msg = (
                    f"{uid}: job {job_id} preempted "
                    f"({rq.get('reason', 'drained')}, "
                    f"{rq.get('remaining_blocks', '?')} block(s) left); "
                    f"requeueing ({preempt_resubmits}/{max_preempt_resubmits})"
                )
                if logger is not None:
                    logger.warning(msg)
                _sup_log(tmp_folder, msg)
                _record_preempt(job_id, rq.get("reason"), resolved=False)
                unknown_since = None
                job_id, submit_t = _submit()
                if logger is not None:
                    logger.info(
                        f"{flavor} job {job_id} requeued after preemption"
                    )
                continue
            _cancel(job_id)  # a zombie must not race the resubmission
            if resubmits >= max_resubmits:
                tail = ""
                try:
                    with open(out_path) as f:
                        tail = f.read()[-2000:]
                except OSError:
                    pass
                _sup_log(
                    tmp_folder,
                    f"{uid}: job {job_id} lost ({lost}); "
                    f"max_resubmits={max_resubmits} exhausted, giving up",
                )
                raise RuntimeError(
                    f"{flavor} job for {uid} lost ({lost}) after "
                    f"{resubmits} resubmission(s) — giving up.  "
                    f"Job output tail:\n{tail}"
                )
            resubmits += 1
            msg = (
                f"{uid}: job {job_id} declared lost ({lost}); "
                f"resubmitting ({resubmits}/{max_resubmits})"
            )
            if logger is not None:
                logger.warning(msg)
            _sup_log(tmp_folder, msg)
            _record_loss(job_id, lost, resolved=False)
            unknown_since = None
            job_id, submit_t = _submit()
            if logger is not None:
                logger.info(f"{flavor} job {job_id} resubmitted")
            continue
        time.sleep(poll)

    if resubmits:
        _record_loss(job_id, None, resolved=True)
        _sup_log(
            tmp_folder,
            f"{uid}: job {job_id} delivered a result after {resubmits} "
            f"resubmission(s)",
        )
    if preempt_resubmits:
        _record_preempt(job_id, None, resolved=True)
        _sup_log(
            tmp_folder,
            f"{uid}: job {job_id} delivered a result after "
            f"{preempt_resubmits} preemption requeue(s)",
        )
    return {
        "job_id": job_id,
        "resubmits": resubmits,
        "preempt_resubmits": preempt_resubmits,
        "job_ids": job_ids,
    }


def _spec_default(obj):
    """Numpy scalars/arrays become their Python equivalents; anything else
    fails AT SUBMIT TIME instead of reaching the remote node stringified."""
    item = getattr(obj, "item", None)
    if item is not None and getattr(obj, "ndim", 1) == 0:
        return item()
    tolist = getattr(obj, "tolist", None)
    if tolist is not None:
        return tolist()
    raise TypeError(
        f"task param of type {type(obj).__name__} is not JSON-serializable; "
        "cluster targets re-execute the task from a JSON spec, so params "
        "must be plain Python / numpy values"
    )


def cluster_dir(tmp_folder: str) -> str:
    d = os.path.join(tmp_folder, "cluster")
    os.makedirs(d, exist_ok=True)
    return d


def make_cluster_task(local_cls, flavor: str):
    """Wrap an ``<Op>Local`` class into a submitting ``<Op>Slurm``/``LSF``.

    The wrapper's ``run_impl`` serializes the task spec, submits a batch
    script that re-executes the LOCAL variant remotely
    (:mod:`.cluster_runner`), polls the scheduler plus the result file,
    and returns the remote result — so manifests, markers, logs, and
    resume behave exactly as for a local run.
    """
    submitter_cls = _SUBMITTERS[flavor]

    def run_impl(self):
        cfg = self.get_config()
        cdir = cluster_dir(self.tmp_folder)
        spec = {
            "module": local_cls.__module__,
            "cls": local_cls.__name__,
            "tmp_folder": self.tmp_folder,
            "config_dir": self.config_dir,
            "max_jobs": self.max_jobs,
            "params": self.params,
            "result_path": os.path.join(cdir, f"{self.uid}.result.json"),
            # liveness: the remote runner heartbeats under this uid so the
            # supervisor below can tell a lost job from a slow one
            "uid": self.uid,
            "heartbeat_interval_s": float(cfg.get("heartbeat_interval_s", 5.0)),
            # graceful preemption: a drained job leaves this marker instead
            # of a result, and the supervisor requeues it
            "requeue_path": requeue_marker_path(self.tmp_folder, self.uid),
        }
        spec_path = os.path.join(cdir, f"{self.uid}.spec.json")
        # atomic (CT002): the spec is read by the remote worker over the
        # shared filesystem; it must never observe a torn document
        fu.atomic_write_json(spec_path, spec, default=_spec_default)
        script_path = os.path.join(cdir, f"{self.uid}.sh")
        out_path = os.path.join(cdir, f"{self.uid}.out")
        # the remote interpreter must find this package regardless of the
        # job's working directory (the reference wrote shebang/env lines
        # into its job scripts for the same reason)
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        hb_path = heartbeat_path(self.tmp_folder, self.uid)
        # unified tracing plane (docs/OBSERVABILITY.md): a traced submitter
        # hands the worker the run's shard directory through the
        # environment, so the remote process's spans land on the SAME
        # merged timeline (the env value both enables tracing and pins the
        # directory)
        trace_export = ""
        if trace_mod.enabled():
            trace_dir = trace_mod.trace_dir() or os.path.join(
                self.tmp_folder, trace_mod.TRACE_DIRNAME
            )
            trace_export = f"export CTT_TRACE={trace_dir}\n"
        with open(script_path, "w") as f:
            f.write(
                "#!/bin/bash\n"
                f"export PYTHONPATH={pkg_root}:$PYTHONPATH\n"
                # no in-memory handoffs across a host boundary: the worker
                # process's memory dies before the submitter-side consumer
                # runs, so its intermediate outputs must hit storage
                # (docs/PERFORMANCE.md "Task-graph fusion")
                "export CTT_HANDOFF=0\n"
                f"{trace_export}"
                # boot heartbeat from the shell, BEFORE the interpreter
                # starts: the supervisor's staleness clock must not count
                # queue exit -> first Python beat (slow jax imports) as
                # dead air.  exec keeps the pid, so the pid stays valid.
                f"mkdir -p {os.path.dirname(hb_path)}\n"
                'printf \'{"time": %s, "pid": %s, "host": "%s"}\' '
                '"$(date +%s)" "$$" "$(hostname)" '
                f"> {hb_path}.boot && mv {hb_path}.boot {hb_path}\n"
                f"exec {fu.python_executable()} -m "
                f"cluster_tools_tpu.runtime.cluster_runner {spec_path}\n"
            )
        os.chmod(script_path, 0o755)
        # a retry must not consume the previous attempt's result (nor its
        # heartbeat: a stale beat would mask a lost resubmission)
        for stale in (spec["result_path"], hb_path):
            try:
                os.unlink(stale)
            except OSError:
                pass

        submitter = submitter_cls()
        sup = supervise_job(
            submitter,
            script_path=script_path,
            job_name=self.uid,
            out_path=out_path,
            result_path=spec["result_path"],
            tmp_folder=self.tmp_folder,
            uid=self.uid,
            cfg=cfg,
            logger=self.logger,
            flavor=flavor,
        )
        with open(spec["result_path"]) as f:
            remote = json.load(f)
        if not remote.get("ok"):
            raise RuntimeError(
                f"{flavor} job {sup['job_id']} failed remotely: "
                f"{remote.get('error', 'unknown error')}"
            )
        result = remote.get("result") or {}
        if sup["resubmits"]:
            result["supervisor"] = {
                "resubmits": sup["resubmits"],
                "job_ids": sup["job_ids"],
            }
        return result

    return type(
        local_cls.__name__.replace("Local", flavor.upper() if flavor == "lsf"
                                   else flavor.capitalize()),
        (local_cls,),
        {"target": flavor, "run_impl": run_impl},
    )
