"""Remote entry point for cluster-target jobs (``python -m
cluster_tools_tpu.runtime.cluster_runner <spec.json>``).

Reconstructs the LOCAL variant of a task from the spec written by
:mod:`.cluster`'s submitting wrapper and executes its ``run_impl`` on the
node, writing ``{ok, result|error}`` to the spec's ``result_path``
(atomic tmp+rename: the submitter polls for this file on the shared
filesystem).  Block markers and per-task logs land in the shared
``tmp_folder`` exactly as for a local run, so a preempted job resumes at
the block grain when resubmitted.

Liveness: for specs carrying a ``uid``, a heartbeat thread writes
``tmp_folder/heartbeats/<uid>.json`` every ``heartbeat_interval_s`` for
the submitting supervisor's staleness/pid checks (the batch script wrote
the first beat before Python started — see ``runtime/cluster.py``).

Preemption (docs/ROBUSTNESS.md "Graceful degradation"): a SIGTERM/SIGUSR1
(scheduler eviction, injected ``preempt`` fault) flips the drain latch
instead of killing the job; the executor/task runtime finishes in-flight
blocks, flushes markers, and raises ``DrainInterrupt``, which this runner
turns into a *requeue marker* (``<uid>.requeue.json`` next to the result
file) plus exit code ``REQUEUE_EXIT_CODE`` — no result file is written, so
the supervisor sees the job leave the queue, finds the marker, and
resubmits under its preemption budget instead of burning failure retries.
"""

from __future__ import annotations

import importlib
import json
import os
import socket
import sys
import traceback


def main(spec_path: str) -> int:
    with open(spec_path) as f:
        spec = json.load(f)

    # honor an explicit CPU pin before any task import touches jax: the
    # env var alone is overridden by platform-pinning sitecustomize hooks
    # (same pattern as bench.py / tests/conftest.py)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    result_path = spec["result_path"]

    def emit(payload) -> None:
        # numpy-aware serialization (same as SuccessTarget manifests) so
        # manifest field types match target='local' exactly
        from ..utils.task_utils import _default

        tmp = f"{result_path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=_default)
        os.replace(tmp, result_path)

    from .supervision import (
        REQUEUE_EXIT_CODE,
        DrainInterrupt,
        install_drain_handler,
        write_heartbeat,
    )

    # arm graceful preemption BEFORE any work: the scheduler's eviction
    # SIGTERM must flip the drain latch, not kill the interpreter mid-block
    install_drain_handler()

    heartbeat = None
    if spec.get("uid"):
        from .supervision import HeartbeatWriter

        heartbeat = HeartbeatWriter(
            spec["tmp_folder"], spec["uid"],
            float(spec.get("heartbeat_interval_s", 5.0)),
        ).start()

    # unified tracing plane (docs/OBSERVABILITY.md): when the submitter's
    # batch script exported CTT_TRACE=<dir>, this process traces into the
    # same shard directory — the worker's spans interleave with the
    # submitter's on one clock-corrected timeline.  The lifetime span is
    # the "cluster-worker lifetime" track; the flush in the finally is
    # best-effort by contract (observability must never fail the job).
    from . import trace as trace_mod

    worker_span = trace_mod.begin(
        "cluster.worker", task=spec.get("uid"), spec=os.path.basename(spec_path)
    )

    def _flush_trace(error: bool = False) -> None:
        try:
            worker_span.end(error=True) if error else worker_span.end()
            trace_mod.flush()
        except Exception:
            pass

    try:
        from . import faults as faults_mod

        # fault specs with a "tasks" filter target this job's task uid
        faults_mod.set_current_task(spec.get("uid"))
        module = importlib.import_module(spec["module"])
        cls = getattr(module, spec["cls"])
        task = cls(
            tmp_folder=spec["tmp_folder"],
            config_dir=spec["config_dir"],
            max_jobs=int(spec["max_jobs"]),
            **spec["params"],
        )
        # the chunk IO happens HERE, in the cluster worker process — the
        # submitter only polls — so this process must record its own
        # io_metrics delta into the shared manifest (additive merge, same
        # discipline as BaseTask.run on the local target)
        from ..io import chunk_cache
        from ..utils import function_utils as fu

        io_snap = chunk_cache.snapshot()
        try:
            result = task.run_impl()
        finally:
            io_metrics = chunk_cache.delta(io_snap)
            if any(io_metrics.values()):
                try:
                    fu.record_io_metrics(
                        fu.io_metrics_path(spec["tmp_folder"]),
                        # the submitter-side uid (heartbeats, failure
                        # records, scheduler artifacts all key on it) —
                        # not the worker's re-derived local identity
                        spec.get("uid") or task.uid,
                        io_metrics,
                    )
                except OSError:
                    pass
        _flush_trace()
        emit({"ok": True, "result": result})
        return 0
    except DrainInterrupt as e:
        # drained for preemption: markers/manifests are flushed, so leave a
        # requeue marker (NOT a result — the work is unfinished) and exit
        # with the requeue code; the supervisor resubmits under its
        # preemption budget and the resumed job picks up at block grain
        requeue_path = spec.get("requeue_path")
        if requeue_path:
            tmp = f"{requeue_path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump({
                    "preempted": True,
                    "reason": e.reason,
                    "remaining_blocks": len(e.remaining_ids),
                    "time": trace_mod.walltime(),
                    "host": socket.gethostname(),
                    "pid": os.getpid(),
                }, f)
            os.replace(tmp, requeue_path)
        _flush_trace()
        if spec.get("uid"):
            # one last beat so the supervisor's staleness clock sees the
            # drain, not dead air, while the marker propagates over NFS
            try:
                write_heartbeat(spec["tmp_folder"], spec["uid"])
            except OSError:
                pass
        return REQUEUE_EXIT_CODE
    except Exception as e:  # noqa: BLE001 - report ANY failure to the poller
        _flush_trace(error=True)
        emit({
            "ok": False,
            "error": f"{type(e).__name__}: {e}",
            "traceback": traceback.format_exc(),
        })
        return 1
    finally:
        if heartbeat is not None:
            heartbeat.stop()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
