"""Remote entry point for cluster-target jobs (``python -m
cluster_tools_tpu.runtime.cluster_runner <spec.json>``).

Reconstructs the LOCAL variant of a task from the spec written by
:mod:`.cluster`'s submitting wrapper and executes its ``run_impl`` on the
node, writing ``{ok, result|error}`` to the spec's ``result_path``
(atomic tmp+rename: the submitter polls for this file on the shared
filesystem).  Block markers and per-task logs land in the shared
``tmp_folder`` exactly as for a local run, so a preempted job resumes at
the block grain when resubmitted.

Liveness: for specs carrying a ``uid``, a heartbeat thread writes
``tmp_folder/heartbeats/<uid>.json`` every ``heartbeat_interval_s`` for
the submitting supervisor's staleness/pid checks (the batch script wrote
the first beat before Python started — see ``runtime/cluster.py``).
"""

from __future__ import annotations

import importlib
import json
import os
import sys
import traceback


def main(spec_path: str) -> int:
    with open(spec_path) as f:
        spec = json.load(f)

    # honor an explicit CPU pin before any task import touches jax: the
    # env var alone is overridden by platform-pinning sitecustomize hooks
    # (same pattern as bench.py / tests/conftest.py)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    result_path = spec["result_path"]

    def emit(payload) -> None:
        # numpy-aware serialization (same as SuccessTarget manifests) so
        # manifest field types match target='local' exactly
        from ..utils.task_utils import _default

        tmp = f"{result_path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=_default)
        os.replace(tmp, result_path)

    heartbeat = None
    if spec.get("uid"):
        from .supervision import HeartbeatWriter

        heartbeat = HeartbeatWriter(
            spec["tmp_folder"], spec["uid"],
            float(spec.get("heartbeat_interval_s", 5.0)),
        ).start()

    try:
        from . import faults as faults_mod

        # fault specs with a "tasks" filter target this job's task uid
        faults_mod.set_current_task(spec.get("uid"))
        module = importlib.import_module(spec["module"])
        cls = getattr(module, spec["cls"])
        task = cls(
            tmp_folder=spec["tmp_folder"],
            config_dir=spec["config_dir"],
            max_jobs=int(spec["max_jobs"]),
            **spec["params"],
        )
        result = task.run_impl()
        emit({"ok": True, "result": result})
        return 0
    except Exception as e:  # noqa: BLE001 - report ANY failure to the poller
        emit({
            "ok": False,
            "error": f"{type(e).__name__}: {e}",
            "traceback": traceback.format_exc(),
        })
        return 1
    finally:
        if heartbeat is not None:
            heartbeat.stop()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
