"""Blockwise executor: maps the block grid onto the device mesh.

This is the TPU-native replacement for the reference's job machinery
(``prepare_jobs`` / ``submit_jobs`` / ``wait_for_jobs`` in SURVEY.md §2a):
instead of serializing per-job JSON configs and submitting slurm array jobs,
the driver batches blocks into device-sized groups, streams them host->HBM
with a double-buffered prefetch pipeline, and runs one jitted, vmapped kernel
per batch with the batch axis sharded across the mesh.

The pipeline per batch:

    host threads: read blocks (+halo) from chunked storage, pad to the
                  static outer shape                               [IO bound]
    device:       jit(vmap(kernel)) over the batch, batch axis sharded
                  across devices                                   [compute]
    host threads: crop inner blocks, write to chunked storage      [IO bound]

Reads for batch i+1 overlap compute for batch i (prefetch depth 2); writes
are fire-and-forget futures drained at the end.  Block-level success markers
give the same resume grain as the reference's ``log_block_success``.
"""

from __future__ import annotations

import math
from concurrent.futures import ThreadPoolExecutor, Future
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.volume_utils import Block, Blocking


# canonical device-selection policy lives in parallel/mesh.py
from ..parallel.mesh import backend_devices as get_devices


def get_mesh(
    target: str = "local",
    n_devices: Optional[int] = None,
    axis_name: str = "blocks",
) -> Mesh:
    devs = get_devices(target, n_devices)
    return Mesh(np.array(devs), (axis_name,))


class BlockwiseExecutor:
    """Run a per-block kernel over a list of blocks, batched across devices.

    ``kernel`` is a pure function over one block's arrays; it is vmapped,
    jitted, and the batch axis is sharded over the mesh.  ``load_fn(block)``
    returns the kernel's input arrays for one block (already padded to a
    uniform shape); ``store_fn(block, outputs)`` persists one block's outputs
    (each already a numpy array).
    """

    def __init__(
        self,
        target: str = "local",
        n_devices: Optional[int] = None,
        device_batch: int = 1,
        io_threads: int = 8,
    ):
        self.target = target
        self.devices = get_devices(target, n_devices)
        self.n_devices = len(self.devices)
        self.device_batch = int(device_batch)
        self.batch_size = self.n_devices * self.device_batch
        self.mesh = Mesh(np.array(self.devices), ("blocks",))
        self.io_threads = io_threads

    def map_blocks(
        self,
        kernel: Callable,
        blocks: Sequence[Block],
        load_fn: Callable[[Block], Tuple],
        store_fn: Optional[Callable[[Block, Any], None]] = None,
        on_block_done: Optional[Callable[[Block], None]] = None,
        prefetch: int = 2,
    ) -> None:
        """Execute ``kernel`` over ``blocks``; see class docstring."""
        if not blocks:
            return
        bs = self.batch_size
        n_batches = math.ceil(len(blocks) / bs)
        sharding = NamedSharding(self.mesh, P("blocks"))
        batched_kernel = jax.jit(
            jax.vmap(kernel), in_shardings=sharding, out_shardings=sharding
        )

        def load_batch(batch_idx: int):
            batch = blocks[batch_idx * bs : (batch_idx + 1) * bs]
            # load_fn may return futures (e.g. io.prefetch.async_loader's
            # tensorstore read futures): issue EVERY read of the batch first,
            # then resolve — the storage layer runs the chunk IO concurrently
            per_block = [load_fn(b) for b in batch]
            per_block = [
                tuple(
                    x.result() if hasattr(x, "result") else x for x in pb
                )
                for pb in per_block
            ]
            n_args = len(per_block[0])
            # pad the final partial batch by repeating the last block so the
            # compiled shape stays static; padded outputs are dropped
            n_pad = bs - len(batch)
            if n_pad:
                per_block = per_block + [per_block[-1]] * n_pad
            arrays = tuple(
                np.stack([pb[i] for pb in per_block]) for i in range(n_args)
            )
            return batch, arrays

        with ThreadPoolExecutor(max_workers=self.io_threads) as pool:
            pending_loads: List[Future] = [
                pool.submit(load_batch, i) for i in range(min(prefetch, n_batches))
            ]
            write_futures: List[Future] = []
            for i in range(n_batches):
                batch, arrays = pending_loads.pop(0).result()
                if i + prefetch < n_batches:
                    pending_loads.append(pool.submit(load_batch, i + prefetch))
                arrays = tuple(jax.device_put(a, sharding) for a in arrays)
                out = batched_kernel(*arrays)

                def store_batch(batch=batch, out=out):
                    # the device->host copy happens HERE, on the IO pool, so
                    # the dispatch loop is free to enqueue the next batch
                    # while this one's outputs stream back
                    out_np = jax.tree_util.tree_map(np.asarray, out)
                    for j, blk in enumerate(batch):
                        block_out = jax.tree_util.tree_map(
                            lambda a: a[j], out_np
                        )
                        if store_fn is not None:
                            store_fn(blk, block_out)
                        if on_block_done is not None:
                            on_block_done(blk)

                write_futures.append(pool.submit(store_batch))
                # backpressure: each pending store closure pins its batch's
                # DEVICE output buffers until its d2h copy runs, so the bound
                # must be a small constant (not thread-count) or HBM fills
                # with undrained outputs
                while len(write_futures) > 2:
                    write_futures.pop(0).result()
            for f in write_futures:
                f.result()
