"""Blockwise executor: maps the block grid onto the device mesh.

This is the TPU-native replacement for the reference's job machinery
(``prepare_jobs`` / ``submit_jobs`` / ``wait_for_jobs`` in SURVEY.md §2a):
instead of serializing per-job JSON configs and submitting slurm array jobs,
the driver batches blocks into device-sized groups, streams them host->HBM
with a double-buffered prefetch pipeline, and runs one jitted, vmapped kernel
per batch with the batch axis sharded across the mesh.

The pipeline per batch:

    host threads: read blocks (+halo) from chunked storage, pad to the
                  static outer shape                               [IO bound]
    device:       one compiled program over the batch, batch axis sharded
                  across devices                                   [compute]
    host threads: crop inner blocks, write to chunked storage      [IO bound]

Reads for batch i+1 overlap compute for batch i (prefetch depth 2); writes
are fire-and-forget futures drained promptly in a bounded window.

Sweep modes (docs/PERFORMANCE.md "Sharded sweeps"): the historical
``per_block`` path compiles ``jit(vmap(kernel))`` at width ``n_devices *
device_batch`` — one dispatch per block on a single-device host, each
paying dispatch + host-sync overhead behind the dispatch lock.  The
``sharded`` mode instead executes a whole Morton batch of blocks as ONE
``shard_map`` program over the device mesh
(:func:`~cluster_tools_tpu.parallel.batch_shard.batched_shard_map`): the
stacked batch axis is split across devices, each device vmaps the kernel
over its sub-batch, and the dispatch lock is held once per batch.  The
default ``sweep_mode="auto"`` picks sharded when the mesh has >= 2 devices
or the sweep has at least one full sharded batch.  Sharded output is
bit-identical to the per-block path (per-lane vmap numerics are width-
independent; asserted by tests/test_sharded.py and ``bench.py --sweep``),
and the per-block program remains the degrade/speculation fallback: a
sharded batch that hits a device OOM or a hung device falls back to
per-block execution for its blocks, attributed in ``failures.json`` as
``resolution="degraded:unsharded"``.

Fault tolerance (docs/ROBUSTNESS.md): per-block loads and stores retry with
exponential backoff + jitter; blocks that exhaust their retries (or whose
outputs fail validation — NaN/inf, or a task-supplied ``validate_fn``) are
*quarantined*: the batch and the run continue, and quarantined blocks are
re-attempted at the end on a reduced-batch path (the block replicated to the
batch width through the *same* compiled kernel, so a recovered block is
bit-identical to an undisturbed run).  Every block that ever failed is
recorded in a structured ``failures.json`` manifest (block id, per-site
attempt counts, capped traceback, resolution); blocks that stay failed after
the quarantine pass raise with their ids attributed.  Block-level success
markers give the same resume grain as the reference's ``log_block_success``
— ``done_block_ids`` filters them built-in.

Silent failures (docs/ROBUSTNESS.md "Silent failures"): ``block_deadline_s``
arms a watchdog that detects *hung* blocks (stuck IO, wedged kernel) within
one watchdog period of the deadline, quarantines them, and speculatively
re-executes them through the same compiled kernel — first result wins, with
a bit-identity check when both copies complete.  ``store_verify_fn`` (built
by :func:`region_verifier` from a checksummed dataset) re-reads each stored
region so a chunk corrupted on storage is repaired by a re-store (retry) or
a recompute (quarantine) while the writer still owns the block.

Graceful degradation (docs/ROBUSTNESS.md "Graceful degradation"): resource
exhaustion — host/device OOM (``MemoryError``, XLA ``RESOURCE_EXHAUSTED``)
and a full filesystem (``ENOSPC``/``EDQUOT``) — is *classified*
(:func:`classify_resource_error`) and routed to a degrade policy instead of
same-size retries (re-running the exact allocation that just failed only
burns the retry budget): the block waits for headroom and re-executes once
at full size through the same compiled kernel (``degraded:backpressure``),
then — for call sites that declare ``splittable=True`` — recursively
re-executes as 2^d halo-correct sub-blocks through the same kernel down to
``min_block_shape``, reassembled via the task's own store path
(``degraded:split``).  A byte-budget admission controller additionally caps
the bytes of in-flight batches and backpressures the store drain when
host-memory or disk headroom runs low.  Preemption: SIGTERM/SIGUSR1 flip a
process-wide drain latch; the sweep stops claiming batches, finishes
in-flight work, flushes markers + ``failures.json``, and raises
:class:`~cluster_tools_tpu.runtime.supervision.DrainInterrupt` so the entry
point exits with ``REQUEUE_EXIT_CODE`` and the supervisor requeues the job.
"""

from __future__ import annotations

import contextlib
import errno
import functools
import inspect
import itertools
import math
import os
import threading
import time
import traceback
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor, Future
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..io import chunk_cache as chunk_cache_mod
from ..io.containers import ChunkCorruptionError
from . import admission as admission_mod
from . import handoff as handoff_mod
from ..utils import function_utils as fu
from ..utils.volume_utils import Block, Blocking
from . import faults as faults_mod
from . import trace as trace_mod
from .supervision import (
    DrainInterrupt,
    FirstWins,
    Watchdog,
    array_digest,
    disk_free_fraction,
    drain_reason,
    drain_requested,
    host_mem_available_bytes,
    host_mem_available_fraction,
    install_drain_handler,
)


# canonical device-selection policy lives in parallel/mesh.py
from ..parallel.mesh import backend_devices as get_devices
from ..parallel.batch_shard import (
    batched_shard_map,
    ragged_shard_map,
    resolve_sharded_batch,
    use_sharded_sweep,
)
from ..parallel import block_pool as block_pool_mod
from ..parallel import device_pool as device_pool_mod


# -- process-wide dispatch metrics -------------------------------------------
# Mirrors io/chunk_cache.py's snapshot/delta counters: the task runtime
# snapshots around run_impl and merges the delta into io_metrics.json, so
# the dispatch-amortization win of the sharded sweep is observable per task
# (docs/PERFORMANCE.md "Sharded sweeps"), not just in bench.

_METRICS_LOCK = threading.Lock()
_DISPATCH_COUNTERS = {
    "batches_dispatched": 0,   # compiled-program executions (batch grain)
    "blocks_dispatched": 0,    # blocks carried by those executions
    "dispatch_wait_s": 0.0,    # dispatch loop stalled on un-overlapped loads
    "sweep_s": 0.0,            # total map_blocks wall time
    # ragged paged sweeps (docs/PERFORMANCE.md "Ragged sweeps"): batches
    # that ran mixed-shape/partial work as one program via the paged
    # block pool, the synthetic padding lanes they carried (discarded on
    # d2h), and the real pool pages those dispatches referenced
    "ragged_batches": 0,
    "lanes_padded": 0,
    "pages_in_use": 0,
}


def dispatch_snapshot() -> Dict[str, float]:
    """Current process-wide dispatch counters (monotonic; diff two
    snapshots with :func:`dispatch_delta` to attribute a task's share)."""
    with _METRICS_LOCK:
        return dict(_DISPATCH_COUNTERS)


def dispatch_delta(snapshot: Dict[str, float]) -> Dict[str, float]:
    """Counter movement since ``snapshot`` (same keys)."""
    cur = dispatch_snapshot()
    return {k: cur[k] - snapshot.get(k, 0) for k in cur}


def _record_dispatch_metrics(batches: int, blocks: int, wait_s: float,
                             sweep_s: float, ragged_batches: int = 0,
                             lanes_padded: int = 0,
                             pages_in_use: int = 0) -> None:
    with _METRICS_LOCK:
        _DISPATCH_COUNTERS["batches_dispatched"] += int(batches)
        _DISPATCH_COUNTERS["blocks_dispatched"] += int(blocks)
        _DISPATCH_COUNTERS["dispatch_wait_s"] += float(wait_s)
        _DISPATCH_COUNTERS["sweep_s"] += float(sweep_s)
        _DISPATCH_COUNTERS["ragged_batches"] += int(ragged_batches)
        _DISPATCH_COUNTERS["lanes_padded"] += int(lanes_padded)
        _DISPATCH_COUNTERS["pages_in_use"] += int(pages_in_use)


#: bound on one executor's compiled-program cache (see
#: :meth:`BlockwiseExecutor._cached_program`); a sweep holds at most a few
#: programs (sharded, ragged, per-block fallback, sub-block), the rest is
#: headroom for executors reused across many kernels.
_PROGRAM_CACHE_SIZE = 16

#: bound on a server-scoped shared cache (docs/SERVING.md): programs for
#: the repeat-request working set of a resident server.
SHARED_PROGRAM_CACHE_SIZE = 64


class _Unfreezable(Exception):
    """A captured value that cannot participate in a kernel identity."""


def _freeze(obj, seen: set, depth: int = 0):
    """A hashable, value-equal snapshot of ``obj`` for kernel-identity
    keys, or :class:`_Unfreezable`.  Containers and callables recurse
    (bounded, cycle-guarded); arrays / datasets / arbitrary objects refuse
    — a kernel closing over them only ever hits the instance cache."""
    if depth > 16:
        raise _Unfreezable("nesting too deep")
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return obj
    if isinstance(obj, np.generic):
        return ("np", obj.dtype.name, obj.item())
    oid = id(obj)
    if oid in seen:
        raise _Unfreezable("cyclic capture")
    seen = seen | {oid}
    if isinstance(obj, (tuple, list)):
        return ("seq", tuple(_freeze(v, seen, depth + 1) for v in obj))
    if isinstance(obj, (set, frozenset)):
        return ("set", frozenset(_freeze(v, seen, depth + 1) for v in obj))
    if isinstance(obj, dict):
        return ("map", tuple(
            (_freeze(k, seen, depth + 1), _freeze(v, seen, depth + 1))
            for k, v in sorted(obj.items(), key=lambda kv: repr(kv[0]))
        ))
    if isinstance(obj, functools.partial):
        return (
            "partial",
            _freeze(obj.func, seen, depth + 1),
            _freeze(obj.args, seen, depth + 1),
            _freeze(obj.keywords, seen, depth + 1),
        )
    # named code objects a kernel commonly captures via function-local
    # imports (``import jax.numpy as jnp`` inside run_impl makes jnp a
    # closure CELL): stable within one process, identified by name
    if inspect.ismodule(obj):
        return ("module", obj.__name__)
    if inspect.isbuiltin(obj) or isinstance(obj, np.ufunc):
        return ("builtin", getattr(obj, "__module__", None), obj.__name__)
    if isinstance(obj, type):
        return ("type", obj.__module__, obj.__qualname__)
    if inspect.ismethod(obj):
        return (
            "method",
            _freeze(obj.__func__, seen, depth + 1),
            _freeze(obj.__self__, seen, depth + 1),
        )
    if isinstance(obj, np.dtype):
        return ("dtype", obj.name)
    if inspect.isfunction(obj):
        cells = ()
        if obj.__closure__:
            vals = []
            for cell in obj.__closure__:
                try:
                    vals.append(_freeze(cell.cell_contents, seen, depth + 1))
                except ValueError:  # empty cell
                    vals.append(("empty-cell",))
            cells = tuple(vals)
        return (
            "fn", obj.__module__, obj.__qualname__,
            _freeze_code(obj.__code__, seen, depth + 1),
            cells,
            _freeze(obj.__defaults__, seen, depth + 1),
            _freeze(obj.__kwdefaults__, seen, depth + 1),
        )
    raise _Unfreezable(type(obj).__name__)


def _freeze_code(code, seen: set, depth: int):
    """Behavioral snapshot of a code object: bytecode alone is NOT enough
    (two kernels calling np.minimum vs np.maximum differ only in
    ``co_names``; nested lambdas differ only in their own consts), so the
    freeze carries the referenced names and recurses into nested code."""
    if depth > 16:
        raise _Unfreezable("code nesting too deep")
    consts = tuple(
        _freeze_code(c, seen, depth + 1) if inspect.iscode(c)
        else _freeze(c, seen, depth + 1)
        for c in code.co_consts
    )
    return ("code", code.co_code, code.co_names, consts)


def kernel_identity(kernel: Callable) -> Optional[tuple]:
    """A hashable identity for ``kernel`` that two *different* callables
    share exactly when their code AND captured values are equal: module /
    qualname / bytecode / recursively frozen closure cells and defaults.
    This is what lets a server-scoped :class:`ProgramCache` serve a warm
    compiled program to a repeat request whose task rebuilt its kernel
    closure (docs/SERVING.md).  Returns None when any captured value
    cannot be frozen (model checkpoints, datasets, ad-hoc objects) — such
    kernels stay instance-scoped, which is always safe.

    Module-level globals the kernel references are NOT part of the
    identity (they are not captured cells); the shared cache therefore
    assumes module code is stable within the server process — true for a
    resident server, and why the batch CLI keeps instance scope.
    Captured dicts freeze by sorted content — Python ``==`` semantics —
    so a kernel whose *trace* depends on dict insertion order (iterating
    ``cfg.items()`` into order-sensitive float accumulation) is outside
    the contract; request configs parsed from JSON documents have stable
    order anyway.
    """
    try:
        return _freeze(kernel, set())
    except _Unfreezable:
        return None


class ProgramCache:
    """Thread-safe bounded LRU of compiled program wrappers.

    Instance-scoped by default (``by_identity=False``): keys include
    ``id(kernel)``, entries strongly reference the kernel so the id stays
    valid, and the cache dies with its executor — a cached wrapper can pin
    a task's captured state (e.g. a model checkpoint), so it must not
    outlive the task (the PR-7 rationale).

    ``by_identity=True`` is the server-scoped promotion (docs/SERVING.md):
    keys use :func:`kernel_identity` + the program's mode/width/devices
    key, so repeat requests through a resident server skip the per-shape
    compile even though every request builds a fresh kernel closure.  The
    LRU bound is what bounds the pinned closures; the resident server is
    exactly the owner that wants warm programs pinned.
    """

    def __init__(self, max_size: int = _PROGRAM_CACHE_SIZE,
                 by_identity: bool = False):
        self.max_size = int(max_size)
        self.by_identity = bool(by_identity)
        self._lock = threading.Lock()
        self._entries: "OrderedDict" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.unkeyed = 0  # identity-mode lookups that could not be keyed

    def kernel_key(self, kernel: Callable):
        if not self.by_identity:
            return id(kernel)
        key = kernel_identity(kernel)
        if key is None:
            with self._lock:
                self.unkeyed += 1
        return key

    def get_or_build(self, kernel: Callable, kernel_key, key: tuple,
                     builder: Callable):
        cache_key = (kernel_key, key)
        with self._lock:
            hit = self._entries.get(cache_key)
            if hit is not None:
                self._entries.move_to_end(cache_key)
                self.hits += 1
                return hit[1]
        # compile outside the lock (it can take seconds); a racing builder
        # of the same program is harmless — last one in wins the slot.  The
        # entry holds a strong ref to the kernel, which keeps an id() key
        # component valid for the entry's lifetime.
        prog = builder()
        with self._lock:
            self.misses += 1
            self._entries[cache_key] = (kernel, prog)
            self._entries.move_to_end(cache_key)
            while len(self._entries) > self.max_size:
                self._entries.popitem(last=False)
        return prog

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "programs": len(self._entries),
                "max_size": self.max_size,
                "hits": self.hits,
                "misses": self.misses,
                "unkeyed": self.unkeyed,
            }


#: the optional process-wide shared program cache.  Installed by the
#: resident server (``runtime/server.py``) so every executor any request
#: task builds shares one identity-keyed cache; batch entry points never
#: install one, keeping the PR-7 instance scope (and its lifetime safety)
#: for one-shot runs.
_SHARED_PROGRAM_CACHE: Optional[ProgramCache] = None


def install_shared_program_cache(
    cache: Optional[ProgramCache],
) -> Optional[ProgramCache]:
    """Install (or, with None, uninstall) the process-wide shared program
    cache; returns the previous one."""
    global _SHARED_PROGRAM_CACHE
    prev = _SHARED_PROGRAM_CACHE
    _SHARED_PROGRAM_CACHE = cache
    return prev


def shared_program_cache() -> Optional[ProgramCache]:
    return _SHARED_PROGRAM_CACHE


def get_mesh(
    target: str = "local",
    n_devices: Optional[int] = None,
    axis_name: str = "blocks",
) -> Mesh:
    devs = get_devices(target, n_devices)
    return Mesh(np.array(devs), (axis_name,))


#: errnos that mean "storage is full", not "storage is broken"
_DISK_FULL_ERRNOS = (errno.ENOSPC, errno.EDQUOT)


def classify_resource_error(exc: BaseException) -> Optional[str]:
    """``"oom"`` / ``"enospc"`` when ``exc`` (or anything on its
    cause/context chain) is a resource-exhaustion failure, else None.

    - ``MemoryError`` — host allocator failure (numpy, stacking, IO
      buffers),
    - XLA's ``RESOURCE_EXHAUSTED`` / out-of-memory runtime errors, matched
      by type name + message so no jaxlib-version-specific import is
      needed,
    - ``OSError`` with ``ENOSPC``/``EDQUOT`` — shared filesystem full.

    Retrying these at the same size re-runs the exact allocation that just
    failed; callers route them to the degrade policy instead.
    """
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        if isinstance(exc, MemoryError):
            return "oom"
        if isinstance(exc, OSError) and exc.errno in _DISK_FULL_ERRNOS:
            return "enospc"
        msg = str(exc)
        if type(exc).__name__ == "XlaRuntimeError" and (
            "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
        ):
            return "oom"
        # older jaxlibs surface allocator failures as a plain RuntimeError
        # carrying the status name; arbitrary exception types that merely
        # MENTION the string are not classified
        if isinstance(exc, RuntimeError) and "RESOURCE_EXHAUSTED" in msg:
            return "oom"
        exc = exc.__cause__ or exc.__context__
    return None


class SubBlock(Block):
    """A degrade-split fragment of a parent block (same ``block_id``).
    Load/store callbacks that pad to a static batch shape can detect these
    (:func:`is_sub_block`) and size buffers per-block instead — sub-blocks
    never enter a stacked batch, so the static-shape contract does not
    apply to them."""


def is_sub_block(block: Block) -> bool:
    return isinstance(block, SubBlock)


def split_block(
    block: Block,
    halo: Optional[Sequence[int]] = None,
    min_shape: Optional[Sequence[int]] = None,
) -> Optional[List[Block]]:
    """Split ``block``'s inner region into up to 2^d halo-correct
    sub-blocks (each axis halved where both halves stay >= ``min_shape``).

    Sub-blocks keep the parent's ``block_id`` (markers, fault targeting and
    failure attribution stay at the parent grain) and get outer boxes of
    ``sub_inner ± halo`` clamped to the parent's outer box — which is the
    volume clamp, since the parent's outer box is itself the volume-clamped
    ``inner ± halo``.  ``halo`` defaults to the parent's own per-axis halo
    (max over the two sides, so border clipping does not shrink it); pass
    it explicitly for single-block axes, where both sides are clipped and
    nothing can be derived.  Returns None when no axis can split.
    """
    nd = len(block.begin)
    if halo is None:
        halo = tuple(
            max(b - ob, oe - e)
            for b, ob, e, oe in zip(
                block.begin, block.outer_begin, block.end, block.outer_end
            )
        )
    halo = tuple(int(h) for h in halo)
    min_shape = tuple(
        max(1, int(m)) for m in (min_shape or (1,) * nd)
    )
    axes_intervals = []
    any_cut = False
    for ax in range(nd):
        lo, hi = block.begin[ax], block.end[ax]
        half = (hi - lo) // 2
        if half >= min_shape[ax] and (hi - lo) - half >= min_shape[ax]:
            axes_intervals.append([(lo, lo + half), (lo + half, hi)])
            any_cut = True
        else:
            axes_intervals.append([(lo, hi)])
    if not any_cut:
        return None
    subs = []
    for combo in itertools.product(*axes_intervals):
        begin = tuple(c[0] for c in combo)
        end = tuple(c[1] for c in combo)
        outer_begin = tuple(
            max(ob, b - h) for ob, b, h in zip(block.outer_begin, begin, halo)
        )
        outer_end = tuple(
            min(oe, e + h) for oe, e, h in zip(block.outer_end, end, halo)
        )
        subs.append(SubBlock(block.block_id, begin, end, outer_begin, outer_end))
    return subs


def morton_order(blocks: Sequence[Block]) -> List[Block]:
    """Reorder ``blocks`` along a Morton/Z-order curve of the block grid.

    Locality-aware sweep scheduling (docs/PERFORMANCE.md "Chunk-aware
    I/O"): raster order walks a whole grid row before returning to a
    neighborhood, so by the time the next row reads the shared boundary
    chunks they have been evicted from the decompressed-chunk cache.
    Z-order keeps consecutive blocks (and therefore consecutive executor
    batches) spatially adjacent — every aligned 2x2x2 octant of the grid is
    visited contiguously — so halo reads land while their neighbors'
    chunks are still resident.

    Grid positions are recovered from the blocks' own ``begin`` coordinates
    (per-axis rank over the distinct values), so ROI-restricted and
    parity-filtered block lists order correctly without a Blocking handle.
    Deterministic: a pure permutation keyed on grid position.
    """
    blocks = list(blocks)
    if len(blocks) < 3:
        return blocks
    nd = len(blocks[0].begin)
    rank = []
    for ax in range(nd):
        values = sorted({int(b.begin[ax]) for b in blocks})
        rank.append({v: i for i, v in enumerate(values)})
    nbits = max(
        1, max(len(r) - 1 for r in rank).bit_length()
    )

    def code(b: Block) -> int:
        c = 0
        for bit in range(nbits):
            for ax in range(nd):
                c |= ((rank[ax][int(b.begin[ax])] >> bit) & 1) << (
                    bit * nd + ax
                )
        return c

    return sorted(blocks, key=code)


def check_finite_outputs(block: Block, out) -> Optional[str]:
    """Built-in output validator: any non-finite value in a float leaf is a
    corrupt kernel output (the classic silent NaN-producing-kernel failure)."""
    for leaf in jax.tree_util.tree_leaves(out):
        a = np.asarray(leaf)
        if a.dtype.kind == "f" and not np.isfinite(a).all():
            return "non-finite values (NaN/inf) in kernel output"
    return None


def region_verifier(
    dataset, bb_of: Optional[Callable[[Block], Any]] = None
) -> Optional[Callable[[Block], None]]:
    """Build a ``store_verify_fn`` for :meth:`BlockwiseExecutor.map_blocks`
    from a dataset with digest sidecars: read the block's stored region back
    and raise :class:`~cluster_tools_tpu.io.containers.ChunkCorruptionError`
    if its bytes no longer match the recorded checksum.  Returns None for
    datasets without checksum support (HDF5), so call sites wire it
    unconditionally.

    Wiring a verifier also declares the dataset a **block-product store**
    for the self-healing plane (docs/SERVING.md "Self-healing"): its
    reads fall under the verifying reader's missing-sidecar policy
    (``io/verified.py``), and the returned callable carries the dataset +
    geometry (``.dataset`` / ``.bb_of``) so the executor can register
    per-block lineage (``runtime/repair.py``) after each verified store —
    call sites wire ONE knob and get detection, policy, scrub, and repair
    together."""
    verify = getattr(dataset, "verify_region", None)
    if verify is None:
        return None
    from ..io import verified as verified_mod

    verified_mod.mark_product(dataset)
    if bb_of is None:
        bb_of = lambda block: block.bb  # noqa: E731 - trivial default

    def store_verify(block: Block) -> None:
        verify(bb_of(block))

    store_verify.dataset = dataset
    store_verify.bb_of = bb_of
    return store_verify


def validate_labels(block: Block, out) -> Optional[str]:
    """Validator for label-producing kernels: negative (signed) or
    saturated (unsigned) label values are the integer shadows of a corrupt
    kernel — a NaN cast to int yields exactly these.  Float leaves are
    covered by ``map_blocks``' built-in ``check_finite`` pass, not here."""
    for leaf in jax.tree_util.tree_leaves(out):
        a = np.asarray(leaf)
        if a.size == 0:
            continue
        if a.dtype.kind == "i" and int(a.min()) < 0:
            return "negative label values (corrupt kernel output)"
        if a.dtype.kind == "u" and bool((a == np.iinfo(a.dtype).max).any()):
            return "saturated label values (corrupt kernel output)"
    return None


class BlockwiseExecutor:
    """Run a per-block kernel over a list of blocks, batched across devices.

    ``kernel`` is a pure function over one block's arrays; it is vmapped,
    jitted, and the batch axis is sharded over the mesh.  ``load_fn(block)``
    returns the kernel's input arrays for one block (already padded to a
    uniform shape); ``store_fn(block, outputs)`` persists one block's outputs
    (each already a numpy array).
    """

    def __init__(
        self,
        target: str = "local",
        n_devices: Optional[int] = None,
        device_batch: int = 1,
        io_threads: int = 8,
        max_retries: int = 2,
        backoff_base: float = 0.05,
        backoff_max: float = 5.0,
    ):
        self.target = target
        self.devices = get_devices(target, n_devices)
        self.n_devices = len(self.devices)
        self.device_batch = int(device_batch)
        self.batch_size = self.n_devices * self.device_batch
        self.mesh = Mesh(np.array(self.devices), ("blocks",))
        self.io_threads = io_threads
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        # compiled-program reuse across sweeps OF THIS EXECUTOR: repeated
        # map_blocks calls with the same kernel callable (bench re-sweeps,
        # resident service workers holding a warm executor) skip the
        # per-shape compile — the same 10x cold-vs-warm tax ROADMAP item 4
        # records for the solver.  Instance-scoped on purpose: the cached
        # wrapper strongly references its kernel closure (which can pin a
        # task's captured state, e.g. a model checkpoint), so the cache
        # must die with the executor, not outlive the task process-wide.
        # Under a resident server, a SHARED identity-keyed cache
        # (install_shared_program_cache, docs/SERVING.md) takes precedence
        # for kernels whose identity is resolvable.
        self._program_cache = ProgramCache(_PROGRAM_CACHE_SIZE)

    def _program_lookup(self, kernel: Callable) -> Callable:
        """Resolve the cache route for ``kernel`` ONCE (the identity
        freeze walks the whole closure — per sweep, not per batch) and
        return a ``(key, builder) -> program`` lookup bound to it."""
        shared = shared_program_cache()
        if shared is not None:
            kernel_key = shared.kernel_key(kernel)
            if kernel_key is not None:
                return functools.partial(
                    shared.get_or_build, kernel, kernel_key
                )
        return functools.partial(
            self._program_cache.get_or_build, kernel, id(kernel)
        )

    def _cached_program(self, kernel: Callable, key: tuple,
                        builder: Callable):
        return self._program_lookup(kernel)(key, builder)

    # -- retry/backoff machinery ------------------------------------------
    def _backoff(self, attempt: int) -> float:
        return fu.backoff_delay(attempt, self.backoff_base, self.backoff_max)

    def _io_with_retries(
        self, site: str, block: Block, fn: Callable,
        on_error: Optional[Callable[[Exception], None]] = None,
    ):
        """Run ``fn`` with injection + retries.  Returns
        ``(value, attempts, traceback_or_None, resource_class_or_None)``;
        the caller quarantines on a non-None traceback.  A resource-
        classified failure (OOM / ENOSPC) short-circuits the retry loop —
        re-running the same allocation at the same size only burns the
        budget; the degrade policy owns it.  ``on_error`` observes each
        caught exception (failure-class attribution, e.g. counting
        ChunkCorruptionErrors)."""
        injector = faults_mod.get_injector()
        voxels = int(np.prod(block.outer_shape))
        last_tb = None
        for k in range(self.max_retries + 1):
            try:
                injector.maybe_fail(site, block.block_id, voxels=voxels)
                injector.maybe_hang(site, block.block_id)
                return fn(), k + 1, None, None
            except Exception as e:
                if on_error is not None:
                    try:
                        on_error(e)
                    except Exception:
                        pass
                last_tb = fu.cap_traceback(traceback.format_exc())
                resource = classify_resource_error(e)
                if resource is not None:
                    return None, k + 1, last_tb, resource
                if k < self.max_retries:
                    time.sleep(self._backoff(k))
        return None, self.max_retries + 1, last_tb, None

    def map_blocks(
        self,
        kernel: Callable,
        blocks: Sequence[Block],
        load_fn: Callable[[Block], Tuple],
        store_fn: Optional[Callable[[Block, Any], None]] = None,
        on_block_done: Optional[Callable[[Block], None]] = None,
        prefetch: int = 2,
        done_block_ids: Optional[Iterable[int]] = None,
        validate_fn: Optional[Callable[[Block, Any], Optional[str]]] = None,
        check_finite: bool = True,
        failures_path: Optional[str] = None,
        task_name: str = "map_blocks",
        block_deadline_s: Optional[float] = None,
        watchdog_period_s: Optional[float] = None,
        speculate: bool = True,
        store_verify_fn: Optional[Callable[[Block], None]] = None,
        splittable: bool = False,
        split_halo: Optional[Sequence[int]] = None,
        min_block_shape: Optional[Sequence[int]] = None,
        degrade_wait_s: float = 5.0,
        inflight_byte_budget: Optional[int] = None,
        mem_headroom_fraction: float = 0.05,
        disk_headroom_fraction: float = 0.02,
        schedule: str = "morton",
        sweep_mode: str = "auto",
        sharded_batch: Optional[int] = None,
        ragged: str = "auto",
        page_shape: Optional[Sequence[int]] = None,
        device_pool: str = "auto",
        device_pool_bytes: Optional[int] = None,
    ) -> Dict[str, int]:
        """Execute ``kernel`` over ``blocks``; see class docstring.

        ``done_block_ids`` — block ids to skip (success-marker resume grain).
        ``validate_fn(block, outputs) -> Optional[str]`` — extra output
        validation; a non-None message quarantines the block for re-compute.
        ``check_finite`` — built-in NaN/inf validation of float outputs.
        ``failures_path`` — where to record the ``failures.json`` manifest.
        ``block_deadline_s`` — per-block wall-clock budget: a watchdog
        thread declares blocks whose load/compute/store exceeds it *hung*
        (recorded + quarantined within one ``watchdog_period_s``, default
        ``deadline/4``) and, when ``speculate``, launches a duplicate
        re-execution through the same compiled kernel — first result wins,
        and if both copies complete they must agree bit-for-bit (a
        disagreement is recorded as a ``determinism`` failure and the block
        is recomputed).  ``store_verify_fn(block)`` — post-store integrity
        check (see :func:`region_verifier`); a ChunkCorruptionError it
        raises makes the store retry (re-write repairs the corrupt chunk),
        then quarantine (recompute repairs it).

        Graceful degradation (module docstring): a resource-classified
        failure (OOM / ENOSPC) skips same-size retries and enters the
        degrade ladder — wait for memory/disk headroom (up to
        ``degrade_wait_s``), re-execute once at full size, then, when
        ``splittable``, recursively re-execute as halo-correct sub-blocks
        down to ``min_block_shape`` through the same kernel (jitted per
        sub-shape), stored via the task's own ``store_fn``.  ``splittable``
        is a *contract*: ``load_fn``/``store_fn``/``kernel`` must be pure
        functions of the block geometry at any shape (no fixed-shape
        padding), and the kernel must be shape-local so sub-block outputs
        tile to the unsplit result bit-identically (voxelwise/copy-like
        kernels; NOT label-flood kernels whose encoding depends on the
        outer shape).  ``split_halo`` defaults to the per-block derived
        halo.  ``inflight_byte_budget`` caps the bytes of loaded-but-
        unstored batches (None = 25% of MemAvailable at start, 0 =
        disabled); ``mem_headroom_fraction`` / ``disk_headroom_fraction``
        backpressure the store drain when host memory / the manifest
        filesystem run low.

        ``schedule`` — sweep order: ``"morton"`` (default) reorders blocks
        (and therefore the batches) along a Z-order curve of the block grid
        so consecutive batches share boundary chunks while they are still
        resident in the decompressed-chunk cache (:func:`morton_order`);
        ``"given"`` keeps the caller's order.  Per-block outputs are
        independent, so the order never changes results — only IO locality.

        ``sweep_mode`` — ``"per_block"`` (the historical path: one
        ``jit(vmap)`` dispatch per ``n_devices * device_batch`` blocks —
        per *block* on a single-device host), ``"sharded"`` (one
        ``shard_map`` program per Morton batch of ``sharded_batch`` blocks
        over the mesh, holding the dispatch lock once per batch — see the
        module docstring), or ``"auto"`` (default: sharded when the mesh
        has >= 2 devices or the sweep fills at least one sharded batch).
        ``sharded_batch`` — blocks per sharded program (None = ``max(2 *
        n_devices * device_batch, 8)``, rounded up to a device multiple).
        Sharded output is bit-identical to the per-block path; a sharded
        batch that fails with a resource/device error (site ``dispatch``)
        or hangs falls its blocks back to per-block execution, attributed
        ``resolution="degraded:unsharded"``.

        ``ragged`` — mixed-shape handling on the sharded path
        (docs/PERFORMANCE.md "Ragged sweeps"): ``"auto"`` (default) packs
        batches the dense program cannot take — mixed-shape lanes from
        un-padded loads, partial final batches, and (for ``splittable``
        call sites) degrade-split sub-blocks — through the paged block
        pool (:mod:`~cluster_tools_tpu.parallel.block_pool`) and runs
        them as ONE descriptor-driven program per batch, synthetic
        padding lanes discarded on d2h; ``"on"`` additionally forces
        uniform full batches through the ragged program; ``"off"``
        restores the historical behavior (mixed-shape batches and split
        sub-blocks execute per-block, attributed
        ``degraded:unsharded``).  Partial uniform batches pack with the
        lane shape as the page, so every real lane sees exactly the
        bytes per-block dispatch would have seen (any kernel, bit-
        identical); mixed-SHAPE lanes run at the batch's page-aligned
        shape, which is only guaranteed bit-identical on each lane's
        stored region for shape-local kernels — the same contract as
        ``splittable``, and why call sites with shape-dependent label
        encodings keep padding in ``load_fn`` (their batches stay
        uniform and dense).  ``page_shape`` overrides the pool's page
        tile (default: chunk-scale, see
        :func:`~cluster_tools_tpu.parallel.block_pool.
        default_page_shape`); set it to the dataset chunk shape for
        chunk-aligned pooling (uniform-lane batches keep the exact
        lane-shape page regardless — the any-kernel guarantee above is
        unconditional).  Ragged dispatches are attributed in the
        dispatch counters (``ragged_batches`` / ``lanes_padded`` /
        ``pages_in_use`` in io_metrics.json) and on the trace timeline
        (``executor.dispatch`` spans with ``grain="ragged"``).

        ``device_pool`` — HBM-resident staging of ragged batches
        (docs/PERFORMANCE.md "Device-resident data plane"): ``"auto"``
        (default) stages ragged batches through the persistent
        content-addressed device page pool
        (:mod:`~cluster_tools_tpu.parallel.device_pool`) when the ragged
        path is active and ``CTT_DEVICE_POOL`` is not 0 — pages whose
        bytes are already resident cost zero h2d traffic; ``"off"``
        restores the per-batch ``device_put`` staging.  A staging
        RESOURCE_EXHAUSTED rides the degrade ladder (evict the resident
        arenas, retry, then per-batch host staging for that batch,
        attributed ``resolution="degraded:host_staged"`` once per sweep)
        — bit-identical either way.  ``device_pool_bytes`` caps the
        resident allocation (None: ``CTT_DEVICE_POOL_BYTES``, default
        256 MiB).  Traffic is attributed in the device-plane counters
        (``h2d_bytes`` / ``d2h_bytes`` / ``device_pool_hits`` /
        ``bytes_not_staged`` in io_metrics.json) and host-staged uploads
        on the timeline (``executor.h2d`` spans — absent on the
        resident-pool happy path).

        Raises RuntimeError naming every block that stays failed after the
        end-of-run quarantine pass, and
        :class:`~cluster_tools_tpu.runtime.supervision.DrainInterrupt`
        when a drain (SIGTERM/SIGUSR1) was requested — in-flight work is
        finished, markers and manifests flushed, remaining blocks left for
        the resumed run.
        """
        if done_block_ids:
            done = {int(b) for b in done_block_ids}
            blocks = [b for b in blocks if int(b.block_id) not in done]
        if schedule == "morton":
            blocks = morton_order(blocks)
        elif schedule not in ("given", None):
            raise ValueError(
                f"unknown schedule {schedule!r} (expected 'morton' or 'given')"
            )
        sharded_width = resolve_sharded_batch(
            self.n_devices, self.batch_size, sharded_batch
        )
        use_sharded = use_sharded_sweep(
            sweep_mode, self.n_devices, len(blocks), sharded_width
        )
        if ragged not in ("auto", "on", "off"):
            raise ValueError(
                f"unknown ragged mode {ragged!r} "
                "(expected 'auto', 'on' or 'off')"
            )
        # the paged block pool is a sharded-path feature: per_block mode
        # dispatches per block anyway, so raggedness costs it nothing
        use_ragged = use_sharded and ragged != "off"
        ragged_pool = (
            block_pool_mod.PagedBlockPool() if use_ragged else None
        )
        if device_pool not in ("auto", "on", "off"):
            raise ValueError(
                f"unknown device_pool mode {device_pool!r} "
                "(expected 'auto', 'on' or 'off')"
            )
        # the resident HBM pool rides the ragged path (its page tables are
        # the re-addressing mechanism); the process kill switch wins over
        # any per-call mode
        dev_pool = (
            device_pool_mod.get_device_pool(device_pool_bytes)
            if use_ragged and device_pool != "off"
            and device_pool_mod.device_pool_enabled()
            else None
        )
        if page_shape is not None:
            page_shape = tuple(int(p) for p in page_shape)
        if not blocks:
            return {"n_blocks": 0, "n_quarantined": 0, "n_failed": 0}
        # preemption-aware draining: SIGTERM/SIGUSR1 flip a latch instead
        # of killing us; the sweep checks it at batch boundaries
        install_drain_handler()
        injector = faults_mod.get_injector()
        deadline = float(block_deadline_s or 0.0)
        block_by_id = {int(b.block_id): b for b in blocks}
        bs0 = self.batch_size
        bs = sharded_width if use_sharded else bs0
        n_batches = math.ceil(len(blocks) / bs)
        sharding = NamedSharding(self.mesh, P("blocks"))
        # page pools of ragged batches are broadcast to every device (each
        # lane gathers from the whole pool); tables/valid shard over blocks
        replicated = NamedSharding(self.mesh, P())
        dev_key = tuple(d.id for d in self.devices)

        def _vmap_program():
            return jax.jit(
                jax.vmap(kernel), in_shardings=sharding, out_shardings=sharding
            )

        # the cache route (shared identity-keyed under a resident server,
        # else this executor's instance cache) is resolved once per sweep:
        # the identity freeze walks the kernel's whole closure
        cached_program = self._program_lookup(kernel)

        if use_sharded:
            batched_kernel = cached_program(
                ("sharded", bs, dev_key),
                lambda: batched_shard_map(kernel, self.mesh, bs),
            )
        else:
            # width is carried by the input shapes, not the wrapper: one
            # cached jit(vmap) serves every batch width of this kernel
            batched_kernel = cached_program(("vmap", dev_key), _vmap_program)
        # the sweep span doubles as the sweep_s clock (docs/OBSERVABILITY.md):
        # trace spans are the one timing source in runtime/ (CT008), and a
        # begin/end pair still measures with the tracer off so the
        # io_metrics counters keep working
        sweep_span = trace_mod.begin(
            "executor.sweep", task=task_name, n_blocks=len(blocks),
            sharded=bool(use_sharded),
        )
        dispatch_stats = {
            "batches": 0, "blocks": 0, "wait_s": 0.0,
            "ragged_batches": 0, "lanes_padded": 0, "pages_in_use": 0,
        }
        stats_lock = threading.Lock()

        def _note_dispatch(n_blocks_dispatched: int, rb=None) -> None:
            with stats_lock:
                dispatch_stats["batches"] += 1
                dispatch_stats["blocks"] += int(n_blocks_dispatched)
                if rb is not None:
                    dispatch_stats["ragged_batches"] += 1
                    dispatch_stats["lanes_padded"] += rb.lanes_padded
                    dispatch_stats["pages_in_use"] += rb.pages_in_use

        # per-block failure bookkeeping (threads: IO pool + dispatch loop)
        failures: Dict[int, Dict[str, Any]] = {}
        fail_lock = threading.Lock()
        quarantined_ids: set = set()
        # blocks whose SHARDED batch failed (device OOM at the dispatch, or
        # hung in the compute stage): they fall back to per-block execution
        # and are attributed "degraded:unsharded" when that resolves them
        sharded_failed_ids: set = set()

        def note_failure(block, site, attempts, error, quarantine,
                         resource=None):
            if quarantine or error is not None:
                # attribution-plane crossing: the failure lands on the
                # timeline next to the latency it caused
                trace_mod.instant(
                    f"fault:{site}", block=int(block.block_id),
                    task=task_name, quarantined=bool(quarantine),
                    resource=resource,
                )
            with fail_lock:
                rec = failures.setdefault(
                    int(block.block_id),
                    {
                        "block_id": int(block.block_id),
                        "sites": {},
                        "error": None,
                        "quarantined": False,
                        "resolved": True,
                    },
                )
                rec["sites"][site] = rec["sites"].get(site, 0) + int(attempts)
                if error is not None:
                    rec["error"] = error
                if resource is not None:
                    # the resource CLASS (oom/enospc), steering the degrade
                    # ladder and counted per class for the post-mortem
                    rec["resource"] = resource
                    rec["sites"][resource] = rec["sites"].get(resource, 0) + 1
                if quarantine:
                    rec["quarantined"] = True
                    rec["resolved"] = False
                    quarantined_ids.add(int(block.block_id))

        def mark_resolved(block, resolution=None):
            if resolution is not None:
                trace_mod.instant(
                    resolution, block=int(block.block_id), task=task_name
                )
            with fail_lock:
                rec = failures.get(int(block.block_id))
                if rec is not None:
                    rec["resolved"] = True
                    if resolution is not None:
                        rec["resolution"] = resolution

        def unsharded_tag(block, resolved_by_fallback):
            """``"degraded:unsharded"`` when the PER-BLOCK path actually
            resolved a block whose sharded batch failed — a late-finishing
            sharded primary that wins its own commit is NOT a fallback, so
            a transient hang must not misreport one."""
            if not use_sharded or not resolved_by_fallback:
                return None
            with fail_lock:
                fell = int(block.block_id) in sharded_failed_ids
            return "degraded:unsharded" if fell else None

        def validate(block, out) -> Optional[str]:
            if check_finite:
                err = check_finite_outputs(block, out)
                if err:
                    return err
            if validate_fn is not None:
                return validate_fn(block, out)
            return None

        # -- hang defense: watchdog + speculative duplicates ----------------
        # in-flight (block, stage) work registers with a watchdog; overdue
        # work is recorded as hung + quarantined, and a duplicate of the
        # block runs through the same compiled kernel — FirstWins arbitrates.
        # ALL dispatches of the compiled kernel share one lock: the program
        # is sharded across every device, and two concurrent executions of a
        # multi-device program deadlock XLA's collective rendezvous (each
        # waits for all participants) — the devices are a serial resource,
        # so serializing dispatch costs nothing and removes the hazard.
        dispatch_lock = threading.Lock()
        speculated: set = set()
        commits = FirstWins()

        # the per-block program: in per_block mode it IS the main program
        # (quarantine re-attempts replicate the block to the batch width
        # through the same compiled kernel); in sharded mode it is the
        # degrade/speculation fallback — one block's share of the batch,
        # a strictly smaller allocation than the sharded program, compiled
        # lazily because a clean sharded sweep never needs it.  Per-lane
        # vmap numerics are width-independent, so recovery through it stays
        # bit-identical to the sharded result (tests/test_sharded.py).
        fallback_state: Dict[str, Any] = {}

        def _per_block_kernel():
            if not use_sharded:
                return batched_kernel, bs
            kern = fallback_state.get("kernel")
            if kern is None:
                kern = cached_program(("vmap", dev_key), _vmap_program)
                fallback_state["kernel"] = kern
            return kern, bs0

        def _exec_single(val):
            """One block through the per-block program; returns its output
            tree as numpy arrays."""
            kern, width = _per_block_kernel()
            stacked = tuple(np.stack([x] * width) for x in val)
            device_pool_mod.record_h2d(sum(int(a.nbytes) for a in stacked))
            stacked = tuple(jax.device_put(a, sharding) for a in stacked)
            # span starts AFTER the lock is held — same grain semantics as
            # the sharded path, so executor.dispatch never bills another
            # dispatch's lock wait regardless of which path emitted it
            with dispatch_lock:
                with trace_mod.span("executor.dispatch", n_blocks=1,
                                    task=task_name, grain="per_block"):
                    out = kern(*stacked)
            _note_dispatch(1)
            out_np = jax.tree_util.tree_map(lambda a: np.asarray(a)[0], out)
            device_pool_mod.record_d2h(sum(
                int(a.nbytes) for a in jax.tree_util.tree_leaves(out_np)
            ))
            return out_np

        # one degraded:host_staged record per sweep (the counter still
        # ticks per fallen-back batch): pool exhaustion is a sweep-level
        # condition, not a per-block fault
        device_fallback = {"recorded": False}

        def _stage_ragged_inputs(rb, block_id):
            """Device inputs + compiled program for one ragged batch.
            With the resident pool on, pages already in HBM are re-
            addressed instead of re-uploaded (the device-resident data
            plane); pool exhaustion — after its internal evict+retry rung
            — falls THIS batch back to per-batch host staging, attributed
            ``degraded:host_staged``.  Bit-identical either way: the same
            page bytes reach the same descriptor-driven program."""
            if dev_pool is not None:
                try:
                    sb = dev_pool.stage(
                        rb, dev_key, replicated, block_id=block_id
                    )
                except device_pool_mod.DevicePoolExhausted as e:
                    device_pool_mod.bump("host_staged_fallbacks")
                    trace_mod.instant(
                        "degraded:host_staged", task=task_name,
                        block=int(block_id),
                    )
                    if not device_fallback["recorded"] and failures_path:
                        device_fallback["recorded"] = True
                        try:
                            fu.record_failures(
                                failures_path,
                                f"{task_name}.device_pool",
                                [{
                                    "block_id": None,
                                    "sites": {"h2d": 1},
                                    "error": fu.cap_traceback(str(e)),
                                    "quarantined": False,
                                    "resolved": True,
                                    "resolution": "degraded:host_staged",
                                }],
                            )
                        except Exception:
                            pass
                else:
                    rep, shd = sb.flat_inputs()
                    # the pools are already resident; only the (tiny)
                    # remapped tables + valid extents cross the host bus
                    device_pool_mod.record_h2d(
                        sum(int(a.nbytes) for a in shd)
                    )
                    dev_inputs = tuple(rep) + tuple(
                        jax.device_put(a, sharding) for a in shd
                    )
                    prog = cached_program(
                        ("ragged", dev_key) + sb.key(),
                        lambda sb=sb: ragged_shard_map(
                            kernel, self.mesh, sb.width, sb.specs
                        ),
                    )
                    return dev_inputs, prog
            # host staging: the per-batch device_put of pools + tables
            # (the pre-pool path, and the ladder's fallback rung) — a
            # REAL h2d transfer, visible on the timeline
            rep, shd = rb.flat_inputs()
            with trace_mod.span(
                "executor.h2d", task=task_name, nbytes=int(rb.nbytes),
                grain="ragged",
            ):
                dev_inputs = tuple(
                    jax.device_put(a, replicated) for a in rep
                ) + tuple(
                    jax.device_put(a, sharding) for a in shd
                )
            device_pool_mod.record_h2d(rb.nbytes)
            prog = cached_program(
                ("ragged", dev_key) + rb.key(),
                lambda rb=rb: ragged_shard_map(
                    kernel, self.mesh, rb.width, rb.specs
                ),
            )
            return dev_inputs, prog

        spec_pool: Optional[ThreadPoolExecutor] = None
        spec_futures: List[Future] = []
        watchdog: Optional[Watchdog] = None
        _tokens = itertools.count()

        @contextlib.contextmanager
        def _watched(block, stage, origin="primary"):
            if watchdog is None:
                yield
                return
            token = next(_tokens)
            watchdog.register(
                token, block_id=int(block.block_id), stage=stage, origin=origin
            )
            try:
                yield
            finally:
                watchdog.clear(token)

        class _PreIssueFailed(Exception):
            pass

        def load_block(block, pre=None, pre_tb=None, pre_resource=None,
                       origin="primary"):
            """Load one block with retries; returns arrays or None
            (quarantined).  ``pre`` is an already-issued load_fn result
            consumed by the first attempt (batch reads are issued together
            so the storage layer runs the chunk IO concurrently).  Resource-
            classified failures (OOM/ENOSPC) skip the same-size retries and
            quarantine straight into the degrade ladder."""
            last_tb, attempts = None, 0
            voxels = int(np.prod(block.outer_shape))
            with contextlib.ExitStack() as stack:
                stack.enter_context(_watched(block, "load", origin))
                stack.enter_context(
                    faults_mod.block_context(int(block.block_id))
                )
                # per-block load span covers the whole retry ladder: the
                # latency an operator chases is time-to-loaded, not
                # per-attempt time.  task passed explicitly: hot-path spans
                # must not pay the thread-local context lookup per block
                stack.enter_context(trace_mod.span(
                    "executor.load", block=int(block.block_id),
                    origin=origin, task=task_name,
                ))
                for k in range(self.max_retries + 1):
                    attempts = k + 1
                    try:
                        injector.maybe_fail(
                            "load", block.block_id, voxels=voxels
                        )
                        injector.maybe_hang("load", block.block_id)
                        if k == 0 and pre_tb is not None:
                            last_tb = pre_tb
                            raise _PreIssueFailed()
                        per = pre if (k == 0 and pre is not None) else load_fn(block)
                        val = tuple(
                            x.result() if hasattr(x, "result") else x for x in per
                        )
                    except _PreIssueFailed:
                        if pre_resource is not None:
                            note_failure(
                                block, "load", attempts, last_tb,
                                quarantine=True, resource=pre_resource,
                            )
                            return None
                        if k < self.max_retries:
                            time.sleep(self._backoff(k))
                    except Exception as e:
                        last_tb = fu.cap_traceback(traceback.format_exc())
                        resource = classify_resource_error(e)
                        if resource is not None:
                            note_failure(
                                block, "load", attempts, last_tb,
                                quarantine=True, resource=resource,
                            )
                            return None
                        if k < self.max_retries:
                            time.sleep(self._backoff(k))
                    else:
                        if attempts > 1:
                            note_failure(block, "load", attempts - 1, None, False)
                        return val
            note_failure(block, "load", attempts, last_tb, quarantine=True)
            return None

        # service mode (docs/SERVING.md): store_fn may publish block-grain
        # artifact handoffs, and those identities are namespaced by the
        # thread-local request context — capture it on the sweep's thread
        # and re-enter it on every pool-submitted worker (loads, stores,
        # speculative re-runs), or a resident server's concurrent requests
        # over the same paths could resolve each other's intermediates
        _req_ctx = admission_mod.current_request()

        def _scoped(fn):
            def run(*a, **kw):
                with admission_mod.request_scope(_req_ctx):
                    return fn(*a, **kw)
            return run

        def load_batch(batch_idx: int):
            """Load one batch; returns ``(blocks, kind, payload)`` where
            ``kind`` routes the dispatch: ``"dense"`` (stacked arrays for
            the uniform-shape program), ``"ragged"`` (a packed
            :class:`~cluster_tools_tpu.parallel.block_pool.RaggedBatch`),
            ``"mixed"`` (per-lane values the pool could not pack — the
            per-block program owns them), or ``"empty"``."""
            batch = blocks[batch_idx * bs : (batch_idx + 1) * bs]
            # load_fn may return futures (e.g. io.prefetch.async_loader's
            # tensorstore read futures): issue EVERY read of the batch first,
            # then resolve — the storage layer runs the chunk IO concurrently
            issued = []
            for b in batch:
                try:
                    with faults_mod.block_context(int(b.block_id)):
                        issued.append((load_fn(b), None, None))
                except Exception as e:
                    issued.append(
                        (None, fu.cap_traceback(traceback.format_exc()),
                         classify_resource_error(e))
                    )
            ok_blocks, per_block = [], []
            for b, (pre, pre_tb, pre_res) in zip(batch, issued):
                val = load_block(b, pre=pre, pre_tb=pre_tb, pre_resource=pre_res)
                if val is None:
                    continue
                # kernel-dispatch fault hook (resource model: this block's
                # share of the batch does not fit): an injected compute
                # OOM routes the block to the degrade ladder pre-dispatch,
                # keeping the rest of the batch intact
                try:
                    injector.maybe_fail(
                        "compute", b.block_id,
                        voxels=int(np.prod(b.outer_shape)),
                    )
                except Exception as e:
                    note_failure(
                        b, "compute", 1,
                        fu.cap_traceback(traceback.format_exc()),
                        quarantine=True,
                        resource=classify_resource_error(e),
                    )
                    continue
                ok_blocks.append(b)
                per_block.append(val)
            if not ok_blocks:
                return [], "empty", None
            vals = [tuple(np.asarray(x) for x in val) for val in per_block]
            n_args = len(vals[0])
            uniform = all(
                len({v[i].shape for v in vals}) == 1 for i in range(n_args)
            )
            full = len(vals) == bs
            if use_ragged and (not uniform or not full or ragged == "on"):
                # mixed-shape lanes, a partial batch (ragged tail or
                # quarantine holes), or a forced ragged sweep: pack through
                # the paged block pool — one descriptor-driven program
                # instead of the per-block fallback; padding lanes are
                # synthesized by the pool and discarded on d2h
                try:
                    return ok_blocks, "ragged", ragged_pool.pack(
                        vals, bs, page_shape=page_shape
                    )
                except ValueError:
                    if uniform:
                        # uniform lanes the pool refuses (exotic dtypes):
                        # the dense repeat-pad path below handles them
                        # exactly as before the pool existed
                        pass
                    else:
                        # mixed-shape lanes that cannot pack: per-block
                        # execution owns them
                        return ok_blocks, "mixed", vals
            if not uniform:
                # ragged="off" (or per_block mode): mixed shapes cannot
                # stack — the per-block program owns them
                return ok_blocks, "mixed", vals
            # pad the partial batch (tail, or quarantine-induced holes) by
            # repeating the last block so the compiled shape stays static;
            # padded outputs are dropped
            n_pad = bs - len(vals)
            if n_pad:
                vals = vals + [vals[-1]] * n_pad
            arrays = tuple(
                np.stack([pb[i] for pb in vals]) for i in range(n_args)
            )
            return ok_blocks, "dense", arrays

        finished_ids: set = set()

        def _register_lineage(blk):
            """Self-healing lineage (docs/SERVING.md, runtime/repair.py):
            after a verified store, record how to recompute THIS block —
            re-load the producing inputs, re-run the per-block program,
            re-store through the ordinary sidecar-recording write path —
            keyed by the product region the verifier just checked.  Best
            effort: lineage must never fail a completed block."""
            ds = getattr(store_verify_fn, "dataset", None) \
                if store_verify_fn is not None else None
            if ds is None or store_fn is None:
                return
            bb_of = getattr(store_verify_fn, "bb_of", None) \
                or (lambda b: b.bb)

            def recompute(b=blk):
                with faults_mod.block_context(int(b.block_id)):
                    # async loaders return futures; resolve them exactly
                    # like load_block does before the kernel sees them
                    val = tuple(
                        x.result() if hasattr(x, "result") else x
                        for x in load_fn(b)
                    )
                    out = _exec_single(val)
                    err = validate(b, out)
                    if err is not None:
                        raise RuntimeError(
                            f"lineage recompute of block {b.block_id} "
                            f"failed validation: {err}"
                        )
                    store_fn(b, out)

            try:
                from . import repair as repair_mod

                repair_mod.register_producer(
                    ds, bb_of(blk), recompute, task=task_name,
                    block_id=int(blk.block_id),
                    failures_path=failures_path,
                )
            except Exception:
                pass

        def finish_block(blk):
            """Completion side effects (success marker + block_done kill
            point) at most ONCE per block — with speculation, two copies of
            a block can both reach a happy end (uncontended-looking winner
            plus a later-agreeing duplicate) and must not double-fire."""
            with fail_lock:
                if int(blk.block_id) in finished_ids:
                    return
                finished_ids.add(int(blk.block_id))
            _register_lineage(blk)
            if on_block_done is not None:
                on_block_done(blk)
            injector.kill_point("block_done")

        def handle_block_output(blk, block_out, origin="primary"):
            """Corrupt-injection, validation, duplicate arbitration, store
            (with retries + integrity verify), marker.  Never raises —
            failures (including programming errors in the validate/marker
            hooks) quarantine the block, keeping every error attributed to
            its block id."""
            bid = int(blk.block_id)
            try:
                block_out = injector.corrupt("kernel", blk.block_id, block_out)
                err = validate(blk, block_out)
                if err is not None:
                    note_failure(blk, "validate", 1, err, quarantine=True)
                    return
                if store_fn is not None:
                    corrupt_seen = [0]
                    dup_state = {"verdict": None, "digest": None,
                                 "contended": False}

                    def _classify(exc):
                        if isinstance(exc, ChunkCorruptionError):
                            corrupt_seen[0] += 1

                    def _store_and_verify():
                        # first-wins gate, decided at the LAST moment before
                        # the write: this copy may have been declared hung
                        # and overtaken by a speculative duplicate while it
                        # was stuck on the way here.  With the watchdog
                        # armed EVERY copy registers its digest — a
                        # duplicate spawned after an uncontended-looking
                        # primary passed this point must still find the
                        # claim.  Decided once; store retries reuse it.
                        if dup_state["verdict"] is None:
                            if watchdog is not None:
                                with fail_lock:
                                    dup_state["contended"] = bid in speculated
                                dup_state["digest"] = array_digest(
                                    jax.tree_util.tree_leaves(block_out)
                                )
                                dup_state["verdict"] = commits.commit(
                                    bid, dup_state["digest"]
                                )
                            else:
                                dup_state["verdict"] = FirstWins.WIN
                        if dup_state["verdict"] != FirstWins.WIN:
                            return  # arbitrated below, nothing to store
                        store_fn(blk, block_out)
                        if store_verify_fn is not None:
                            store_verify_fn(blk)

                    with contextlib.ExitStack() as stack:
                        stack.enter_context(_watched(blk, "store", origin))
                        stack.enter_context(faults_mod.block_context(bid))
                        stack.enter_context(trace_mod.span(
                            "executor.store", block=bid, origin=origin,
                            task=task_name,
                        ))
                        _, attempts, tb, store_resource = self._io_with_retries(
                            "store", blk, _store_and_verify, on_error=_classify
                        )
                    if dup_state["verdict"] == FirstWins.AGREE:
                        # this copy confirms the stored winner bit-for-bit:
                        # resolved without a second store (also the
                        # arbitration path after a mismatch — a third copy
                        # agreeing with the winner validates it).  A
                        # contended winner deferred the completion side
                        # effects to this settling point; finish_block
                        # de-duplicates against a winner that already ran
                        # them (it looked uncontended when it decided).
                        # The stored winner is the OTHER copy: when this
                        # agreeing copy is the primary, a speculative
                        # per-block duplicate won — that is the sharded ->
                        # per-block fallback, attributed as such.
                        mark_resolved(
                            blk, unsharded_tag(blk, origin == "primary")
                        )
                        with fail_lock:
                            rec = failures.get(bid)
                            if rec is not None:
                                rec["duplicate"] = "agreed"
                        finish_block(blk)
                        return
                    if dup_state["verdict"] == FirstWins.MISMATCH:
                        note_failure(
                            blk, "determinism", 1,
                            "speculative duplicate disagreed with the first "
                            "result (nondeterministic kernel or corrupted "
                            "data); block left unresolved for recompute",
                            quarantine=True,
                        )
                        return
                    if corrupt_seen[0]:
                        # attribute the fault class: the store "failures"
                        # were chunk corruption caught by the digest verify
                        note_failure(
                            blk, "corrupt", corrupt_seen[0], None,
                            quarantine=False,
                        )
                    if tb is not None:
                        if dup_state["digest"] is not None:
                            # the WIN claim's store never landed: release it
                            # so the quarantine recompute is not misread as
                            # a duplicate of a result that does not exist
                            commits.withdraw(bid, dup_state["digest"])
                        note_failure(blk, "store", attempts, tb,
                                     quarantine=True, resource=store_resource)
                        return
                    if attempts > 1:
                        note_failure(
                            blk, "store", attempts - 1, None, quarantine=False
                        )
                    # this copy stored the result: only a SPECULATIVE win
                    # came through the per-block fallback program
                    mark_resolved(
                        blk, unsharded_tag(blk, origin == "speculative")
                    )
                    if not dup_state["contended"]:
                        # a contended winner defers the success marker to the
                        # duplicate's AGREE above: a mismatch must not leave
                        # a marker a resumed run would trust (if the other
                        # copy dies instead, the unmarked block is merely
                        # recomputed on resume — safe)
                        finish_block(blk)
                else:
                    mark_resolved(blk)
                    finish_block(blk)
            except Exception:
                # site "hook", not "store": the store path itself retries
                # and records above — only validate_fn/on_block_done/corrupt
                # programming errors land here
                note_failure(
                    blk,
                    "hook",
                    1,
                    fu.cap_traceback(traceback.format_exc()),
                    quarantine=True,
                )
                return

        def speculative_rerun(blk):
            """Duplicate execution of a hung block: fresh load, the
            per-block program (the same compiled kernel in per_block mode;
            the per-block fallback twin in sharded mode), and a first-wins
            commit against the (possibly still stuck) original."""
            try:
                with trace_mod.span(
                    "executor.speculate", block=int(blk.block_id),
                    task=task_name,
                ):
                    val = load_block(blk, origin="speculative")
                    if val is None:
                        return
                    out0 = _exec_single(val)
                    handle_block_output(blk, out0, origin="speculative")
            except Exception:
                note_failure(
                    blk, "speculate", 1,
                    fu.cap_traceback(traceback.format_exc()),
                    quarantine=False,
                )

        if deadline > 0:
            spec_pool = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="speculate"
            )

            def _on_hung(token, info, elapsed):
                bid = int(info["block_id"])
                blk = block_by_id[bid]
                note_failure(
                    blk, "hung", 1,
                    f"block exceeded block_deadline_s={deadline:g}s in "
                    f"stage {info['stage']} ({elapsed:.2f}s elapsed)",
                    quarantine=True,
                )
                if not speculate or info.get("origin") != "primary":
                    return
                with fail_lock:
                    if use_sharded and info.get("stage") == "compute":
                        # a hung device stalls the whole sharded program:
                        # this block's recovery is a sharded -> per-block
                        # fallback, attributed degraded:unsharded
                        sharded_failed_ids.add(bid)
                    if bid in speculated:
                        return
                    speculated.add(bid)
                spec_futures.append(spec_pool.submit(_scoped(speculative_rerun), blk))

            watchdog = Watchdog(
                deadline,
                watchdog_period_s or max(0.02, deadline / 4.0),
                _on_hung,
            ).start()

        # -- byte-budget admission control + headroom backpressure ----------
        # in-flight = loaded-but-not-yet-stored batch bytes; the budget caps
        # it (default: a quarter of MemAvailable at sweep start), and low
        # host-memory / manifest-filesystem headroom drains the pending
        # store window before the next batch is admitted.
        if inflight_byte_budget is None:
            avail = host_mem_available_bytes()
            budget = int(avail * 0.25) if avail else 0
            # tenant-tagged budgets (docs/SERVING.md): under a service-mode
            # request context, the auto budget is additionally capped at
            # the running request's share of its tenant's byte quota — one
            # tenant's sweep cannot claim the whole host envelope away
            # from its neighbors.  An explicit inflight_byte_budget (the
            # operator's word) is never overridden.
            tenant_cap = admission_mod.ambient_byte_cap()
            if tenant_cap:
                budget = min(budget, int(tenant_cap)) if budget \
                    else int(tenant_cap)
            if budget and chunk_cache_mod.cache_enabled():
                # the decompressed-chunk cache is co-resident host memory:
                # subtract its byte budget from the same headroom probe so
                # cache + in-flight batches together stay inside the
                # 25%-of-MemAvailable envelope (floored at a quarter of the
                # probe so tiny hosts keep making progress)
                budget = max(
                    budget - chunk_cache_mod.get_chunk_cache().max_bytes,
                    budget // 4,
                )
            live_handoff = handoff_mod.live_bytes()
            if budget and live_handoff:
                # in-memory handoff targets (docs/PERFORMANCE.md
                # "Task-graph fusion") are co-resident too — same envelope,
                # same floor
                budget = max(budget - live_handoff, budget // 4)
        else:
            budget = int(inflight_byte_budget)
        inflight = {"bytes": 0}
        admission_lock = threading.Lock()
        backpressure = {"waits": 0}
        headroom_path = (
            os.path.dirname(os.path.abspath(failures_path))
            if failures_path else None
        )
        drained = False

        def _release_inflight(nbytes):
            with admission_lock:
                inflight["bytes"] -= nbytes

        def _admit(nbytes, write_futures):
            """Admission gate for one loaded batch: drain pending stores
            until the byte budget fits (the current batch is always
            admitted — progress beats the cap) and while memory/disk
            headroom is below threshold.  Low host memory additionally
            flushes completed in-memory handoff targets to their storage
            spill paths (docs/PERFORMANCE.md "Task-graph fusion") — the
            degrade ladder prefers releasing recoverable resident bytes
            over stalling the sweep."""
            waited = False
            mem = host_mem_available_fraction()
            if mem is not None and mem < mem_headroom_fraction:
                # BEFORE the pending-store drain (which may be empty —
                # in-memory sinks complete their stores immediately):
                # completed handoffs are safe to flush (storage becomes
                # the source of truth; consumers fall back transparently)
                # and free real headroom
                handoff_mod.spill_for_headroom()
            while write_futures:
                with admission_lock:
                    over = budget and inflight["bytes"] + nbytes > budget
                mem = host_mem_available_fraction()
                low_mem = mem is not None and mem < mem_headroom_fraction
                disk = (
                    disk_free_fraction(headroom_path) if headroom_path else None
                )
                low_disk = disk is not None and disk < disk_headroom_fraction
                if not (over or low_mem or low_disk):
                    break
                waited = True
                write_futures.pop(0).result()
            if waited:
                backpressure["waits"] += 1
            with admission_lock:
                inflight["bytes"] += nbytes

        try:
            with ThreadPoolExecutor(max_workers=self.io_threads) as pool:
                pending_loads: List[Future] = [
                    pool.submit(_scoped(load_batch), i)
                    for i in range(min(prefetch, n_batches))
                ]
                write_futures: List[Future] = []
                for i in range(n_batches):
                    if drain_requested():
                        # stop claiming batches; in-flight loads/stores are
                        # finished below, markers+manifests flushed, and the
                        # sweep exits through DrainInterrupt for a requeue
                        drained = True
                        break
                    # the wait span doubles as the wait_s clock: the IO the
                    # double-buffering failed to hide, and (traced) the gap
                    # Perfetto shows between consecutive dispatch spans.
                    # Sub-100us waits are measured (the counter needs them)
                    # but not recorded — a fully-overlapped sweep must not
                    # pay one timeline event per batch for a non-stall
                    wait_span = trace_mod.begin(
                        "executor.batch_wait", task=task_name, batch=i
                    )
                    batch, kind, payload = pending_loads.pop(0).result()
                    waited = wait_span.end(discard=True)
                    if waited > 1e-4:
                        wait_span.end()
                    with stats_lock:
                        dispatch_stats["wait_s"] += waited
                    if i + prefetch < n_batches:
                        pending_loads.append(
                            pool.submit(_scoped(load_batch), i + prefetch)
                        )
                    # prompt drain: surface finished stores (and any programming
                    # error in the store path, with its batch's block ids) now,
                    # not at the end of the run
                    while write_futures and write_futures[0].done():
                        write_futures.pop(0).result()
                    if not batch:
                        continue  # every block of this batch was quarantined
                    if kind == "mixed":
                        # lanes neither the dense nor the ragged program can
                        # take (pool off or unpackable): the per-block
                        # program owns them — on the sharded path that is a
                        # degrade, attributed like every other fallback
                        mixed_bytes = sum(
                            int(x.nbytes) for val in payload for x in val
                        )
                        _admit(mixed_bytes, write_futures)

                        def run_mixed(batch=batch, vals=payload,
                                      nbytes=mixed_bytes):
                            try:
                                for blk, val in zip(batch, vals):
                                    bid = int(blk.block_id)
                                    if use_sharded:
                                        note_failure(
                                            blk, "pack", 1,
                                            "mixed-shape lanes with the "
                                            "ragged pool unavailable; "
                                            "executed per-block",
                                            quarantine=True,
                                        )
                                        with fail_lock:
                                            sharded_failed_ids.add(bid)
                                    try:
                                        out0 = _exec_single(val)
                                    except Exception:
                                        note_failure(
                                            blk, "compute", 1,
                                            fu.cap_traceback(
                                                traceback.format_exc()
                                            ),
                                            quarantine=True,
                                        )
                                        continue
                                    handle_block_output(blk, out0)
                                    if use_sharded:
                                        with fail_lock:
                                            rec = failures.get(bid)
                                            done = bool(
                                                rec and rec["resolved"]
                                            )
                                        if done:
                                            mark_resolved(
                                                blk, "degraded:unsharded"
                                            )
                            finally:
                                _release_inflight(nbytes)

                        write_futures.append(
                            pool.submit(_scoped(run_mixed))
                        )
                        while len(write_futures) > 2:
                            write_futures.pop(0).result()
                        continue
                    rb = payload if kind == "ragged" else None
                    if rb is not None:
                        batch_bytes = rb.nbytes
                    else:
                        arrays = payload
                        batch_bytes = sum(int(a.nbytes) for a in arrays)
                    _admit(batch_bytes, write_futures)
                    if rb is not None:
                        dev_inputs, prog = _stage_ragged_inputs(
                            rb, batch[0].block_id
                        )
                    else:
                        with trace_mod.span(
                            "executor.h2d", task=task_name,
                            nbytes=int(batch_bytes), grain="dense",
                        ):
                            dev_inputs = tuple(
                                jax.device_put(a, sharding) for a in arrays
                            )
                        device_pool_mod.record_h2d(batch_bytes)
                        prog = batched_kernel
                    try:
                        if use_sharded:
                            # batch-grain fault surface: a device OOM or a
                            # wedged device takes down the whole sharded
                            # program, not one block — the 'dispatch' site
                            # models it (registered as compute so the
                            # watchdog's hung-batch detection covers it)
                            with contextlib.ExitStack() as stack:
                                for blk in batch:
                                    stack.enter_context(
                                        _watched(blk, "compute")
                                    )
                                batch_voxels = sum(
                                    int(np.prod(b.outer_shape))
                                    for b in batch
                                )
                                injector.maybe_fail(
                                    "dispatch", batch[0].block_id,
                                    voxels=batch_voxels,
                                )
                                injector.maybe_hang(
                                    "dispatch", batch[0].block_id
                                )
                        # take the dispatch lock BEFORE starting the blocks'
                        # compute clocks: waiting behind a (possibly cold-
                        # compiling) speculative dispatch is not this batch's
                        # wall time, and must not cascade into false hangs
                        with dispatch_lock, contextlib.ExitStack() as stack:
                            span_args = dict(
                                task=task_name, n_blocks=len(batch),
                                grain=(
                                    "ragged" if rb is not None
                                    else "sharded" if use_sharded
                                    else "batch"
                                ),
                            )
                            if rb is not None:
                                # ragged-lane attribution on the timeline:
                                # how much of the dispatch was padding
                                span_args["lanes_padded"] = rb.lanes_padded
                            stack.enter_context(trace_mod.span(
                                "executor.dispatch", **span_args
                            ))
                            for blk in batch:
                                stack.enter_context(_watched(blk, "compute"))
                            out = prog(*dev_inputs)
                        _note_dispatch(len(batch), rb)
                    except Exception as e:
                        # a compute failure poisons the whole batch; quarantine
                        # all of it — the reduced-batch pass isolates the
                        # culprit, and a resource-classified failure (device
                        # OOM) steers every member into the degrade ladder.
                        # In sharded mode the batch falls back to per-block
                        # execution (site 'dispatch', degraded:unsharded).
                        tb = fu.cap_traceback(traceback.format_exc())
                        resource = classify_resource_error(e)
                        site = "dispatch" if use_sharded else "compute"
                        for blk in batch:
                            note_failure(blk, site, 1, tb,
                                         quarantine=True, resource=resource)
                        if use_sharded:
                            with fail_lock:
                                sharded_failed_ids.update(
                                    int(b.block_id) for b in batch
                                )
                        _release_inflight(batch_bytes)
                        continue

                    def store_batch(batch=batch, out=out, nbytes=batch_bytes,
                                    rb=rb):
                        # the device->host copy happens HERE, on the IO pool, so
                        # the dispatch loop is free to enqueue the next batch
                        # while this one's outputs stream back.  This copy is
                        # also where a kernel wedged at RUNTIME blocks (the
                        # jitted call above returns at dispatch — async), so
                        # it is the stage the compute watchdog must cover.
                        try:
                            with contextlib.ExitStack() as stack:
                                # this copy is where a wedged kernel blocks
                                # (dispatch is async): the span is the
                                # timeline's true per-batch compute extent
                                stack.enter_context(trace_mod.span(
                                    "executor.d2h", task=task_name,
                                    n_blocks=len(batch),
                                ))
                                for blk in batch:
                                    stack.enter_context(_watched(blk, "compute"))
                                out_np = jax.tree_util.tree_map(np.asarray, out)
                            device_pool_mod.record_d2h(sum(
                                int(a.nbytes)
                                for a in jax.tree_util.tree_leaves(out_np)
                            ))
                            if rb is not None:
                                # the execution is complete once the copy
                                # above lands: the pool's host buffers are
                                # safe to recycle for later batches
                                rb.release()
                            for j, blk in enumerate(batch):
                                block_out = jax.tree_util.tree_map(
                                    lambda a: (
                                        a[j] if rb is None
                                        # ragged lane: crop the page-aligned
                                        # output back to the lane's valid
                                        # extent (padding lanes never reach
                                        # here — only real blocks iterate)
                                        else rb.crop(j, a[j])
                                    ),
                                    out_np,
                                )
                                handle_block_output(blk, block_out)
                        finally:
                            _release_inflight(nbytes)

                    write_futures.append(pool.submit(_scoped(store_batch)))
                    # backpressure: each pending store closure pins its batch's
                    # DEVICE output buffers until its d2h copy runs, so the bound
                    # must be a small constant (not thread-count) or HBM fills
                    # with undrained outputs
                    while len(write_futures) > 2:
                        write_futures.pop(0).result()
                for f in write_futures:
                    f.result()

                # settle speculative duplicates before judging what is still
                # unresolved (the list can grow while we drain: a primary still
                # stuck past its deadline fires the watchdog mid-drain)
                i_spec = 0
                while i_spec < len(spec_futures):
                    spec_futures[i_spec].result()
                    i_spec += 1
                if watchdog is not None:
                    watchdog.stop()
                if spec_pool is not None:
                    spec_pool.shutdown(wait=True)

                # -- degrade ladder: headroom wait + split machinery ------------

                def _wait_for_headroom(resource):
                    """Bounded backpressure before a degrade re-attempt:
                    transient exhaustion (a sibling job's spike, a filling
                    scratch disk being cleaned) often clears within
                    seconds; a healthy (or unmeasurable) host returns
                    immediately."""
                    deadline_t = time.monotonic() + max(0.0, degrade_wait_s)
                    while time.monotonic() < deadline_t:
                        if resource == "enospc":
                            frac = (
                                disk_free_fraction(headroom_path)
                                if headroom_path else None
                            )
                            if frac is None or frac > disk_headroom_fraction:
                                return
                        else:
                            frac = host_mem_available_fraction()
                            if frac is None or frac > mem_headroom_fraction:
                                return
                        time.sleep(min(0.2, max(0.01, degrade_wait_s / 20.0)))

                # the SAME kernel function, unbatched + jitted: jit caches
                # one compiled twin per distinct sub-block shape, each a
                # smaller allocation than the batch program — the point
                sub_jit = cached_program(("sub",), lambda: jax.jit(kernel))

                def _sub_exec(val):
                    device_pool_mod.record_h2d(
                        sum(int(np.asarray(x).nbytes) for x in val)
                    )
                    with dispatch_lock:
                        out = sub_jit(*val)
                    _note_dispatch(1)
                    out_np = jax.tree_util.tree_map(np.asarray, out)
                    device_pool_mod.record_d2h(sum(
                        int(a.nbytes)
                        for a in jax.tree_util.tree_leaves(out_np)
                    ))
                    return out_np

                split_stats = {"splits": 0, "max_depth": 0, "sub_blocks": 0}

                def _load_sub(sub):
                    """Load one sub-block with retries.  Returns
                    ``("ok", val)``, ``("recurse", None)`` (a resource
                    failure: the caller splits one level deeper), or
                    ``("fail", None)`` (attributed, permanently failed)."""
                    voxels = int(np.prod(sub.outer_shape))
                    val, last_tb = None, None
                    for k in range(self.max_retries + 1):
                        try:
                            injector.maybe_fail(
                                "load", sub.block_id, voxels=voxels
                            )
                            injector.maybe_hang("load", sub.block_id)
                            per = load_fn(sub)
                            val = tuple(
                                x.result() if hasattr(x, "result") else x
                                for x in per
                            )
                            break
                        except Exception as e:
                            last_tb = fu.cap_traceback(traceback.format_exc())
                            if classify_resource_error(e) is not None:
                                return "recurse", None
                            if k < self.max_retries:
                                time.sleep(self._backoff(k))
                    if val is None:
                        note_failure(sub, "load", 1, last_tb, quarantine=True)
                        return "fail", None
                    return "ok", val

                def _store_sub(sub, out, depth, tracker):
                    """Validate + store (+ integrity verify) one sub-block's
                    output with retries; a resource failure waits for
                    headroom and recurses one level deeper."""
                    voxels = int(np.prod(sub.outer_shape))
                    err = validate(sub, out)
                    if err is not None:
                        note_failure(sub, "validate", 1, err, quarantine=True)
                        return False
                    if store_fn is None:
                        return True

                    def _store():
                        store_fn(sub, out)
                        if store_verify_fn is not None:
                            store_verify_fn(sub)

                    last_tb = None
                    for k in range(self.max_retries + 1):
                        try:
                            injector.maybe_fail(
                                "store", sub.block_id, voxels=voxels
                            )
                            injector.maybe_hang("store", sub.block_id)
                            _store()
                            return True
                        except Exception as e:
                            last_tb = fu.cap_traceback(traceback.format_exc())
                            resource = classify_resource_error(e)
                            if resource is not None:
                                _wait_for_headroom(resource)
                                return _split_and_run(sub, depth + 1,
                                                      tracker)
                            if k < self.max_retries:
                                time.sleep(self._backoff(k))
                    note_failure(sub, "store", 1, last_tb, quarantine=True)
                    return False

                def _run_sub(sub, depth, tracker, val=None):
                    """One sub-block through load -> kernel -> validate ->
                    store(+verify); a resource failure at any stage recurses
                    one level deeper.  Failures are attributed to the parent
                    block id (sub-blocks carry it).  ``val`` skips the load
                    when the caller already holds the arrays (the ragged
                    sub path falling back after a failed dispatch must not
                    re-read storage — or burn load-fault attempts)."""
                    voxels = int(np.prod(sub.outer_shape))
                    with faults_mod.block_context(int(sub.block_id)):
                        if val is None:
                            status, val = _load_sub(sub)
                            if status == "recurse":
                                return _split_and_run(sub, depth + 1,
                                                      tracker)
                            if status == "fail":
                                return False
                        # compute at the sub shape
                        try:
                            injector.maybe_fail(
                                "compute", sub.block_id, voxels=voxels
                            )
                            out = _sub_exec(val)
                        except Exception as e:
                            tb = fu.cap_traceback(traceback.format_exc())
                            if classify_resource_error(e) is not None:
                                return _split_and_run(sub, depth + 1,
                                                      tracker)
                            note_failure(sub, "compute", 1, tb, quarantine=True)
                            return False
                        return _store_sub(sub, out, depth, tracker)

                def _run_subs_ragged(subs, depth, tracker):
                    """All sub-blocks of one split parent through the paged
                    block pool: mixed sub-shapes pack into ragged batches
                    and execute as ONE program per batch instead of one
                    ``jit`` dispatch per sub-block (docs/PERFORMANCE.md
                    "Ragged sweeps") — the split ladder's semantics are
                    unchanged: per-lane resource failures recurse deeper,
                    and a failed ragged dispatch falls the chunk back to
                    the per-sub path (the same program the unsplit
                    quarantine pass uses)."""
                    ok = True
                    ready = []
                    for sub in subs:
                        with faults_mod.block_context(int(sub.block_id)):
                            status, val = _load_sub(sub)
                            if status == "recurse":
                                ok &= _split_and_run(sub, depth + 1, tracker)
                                continue
                            if status == "fail":
                                ok = False
                                continue
                            try:
                                injector.maybe_fail(
                                    "compute", sub.block_id,
                                    voxels=int(np.prod(sub.outer_shape)),
                                )
                            except Exception as e:
                                tb = fu.cap_traceback(traceback.format_exc())
                                if classify_resource_error(e) is not None:
                                    ok &= _split_and_run(sub, depth + 1,
                                                         tracker)
                                    continue
                                note_failure(sub, "compute", 1, tb,
                                             quarantine=True)
                                ok = False
                                continue
                            ready.append((sub, tuple(
                                np.asarray(x) for x in val
                            )))
                    for start in range(0, len(ready), bs):
                        chunk = ready[start:start + bs]
                        width = min(
                            bs,
                            -(-len(chunk) // self.n_devices) * self.n_devices,
                        )
                        try:
                            rb = ragged_pool.pack(
                                [val for _, val in chunk], width,
                                page_shape=page_shape,
                            )
                            # split sub-blocks stage through the resident
                            # pool too (half-size pages of a split parent
                            # are fresh content, but the fill page and
                            # repeated retries hit)
                            dev_inputs, prog = _stage_ragged_inputs(
                                rb, chunk[0][0].block_id
                            )
                            injector.maybe_fail(
                                "dispatch", chunk[0][0].block_id,
                                voxels=sum(
                                    int(np.prod(s.outer_shape))
                                    for s, _ in chunk
                                ),
                            )
                            injector.maybe_hang(
                                "dispatch", chunk[0][0].block_id
                            )
                            with dispatch_lock:
                                with trace_mod.span(
                                    "executor.dispatch", task=task_name,
                                    n_blocks=len(chunk), grain="ragged",
                                    lanes_padded=rb.lanes_padded,
                                ):
                                    out = prog(*dev_inputs)
                            out_np = jax.tree_util.tree_map(np.asarray, out)
                            device_pool_mod.record_d2h(sum(
                                int(a.nbytes)
                                for a in jax.tree_util.tree_leaves(out_np)
                            ))
                            rb.release()
                            _note_dispatch(len(chunk), rb)
                        except Exception:
                            # the ragged sub dispatch failed (device OOM, a
                            # wedged device, an unpackable chunk): the
                            # unchanged per-sub fallback owns these lanes,
                            # reusing the values already in hand
                            for sub, val in chunk:
                                ok &= _run_sub(sub, depth, tracker, val=val)
                            continue
                        for j, (sub, _) in enumerate(chunk):
                            block_out = jax.tree_util.tree_map(
                                lambda a, j=j: rb.crop(j, np.asarray(a)[j]),
                                out_np,
                            )
                            with faults_mod.block_context(int(sub.block_id)):
                                ok &= _store_sub(sub, block_out, depth,
                                                 tracker)
                    return ok

                def _split_and_run(blk, depth=1, tracker=None):
                    """Recursive 2^d halo-correct split of ``blk``; True when
                    every sub-block landed (the parent's stored region is then
                    exactly the reassembled sub-results).  ``tracker`` records
                    the depth THIS parent block actually reached (the sweep-
                    wide maximum lives in ``split_stats``)."""
                    subs = split_block(blk, halo=split_halo,
                                       min_shape=min_block_shape)
                    if subs is None:
                        note_failure(
                            blk, "split", 1,
                            "resource exhaustion persisted at "
                            f"min_block_shape={tuple(min_block_shape or ())} "
                            "— cannot split further",
                            quarantine=True,
                        )
                        return False
                    split_stats["splits"] += 1
                    split_stats["max_depth"] = max(split_stats["max_depth"], depth)
                    split_stats["sub_blocks"] += len(subs)
                    if tracker is not None:
                        tracker["depth"] = max(tracker.get("depth", 0), depth)
                    if use_ragged:
                        # split sub-blocks stay on the sharded path: one
                        # ragged program per parent instead of 2^d per-shape
                        # jit dispatches (docs/PERFORMANCE.md "Ragged
                        # sweeps")
                        return _run_subs_ragged(subs, depth, tracker)
                    return all(_run_sub(sub, depth, tracker) for sub in subs)

                # -- quarantine pass: reduced-batch re-attempts -----------------
                # re-run each still-unresolved quarantined block alone,
                # replicated to the batch width through the SAME compiled kernel
                # — bit-identical results, and a batch-poisoning block is
                # isolated to itself.  Blocks a speculative duplicate (or a
                # late-finishing hung primary) already resolved are skipped.
                # Resource-exhausted blocks enter here as the degrade ladder:
                # wait for headroom, full-size re-attempt, then (splittable
                # call sites) recursive sub-block re-execution.
                with fail_lock:
                    unresolved_q = {
                        b for b in quarantined_ids if not failures[b]["resolved"]
                    }
                degraded_ids: set = set()
                for blk in [b for b in blocks if int(b.block_id) in unresolved_q]:
                    if drained or drain_requested():
                        drained = True
                        break
                    bid = int(blk.block_id)
                    with fail_lock:
                        resource = failures[bid].get("resource")
                    if resource is not None:
                        degraded_ids.add(bid)
                        _wait_for_headroom(resource)
                    val = load_block(blk)
                    if val is not None:
                        ok = False
                        try:
                            injector.maybe_fail(
                                "compute", blk.block_id,
                                voxels=int(np.prod(blk.outer_shape)),
                            )
                            out0 = _exec_single(val)
                            ok = True
                        except Exception as e:
                            tb = fu.cap_traceback(traceback.format_exc())
                            note_failure(
                                blk, "compute", 1, tb, quarantine=True,
                                resource=classify_resource_error(e),
                            )
                        if ok:
                            handle_block_output(blk, out0)
                    # ladder outcome: a resolved resource block recovered via
                    # the headroom wait; a still-unresolved one splits (when
                    # the call site declared the kernel split-safe).  A block
                    # whose SHARDED batch failed resolved through the
                    # per-block fallback — attribute that, not backpressure.
                    with fail_lock:
                        rec = failures[bid]
                        resolved_now = rec["resolved"]
                        resource = rec.get("resource")
                        fell_back = bid in sharded_failed_ids
                    if resolved_now:
                        if fell_back:
                            mark_resolved(blk, "degraded:unsharded")
                        elif resource is not None:
                            mark_resolved(blk, "degraded:backpressure")
                        continue
                    if resource is not None and splittable:
                        tracker = {"depth": 0}
                        if _split_and_run(blk, tracker=tracker):
                            mark_resolved(blk, "degraded:split")
                            with fail_lock:
                                rec = failures[bid]
                                rec["split_depth"] = tracker["depth"]
                            finish_block(blk)

        finally:
            # the watchdog and speculation pool must not outlive the
            # sweep, even when a load/store future propagates an error
            if watchdog is not None:
                watchdog.stop()
            if spec_pool is not None:
                spec_pool.shutdown(wait=True)
            _record_dispatch_metrics(
                dispatch_stats["batches"],
                dispatch_stats["blocks"],
                dispatch_stats["wait_s"],
                sweep_span.end(
                    n_batches=dispatch_stats["batches"],
                    n_quarantined=len(quarantined_ids),
                ),
                ragged_batches=dispatch_stats["ragged_batches"],
                lanes_padded=dispatch_stats["lanes_padded"],
                pages_in_use=dispatch_stats["pages_in_use"],
            )

        unresolved = sorted(
            b for b, rec in failures.items() if not rec["resolved"]
        )
        if failures_path and failures:
            fu.record_failures(
                failures_path,
                task_name,
                [failures[b] for b in sorted(failures)],
            )
        if drained:
            # graceful drain: everything dispatched was finished and
            # markered; what is left belongs to the requeued/resumed run.
            reason = drain_reason() or "drain requested"
            remaining = sorted(
                int(b.block_id) for b in blocks
                if int(b.block_id) not in finished_ids
            )
            if failures_path:
                # keyed under "<task>.drain": records merge by
                # (task, block_id), and (task, None) is already used by the
                # supervisor's job_loss record (and "<task>.preempt" by its
                # requeue record) — a drain must not overwrite either
                fu.record_failures(
                    failures_path,
                    f"{task_name}.drain",
                    [{
                        "block_id": None,
                        "sites": {"preempt": 1},
                        "error": reason,
                        "quarantined": False,
                        "resolved": True,
                        "resolution": "requeued:preempt",
                        "remaining_blocks": len(remaining),
                    }],
                )
            raise DrainInterrupt(reason, remaining)
        if unresolved:
            details = "\n".join(
                f"-- block {b} (sites {failures[b]['sites']}) --\n"
                f"{failures[b]['error']}"
                for b in unresolved[:5]
            )
            raise RuntimeError(
                f"{task_name}: {len(unresolved)}/{len(blocks)} blocks failed "
                f"permanently after retries + quarantine re-attempts "
                f"(ids: {unresolved})"
                + (f"; see {failures_path}" if failures_path else "")
                + f"; first errors:\n{details}"
            )
        summary = {
            "n_blocks": len(blocks),
            "n_quarantined": len(quarantined_ids),
            "n_failed": 0,
            "sweep_mode": "sharded" if use_sharded else "per_block",
            "n_dispatches": dispatch_stats["batches"],
        }
        if sharded_failed_ids:
            summary["n_unsharded"] = len(sharded_failed_ids)
        if dispatch_stats["ragged_batches"]:
            summary["n_ragged_batches"] = dispatch_stats["ragged_batches"]
            summary["n_lanes_padded"] = dispatch_stats["lanes_padded"]
            summary["pages_in_use"] = dispatch_stats["pages_in_use"]
        if dev_pool is not None:
            summary["device_pool"] = "on"
            summary["device_pool_resident_bytes"] = dev_pool.resident_bytes()
        if deadline > 0:
            summary["n_hung"] = sum(
                1 for rec in failures.values() if "hung" in rec["sites"]
            )
            summary["n_speculated"] = len(speculated)
        if degraded_ids or split_stats["splits"] or backpressure["waits"]:
            summary["n_degraded"] = len(degraded_ids)
            summary["n_split"] = split_stats["splits"]
            summary["n_sub_blocks"] = split_stats["sub_blocks"]
            summary["split_depth"] = split_stats["max_depth"]
            summary["n_backpressure_waits"] = backpressure["waits"]
        return summary
