"""Blockwise executor: maps the block grid onto the device mesh.

This is the TPU-native replacement for the reference's job machinery
(``prepare_jobs`` / ``submit_jobs`` / ``wait_for_jobs`` in SURVEY.md §2a):
instead of serializing per-job JSON configs and submitting slurm array jobs,
the driver batches blocks into device-sized groups, streams them host->HBM
with a double-buffered prefetch pipeline, and runs one jitted, vmapped kernel
per batch with the batch axis sharded across the mesh.

The pipeline per batch:

    host threads: read blocks (+halo) from chunked storage, pad to the
                  static outer shape                               [IO bound]
    device:       jit(vmap(kernel)) over the batch, batch axis sharded
                  across devices                                   [compute]
    host threads: crop inner blocks, write to chunked storage      [IO bound]

Reads for batch i+1 overlap compute for batch i (prefetch depth 2); writes
are fire-and-forget futures drained promptly in a bounded window.

Fault tolerance (docs/ROBUSTNESS.md): per-block loads and stores retry with
exponential backoff + jitter; blocks that exhaust their retries (or whose
outputs fail validation — NaN/inf, or a task-supplied ``validate_fn``) are
*quarantined*: the batch and the run continue, and quarantined blocks are
re-attempted at the end on a reduced-batch path (the block replicated to the
batch width through the *same* compiled kernel, so a recovered block is
bit-identical to an undisturbed run).  Every block that ever failed is
recorded in a structured ``failures.json`` manifest (block id, per-site
attempt counts, capped traceback, resolution); blocks that stay failed after
the quarantine pass raise with their ids attributed.  Block-level success
markers give the same resume grain as the reference's ``log_block_success``
— ``done_block_ids`` filters them built-in.

Silent failures (docs/ROBUSTNESS.md "Silent failures"): ``block_deadline_s``
arms a watchdog that detects *hung* blocks (stuck IO, wedged kernel) within
one watchdog period of the deadline, quarantines them, and speculatively
re-executes them through the same compiled kernel — first result wins, with
a bit-identity check when both copies complete.  ``store_verify_fn`` (built
by :func:`region_verifier` from a checksummed dataset) re-reads each stored
region so a chunk corrupted on storage is repaired by a re-store (retry) or
a recompute (quarantine) while the writer still owns the block.
"""

from __future__ import annotations

import contextlib
import itertools
import math
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor, Future
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..io.containers import ChunkCorruptionError
from ..utils import function_utils as fu
from ..utils.volume_utils import Block, Blocking
from . import faults as faults_mod
from .supervision import FirstWins, Watchdog, array_digest


# canonical device-selection policy lives in parallel/mesh.py
from ..parallel.mesh import backend_devices as get_devices


def get_mesh(
    target: str = "local",
    n_devices: Optional[int] = None,
    axis_name: str = "blocks",
) -> Mesh:
    devs = get_devices(target, n_devices)
    return Mesh(np.array(devs), (axis_name,))


def check_finite_outputs(block: Block, out) -> Optional[str]:
    """Built-in output validator: any non-finite value in a float leaf is a
    corrupt kernel output (the classic silent NaN-producing-kernel failure)."""
    for leaf in jax.tree_util.tree_leaves(out):
        a = np.asarray(leaf)
        if a.dtype.kind == "f" and not np.isfinite(a).all():
            return "non-finite values (NaN/inf) in kernel output"
    return None


def region_verifier(
    dataset, bb_of: Optional[Callable[[Block], Any]] = None
) -> Optional[Callable[[Block], None]]:
    """Build a ``store_verify_fn`` for :meth:`BlockwiseExecutor.map_blocks`
    from a dataset with digest sidecars: read the block's stored region back
    and raise :class:`~cluster_tools_tpu.io.containers.ChunkCorruptionError`
    if its bytes no longer match the recorded checksum.  Returns None for
    datasets without checksum support (HDF5), so call sites wire it
    unconditionally."""
    verify = getattr(dataset, "verify_region", None)
    if verify is None:
        return None
    if bb_of is None:
        bb_of = lambda block: block.bb  # noqa: E731 - trivial default

    def store_verify(block: Block) -> None:
        verify(bb_of(block))

    return store_verify


def validate_labels(block: Block, out) -> Optional[str]:
    """Validator for label-producing kernels: negative (signed) or
    saturated (unsigned) label values are the integer shadows of a corrupt
    kernel — a NaN cast to int yields exactly these.  Float leaves are
    covered by ``map_blocks``' built-in ``check_finite`` pass, not here."""
    for leaf in jax.tree_util.tree_leaves(out):
        a = np.asarray(leaf)
        if a.size == 0:
            continue
        if a.dtype.kind == "i" and int(a.min()) < 0:
            return "negative label values (corrupt kernel output)"
        if a.dtype.kind == "u" and bool((a == np.iinfo(a.dtype).max).any()):
            return "saturated label values (corrupt kernel output)"
    return None


class BlockwiseExecutor:
    """Run a per-block kernel over a list of blocks, batched across devices.

    ``kernel`` is a pure function over one block's arrays; it is vmapped,
    jitted, and the batch axis is sharded over the mesh.  ``load_fn(block)``
    returns the kernel's input arrays for one block (already padded to a
    uniform shape); ``store_fn(block, outputs)`` persists one block's outputs
    (each already a numpy array).
    """

    def __init__(
        self,
        target: str = "local",
        n_devices: Optional[int] = None,
        device_batch: int = 1,
        io_threads: int = 8,
        max_retries: int = 2,
        backoff_base: float = 0.05,
        backoff_max: float = 5.0,
    ):
        self.target = target
        self.devices = get_devices(target, n_devices)
        self.n_devices = len(self.devices)
        self.device_batch = int(device_batch)
        self.batch_size = self.n_devices * self.device_batch
        self.mesh = Mesh(np.array(self.devices), ("blocks",))
        self.io_threads = io_threads
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)

    # -- retry/backoff machinery ------------------------------------------
    def _backoff(self, attempt: int) -> float:
        return fu.backoff_delay(attempt, self.backoff_base, self.backoff_max)

    def _io_with_retries(
        self, site: str, block: Block, fn: Callable,
        on_error: Optional[Callable[[Exception], None]] = None,
    ):
        """Run ``fn`` with injection + retries.  Returns
        ``(value, attempts, traceback_or_None)``; the caller quarantines on
        a non-None traceback.  ``on_error`` observes each caught exception
        (failure-class attribution, e.g. counting ChunkCorruptionErrors)."""
        injector = faults_mod.get_injector()
        last_tb = None
        for k in range(self.max_retries + 1):
            try:
                injector.maybe_fail(site, block.block_id)
                injector.maybe_hang(site, block.block_id)
                return fn(), k + 1, None
            except Exception as e:
                if on_error is not None:
                    try:
                        on_error(e)
                    except Exception:
                        pass
                last_tb = fu.cap_traceback(traceback.format_exc())
                if k < self.max_retries:
                    time.sleep(self._backoff(k))
        return None, self.max_retries + 1, last_tb

    def map_blocks(
        self,
        kernel: Callable,
        blocks: Sequence[Block],
        load_fn: Callable[[Block], Tuple],
        store_fn: Optional[Callable[[Block, Any], None]] = None,
        on_block_done: Optional[Callable[[Block], None]] = None,
        prefetch: int = 2,
        done_block_ids: Optional[Iterable[int]] = None,
        validate_fn: Optional[Callable[[Block, Any], Optional[str]]] = None,
        check_finite: bool = True,
        failures_path: Optional[str] = None,
        task_name: str = "map_blocks",
        block_deadline_s: Optional[float] = None,
        watchdog_period_s: Optional[float] = None,
        speculate: bool = True,
        store_verify_fn: Optional[Callable[[Block], None]] = None,
    ) -> Dict[str, int]:
        """Execute ``kernel`` over ``blocks``; see class docstring.

        ``done_block_ids`` — block ids to skip (success-marker resume grain).
        ``validate_fn(block, outputs) -> Optional[str]`` — extra output
        validation; a non-None message quarantines the block for re-compute.
        ``check_finite`` — built-in NaN/inf validation of float outputs.
        ``failures_path`` — where to record the ``failures.json`` manifest.
        ``block_deadline_s`` — per-block wall-clock budget: a watchdog
        thread declares blocks whose load/compute/store exceeds it *hung*
        (recorded + quarantined within one ``watchdog_period_s``, default
        ``deadline/4``) and, when ``speculate``, launches a duplicate
        re-execution through the same compiled kernel — first result wins,
        and if both copies complete they must agree bit-for-bit (a
        disagreement is recorded as a ``determinism`` failure and the block
        is recomputed).  ``store_verify_fn(block)`` — post-store integrity
        check (see :func:`region_verifier`); a ChunkCorruptionError it
        raises makes the store retry (re-write repairs the corrupt chunk),
        then quarantine (recompute repairs it).
        Raises RuntimeError naming every block that stays failed after the
        end-of-run quarantine pass.
        """
        if done_block_ids:
            done = {int(b) for b in done_block_ids}
            blocks = [b for b in blocks if int(b.block_id) not in done]
        if not blocks:
            return {"n_blocks": 0, "n_quarantined": 0, "n_failed": 0}
        injector = faults_mod.get_injector()
        deadline = float(block_deadline_s or 0.0)
        block_by_id = {int(b.block_id): b for b in blocks}
        bs = self.batch_size
        n_batches = math.ceil(len(blocks) / bs)
        sharding = NamedSharding(self.mesh, P("blocks"))
        batched_kernel = jax.jit(
            jax.vmap(kernel), in_shardings=sharding, out_shardings=sharding
        )

        # per-block failure bookkeeping (threads: IO pool + dispatch loop)
        failures: Dict[int, Dict[str, Any]] = {}
        fail_lock = threading.Lock()
        quarantined_ids: set = set()

        def note_failure(block, site, attempts, error, quarantine):
            with fail_lock:
                rec = failures.setdefault(
                    int(block.block_id),
                    {
                        "block_id": int(block.block_id),
                        "sites": {},
                        "error": None,
                        "quarantined": False,
                        "resolved": True,
                    },
                )
                rec["sites"][site] = rec["sites"].get(site, 0) + int(attempts)
                if error is not None:
                    rec["error"] = error
                if quarantine:
                    rec["quarantined"] = True
                    rec["resolved"] = False
                    quarantined_ids.add(int(block.block_id))

        def mark_resolved(block):
            with fail_lock:
                rec = failures.get(int(block.block_id))
                if rec is not None:
                    rec["resolved"] = True

        def validate(block, out) -> Optional[str]:
            if check_finite:
                err = check_finite_outputs(block, out)
                if err:
                    return err
            if validate_fn is not None:
                return validate_fn(block, out)
            return None

        # -- hang defense: watchdog + speculative duplicates ----------------
        # in-flight (block, stage) work registers with a watchdog; overdue
        # work is recorded as hung + quarantined, and a duplicate of the
        # block runs through the same compiled kernel — FirstWins arbitrates.
        # ALL dispatches of the compiled kernel share one lock: the program
        # is sharded across every device, and two concurrent executions of a
        # multi-device program deadlock XLA's collective rendezvous (each
        # waits for all participants) — the devices are a serial resource,
        # so serializing dispatch costs nothing and removes the hazard.
        dispatch_lock = threading.Lock()
        speculated: set = set()
        commits = FirstWins()
        spec_pool: Optional[ThreadPoolExecutor] = None
        spec_futures: List[Future] = []
        watchdog: Optional[Watchdog] = None
        _tokens = itertools.count()

        @contextlib.contextmanager
        def _watched(block, stage, origin="primary"):
            if watchdog is None:
                yield
                return
            token = next(_tokens)
            watchdog.register(
                token, block_id=int(block.block_id), stage=stage, origin=origin
            )
            try:
                yield
            finally:
                watchdog.clear(token)

        class _PreIssueFailed(Exception):
            pass

        def load_block(block, pre=None, pre_tb=None, origin="primary"):
            """Load one block with retries; returns arrays or None
            (quarantined).  ``pre`` is an already-issued load_fn result
            consumed by the first attempt (batch reads are issued together
            so the storage layer runs the chunk IO concurrently)."""
            last_tb, attempts = None, 0
            with contextlib.ExitStack() as stack:
                stack.enter_context(_watched(block, "load", origin))
                stack.enter_context(
                    faults_mod.block_context(int(block.block_id))
                )
                for k in range(self.max_retries + 1):
                    attempts = k + 1
                    try:
                        injector.maybe_fail("load", block.block_id)
                        injector.maybe_hang("load", block.block_id)
                        if k == 0 and pre_tb is not None:
                            last_tb = pre_tb
                            raise _PreIssueFailed()
                        per = pre if (k == 0 and pre is not None) else load_fn(block)
                        val = tuple(
                            x.result() if hasattr(x, "result") else x for x in per
                        )
                    except _PreIssueFailed:
                        if k < self.max_retries:
                            time.sleep(self._backoff(k))
                    except Exception:
                        last_tb = fu.cap_traceback(traceback.format_exc())
                        if k < self.max_retries:
                            time.sleep(self._backoff(k))
                    else:
                        if attempts > 1:
                            note_failure(block, "load", attempts - 1, None, False)
                        return val
            note_failure(block, "load", attempts, last_tb, quarantine=True)
            return None

        def load_batch(batch_idx: int):
            batch = blocks[batch_idx * bs : (batch_idx + 1) * bs]
            # load_fn may return futures (e.g. io.prefetch.async_loader's
            # tensorstore read futures): issue EVERY read of the batch first,
            # then resolve — the storage layer runs the chunk IO concurrently
            issued = []
            for b in batch:
                try:
                    with faults_mod.block_context(int(b.block_id)):
                        issued.append((load_fn(b), None))
                except Exception:
                    issued.append(
                        (None, fu.cap_traceback(traceback.format_exc()))
                    )
            ok_blocks, per_block = [], []
            for b, (pre, pre_tb) in zip(batch, issued):
                val = load_block(b, pre=pre, pre_tb=pre_tb)
                if val is not None:
                    ok_blocks.append(b)
                    per_block.append(val)
            if not ok_blocks:
                return [], None
            n_args = len(per_block[0])
            # pad the partial batch (tail, or quarantine-induced holes) by
            # repeating the last block so the compiled shape stays static;
            # padded outputs are dropped
            n_pad = bs - len(per_block)
            if n_pad:
                per_block = per_block + [per_block[-1]] * n_pad
            arrays = tuple(
                np.stack([pb[i] for pb in per_block]) for i in range(n_args)
            )
            return ok_blocks, arrays

        finished_ids: set = set()

        def finish_block(blk):
            """Completion side effects (success marker + block_done kill
            point) at most ONCE per block — with speculation, two copies of
            a block can both reach a happy end (uncontended-looking winner
            plus a later-agreeing duplicate) and must not double-fire."""
            with fail_lock:
                if int(blk.block_id) in finished_ids:
                    return
                finished_ids.add(int(blk.block_id))
            if on_block_done is not None:
                on_block_done(blk)
            injector.kill_point("block_done")

        def handle_block_output(blk, block_out, origin="primary"):
            """Corrupt-injection, validation, duplicate arbitration, store
            (with retries + integrity verify), marker.  Never raises —
            failures (including programming errors in the validate/marker
            hooks) quarantine the block, keeping every error attributed to
            its block id."""
            bid = int(blk.block_id)
            try:
                block_out = injector.corrupt("kernel", blk.block_id, block_out)
                err = validate(blk, block_out)
                if err is not None:
                    note_failure(blk, "validate", 1, err, quarantine=True)
                    return
                if store_fn is not None:
                    corrupt_seen = [0]
                    dup_state = {"verdict": None, "digest": None,
                                 "contended": False}

                    def _classify(exc):
                        if isinstance(exc, ChunkCorruptionError):
                            corrupt_seen[0] += 1

                    def _store_and_verify():
                        # first-wins gate, decided at the LAST moment before
                        # the write: this copy may have been declared hung
                        # and overtaken by a speculative duplicate while it
                        # was stuck on the way here.  With the watchdog
                        # armed EVERY copy registers its digest — a
                        # duplicate spawned after an uncontended-looking
                        # primary passed this point must still find the
                        # claim.  Decided once; store retries reuse it.
                        if dup_state["verdict"] is None:
                            if watchdog is not None:
                                with fail_lock:
                                    dup_state["contended"] = bid in speculated
                                dup_state["digest"] = array_digest(
                                    jax.tree_util.tree_leaves(block_out)
                                )
                                dup_state["verdict"] = commits.commit(
                                    bid, dup_state["digest"]
                                )
                            else:
                                dup_state["verdict"] = FirstWins.WIN
                        if dup_state["verdict"] != FirstWins.WIN:
                            return  # arbitrated below, nothing to store
                        store_fn(blk, block_out)
                        if store_verify_fn is not None:
                            store_verify_fn(blk)

                    with contextlib.ExitStack() as stack:
                        stack.enter_context(_watched(blk, "store", origin))
                        stack.enter_context(faults_mod.block_context(bid))
                        _, attempts, tb = self._io_with_retries(
                            "store", blk, _store_and_verify, on_error=_classify
                        )
                    if dup_state["verdict"] == FirstWins.AGREE:
                        # this copy confirms the stored winner bit-for-bit:
                        # resolved without a second store (also the
                        # arbitration path after a mismatch — a third copy
                        # agreeing with the winner validates it).  A
                        # contended winner deferred the completion side
                        # effects to this settling point; finish_block
                        # de-duplicates against a winner that already ran
                        # them (it looked uncontended when it decided).
                        mark_resolved(blk)
                        with fail_lock:
                            rec = failures.get(bid)
                            if rec is not None:
                                rec["duplicate"] = "agreed"
                        finish_block(blk)
                        return
                    if dup_state["verdict"] == FirstWins.MISMATCH:
                        note_failure(
                            blk, "determinism", 1,
                            "speculative duplicate disagreed with the first "
                            "result (nondeterministic kernel or corrupted "
                            "data); block left unresolved for recompute",
                            quarantine=True,
                        )
                        return
                    if corrupt_seen[0]:
                        # attribute the fault class: the store "failures"
                        # were chunk corruption caught by the digest verify
                        note_failure(
                            blk, "corrupt", corrupt_seen[0], None,
                            quarantine=False,
                        )
                    if tb is not None:
                        if dup_state["digest"] is not None:
                            # the WIN claim's store never landed: release it
                            # so the quarantine recompute is not misread as
                            # a duplicate of a result that does not exist
                            commits.withdraw(bid, dup_state["digest"])
                        note_failure(blk, "store", attempts, tb, quarantine=True)
                        return
                    if attempts > 1:
                        note_failure(
                            blk, "store", attempts - 1, None, quarantine=False
                        )
                    mark_resolved(blk)
                    if not dup_state["contended"]:
                        # a contended winner defers the success marker to the
                        # duplicate's AGREE above: a mismatch must not leave
                        # a marker a resumed run would trust (if the other
                        # copy dies instead, the unmarked block is merely
                        # recomputed on resume — safe)
                        finish_block(blk)
                else:
                    mark_resolved(blk)
                    finish_block(blk)
            except Exception:
                # site "hook", not "store": the store path itself retries
                # and records above — only validate_fn/on_block_done/corrupt
                # programming errors land here
                note_failure(
                    blk,
                    "hook",
                    1,
                    fu.cap_traceback(traceback.format_exc()),
                    quarantine=True,
                )
                return

        def speculative_rerun(blk):
            """Duplicate execution of a hung block: fresh load, the SAME
            compiled kernel on the reduced-batch path, and a first-wins
            commit against the (possibly still stuck) original."""
            try:
                val = load_block(blk, origin="speculative")
                if val is None:
                    return
                stacked = tuple(np.stack([x] * bs) for x in val)
                stacked = tuple(jax.device_put(a, sharding) for a in stacked)
                with dispatch_lock:
                    out = batched_kernel(*stacked)
                out0 = jax.tree_util.tree_map(lambda a: np.asarray(a)[0], out)
                handle_block_output(blk, out0, origin="speculative")
            except Exception:
                note_failure(
                    blk, "speculate", 1,
                    fu.cap_traceback(traceback.format_exc()),
                    quarantine=False,
                )

        if deadline > 0:
            spec_pool = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="speculate"
            )

            def _on_hung(token, info, elapsed):
                bid = int(info["block_id"])
                blk = block_by_id[bid]
                note_failure(
                    blk, "hung", 1,
                    f"block exceeded block_deadline_s={deadline:g}s in "
                    f"stage {info['stage']} ({elapsed:.2f}s elapsed)",
                    quarantine=True,
                )
                if not speculate or info.get("origin") != "primary":
                    return
                with fail_lock:
                    if bid in speculated:
                        return
                    speculated.add(bid)
                spec_futures.append(spec_pool.submit(speculative_rerun, blk))

            watchdog = Watchdog(
                deadline,
                watchdog_period_s or max(0.02, deadline / 4.0),
                _on_hung,
            ).start()

        try:
            with ThreadPoolExecutor(max_workers=self.io_threads) as pool:
                pending_loads: List[Future] = [
                    pool.submit(load_batch, i) for i in range(min(prefetch, n_batches))
                ]
                write_futures: List[Future] = []
                for i in range(n_batches):
                    batch, arrays = pending_loads.pop(0).result()
                    if i + prefetch < n_batches:
                        pending_loads.append(pool.submit(load_batch, i + prefetch))
                    # prompt drain: surface finished stores (and any programming
                    # error in the store path, with its batch's block ids) now,
                    # not at the end of the run
                    while write_futures and write_futures[0].done():
                        write_futures.pop(0).result()
                    if not batch:
                        continue  # every block of this batch was quarantined
                    arrays = tuple(jax.device_put(a, sharding) for a in arrays)
                    try:
                        # take the dispatch lock BEFORE starting the blocks'
                        # compute clocks: waiting behind a (possibly cold-
                        # compiling) speculative dispatch is not this batch's
                        # wall time, and must not cascade into false hangs
                        with dispatch_lock, contextlib.ExitStack() as stack:
                            for blk in batch:
                                stack.enter_context(_watched(blk, "compute"))
                            out = batched_kernel(*arrays)
                    except Exception:
                        # a compute failure poisons the whole batch; quarantine
                        # all of it — the reduced-batch pass isolates the culprit
                        tb = fu.cap_traceback(traceback.format_exc())
                        for blk in batch:
                            note_failure(blk, "compute", 1, tb, quarantine=True)
                        continue

                    def store_batch(batch=batch, out=out):
                        # the device->host copy happens HERE, on the IO pool, so
                        # the dispatch loop is free to enqueue the next batch
                        # while this one's outputs stream back.  This copy is
                        # also where a kernel wedged at RUNTIME blocks (the
                        # jitted call above returns at dispatch — async), so
                        # it is the stage the compute watchdog must cover.
                        with contextlib.ExitStack() as stack:
                            for blk in batch:
                                stack.enter_context(_watched(blk, "compute"))
                            out_np = jax.tree_util.tree_map(np.asarray, out)
                        for j, blk in enumerate(batch):
                            block_out = jax.tree_util.tree_map(
                                lambda a: a[j], out_np
                            )
                            handle_block_output(blk, block_out)

                    write_futures.append(pool.submit(store_batch))
                    # backpressure: each pending store closure pins its batch's
                    # DEVICE output buffers until its d2h copy runs, so the bound
                    # must be a small constant (not thread-count) or HBM fills
                    # with undrained outputs
                    while len(write_futures) > 2:
                        write_futures.pop(0).result()
                for f in write_futures:
                    f.result()

                # settle speculative duplicates before judging what is still
                # unresolved (the list can grow while we drain: a primary still
                # stuck past its deadline fires the watchdog mid-drain)
                i_spec = 0
                while i_spec < len(spec_futures):
                    spec_futures[i_spec].result()
                    i_spec += 1
                if watchdog is not None:
                    watchdog.stop()
                if spec_pool is not None:
                    spec_pool.shutdown(wait=True)

                # -- quarantine pass: reduced-batch re-attempts -----------------
                # re-run each still-unresolved quarantined block alone,
                # replicated to the batch width through the SAME compiled kernel
                # — bit-identical results, and a batch-poisoning block is
                # isolated to itself.  Blocks a speculative duplicate (or a
                # late-finishing hung primary) already resolved are skipped.
                with fail_lock:
                    unresolved_q = {
                        b for b in quarantined_ids if not failures[b]["resolved"]
                    }
                for blk in [b for b in blocks if int(b.block_id) in unresolved_q]:
                    val = load_block(blk)
                    if val is None:
                        continue  # still failing; stays unresolved
                    stacked = tuple(np.stack([x] * bs) for x in val)
                    stacked = tuple(jax.device_put(a, sharding) for a in stacked)
                    try:
                        with dispatch_lock:
                            out = batched_kernel(*stacked)
                    except Exception:
                        tb = fu.cap_traceback(traceback.format_exc())
                        note_failure(blk, "compute", 1, tb, quarantine=True)
                        continue
                    out0 = jax.tree_util.tree_map(
                        lambda a: np.asarray(a)[0], out
                    )
                    handle_block_output(blk, out0)

        finally:
            # the watchdog and speculation pool must not outlive the
            # sweep, even when a load/store future propagates an error
            if watchdog is not None:
                watchdog.stop()
            if spec_pool is not None:
                spec_pool.shutdown(wait=True)

        unresolved = sorted(
            b for b, rec in failures.items() if not rec["resolved"]
        )
        if failures_path and failures:
            fu.record_failures(
                failures_path,
                task_name,
                [failures[b] for b in sorted(failures)],
            )
        if unresolved:
            details = "\n".join(
                f"-- block {b} (sites {failures[b]['sites']}) --\n"
                f"{failures[b]['error']}"
                for b in unresolved[:5]
            )
            raise RuntimeError(
                f"{task_name}: {len(unresolved)}/{len(blocks)} blocks failed "
                f"permanently after retries + quarantine re-attempts "
                f"(ids: {unresolved})"
                + (f"; see {failures_path}" if failures_path else "")
                + f"; first errors:\n{details}"
            )
        summary = {
            "n_blocks": len(blocks),
            "n_quarantined": len(quarantined_ids),
            "n_failed": 0,
        }
        if deadline > 0:
            summary["n_hung"] = sum(
                1 for rec in failures.values() if "hung" in rec["sites"]
            )
            summary["n_speculated"] = len(speculated)
        return summary
