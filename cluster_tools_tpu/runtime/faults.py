"""Deterministic fault injection for chaos testing the execution layer.

Long-running multi-chip jobs make preemption, transient IO failure, and
corrupt kernel outputs the *common* case; this module is how we prove the
runtime survives them.  A :class:`FaultInjector` is configured from a JSON
document (programmatically via :func:`configure`, or across process
boundaries via the ``CTT_FAULTS`` environment variable — either inline JSON
or a path to a JSON file) and exposes three hook points that the executor,
task runtime, and container IO layer call at their failure-relevant sites:

- :meth:`FaultInjector.maybe_fail` — raise :class:`InjectedFault` at a load
  / store / io_read / io_write site (transient or persistent, depending on
  ``fail_attempts``),
- :meth:`FaultInjector.corrupt` — poison kernel outputs (NaN for float
  leaves; the NaN-cast garbage values for integer leaves), modelling a
  NaN/inf-producing kernel,
- :meth:`FaultInjector.kill_point` — ``os._exit`` at the N-th crossing of a
  named progress point (``block_done`` / ``task_done``), modelling
  preemption.  A latch file in ``state_dir`` makes the kill one-shot, so a
  resumed run with the *same* ``CTT_FAULTS`` does not die again.

Config schema::

    {
      "seed": 7,                      # drives rate-based faults
      "state_dir": "/scratch/chaos",  # kill latches (required for kills)
      "faults": [
        # transient load failure: block 3 fails its first attempt
        {"site": "load", "kind": "error", "blocks": [3]},
        # persistent store failure: block 5 fails its first 4 attempts
        {"site": "store", "kind": "error", "blocks": [5], "fail_attempts": 4},
        # NaN-producing kernel on block 2 (first attempt only)
        {"site": "kernel", "kind": "nan", "blocks": [2]},
        # random 10% of io reads fail (seeded, deterministic per attempt)
        {"site": "io_read", "kind": "error", "rate": 0.1,
         "fail_attempts": 1000000},
        # preemption: exit hard at the 3rd completed block
        {"site": "block_done", "kind": "kill", "after": 3}
      ]
    }

Attempt counting is per ``(site, block, fault)`` and in-memory: the N-th
call of a hook for a given block is the N-th attempt, so ``fail_attempts``
models transient (1–2) versus persistent (> the executor's retry budget)
failures, and retries/quarantine re-attempts eventually pass.  Rate-based
faults hash ``(seed, site, block, attempt)`` so they are reproducible
without shared state.
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Any, Dict, Optional

import numpy as np

#: Exit code used by kill faults — chaos tests assert on it to distinguish
#: an injected kill from a genuine crash.
KILL_EXIT_CODE = 113

ENV_VAR = "CTT_FAULTS"

_ERROR_SITES = ("load", "store", "io_read", "io_write", "submit", "task")
_KILL_SITES = ("block_done", "task_done")


class InjectedFault(RuntimeError):
    """The exception raised by ``kind='error'`` faults."""

    def __init__(self, site: str, block_id: Optional[int], attempt: int):
        self.site = site
        self.block_id = block_id
        self.attempt = attempt
        super().__init__(
            f"injected {site} fault"
            + (f" on block {block_id}" if block_id is not None else "")
            + f" (attempt {attempt})"
        )


def _poison_leaf(a):
    """Model a NaN-producing kernel: float leaves become NaN; integer
    leaves get the value a NaN cast yields (INT_MIN for signed, max for
    unsigned) — the garbage that reaches storage when nobody validates."""
    a = np.asarray(a)
    if a.dtype.kind == "f":
        return np.full_like(a, np.nan)
    if a.dtype.kind == "i":
        return np.full_like(a, np.iinfo(a.dtype).min)
    if a.dtype.kind == "u":
        return np.full_like(a, np.iinfo(a.dtype).max)
    return a


class FaultInjector:
    """Seeded, deterministic fault injector.  With no faults configured
    every hook is a cheap no-op, so the hooks stay permanently wired into
    the production paths."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        config = dict(config or {})
        self.seed = int(config.get("seed", 0))
        self.state_dir = config.get("state_dir")
        self.specs = [dict(s) for s in config.get("faults", [])]
        self.enabled = bool(self.specs)
        self._counts: Dict[tuple, int] = {}
        self._lock = threading.Lock()
        for spec in self.specs:
            kind = spec.get("kind")
            site = spec.get("site")
            if kind == "kill":
                if site not in _KILL_SITES:
                    raise ValueError(
                        f"kill fault site must be one of {_KILL_SITES}, "
                        f"got {site!r}"
                    )
                if not self.state_dir:
                    raise ValueError(
                        "kill faults require 'state_dir' (the one-shot "
                        "latch must survive the process they kill)"
                    )
            elif kind == "nan":
                if site != "kernel":
                    raise ValueError("nan faults only apply to site='kernel'")
            elif kind == "error":
                if site not in _ERROR_SITES:
                    raise ValueError(
                        f"error fault site must be one of {_ERROR_SITES}, "
                        f"got {site!r}"
                    )
            else:
                raise ValueError(f"unknown fault kind {kind!r}")
        if self.state_dir:
            os.makedirs(self.state_dir, exist_ok=True)

    # -- internals ---------------------------------------------------------
    def _unit(self, *parts) -> float:
        key = ":".join(str(p) for p in (self.seed,) + parts)
        return zlib.crc32(key.encode()) / 0xFFFFFFFF

    def _next_attempt(self, site, block_id, idx) -> int:
        with self._lock:
            key = (site, block_id, idx)
            attempt = self._counts.get(key, 0) + 1
            self._counts[key] = attempt
            return attempt

    def _active(self, idx, spec, site, block_id, kind) -> Optional[int]:
        """Attempt number if this spec fires for (site, block), else None.
        Calling this *counts* an attempt for matching specs."""
        if spec.get("kind") != kind or spec.get("site") != site:
            return None
        blocks = spec.get("blocks")
        if blocks is not None:
            if block_id is None or int(block_id) not in {int(b) for b in blocks}:
                return None
        attempt = self._next_attempt(site, block_id, idx)
        if attempt > int(spec.get("fail_attempts", 1)):
            return None
        rate = spec.get("rate")
        if rate is not None and self._unit(site, block_id, attempt) >= float(rate):
            return None
        return attempt

    # -- hook points ---------------------------------------------------------
    def maybe_fail(self, site: str, block_id: Optional[int] = None) -> None:
        """Raise :class:`InjectedFault` if an error fault fires here."""
        if not self.enabled:
            return
        for idx, spec in enumerate(self.specs):
            attempt = self._active(idx, spec, site, block_id, "error")
            if attempt is not None:
                raise InjectedFault(site, block_id, attempt)

    def corrupt(self, site: str, block_id: Optional[int], tree):
        """Return ``tree`` with every array leaf poisoned if a nan fault
        fires here, else ``tree`` unchanged."""
        if not self.enabled:
            return tree
        for idx, spec in enumerate(self.specs):
            if self._active(idx, spec, site, block_id, "nan") is not None:
                import jax

                return jax.tree_util.tree_map(_poison_leaf, tree)
        return tree

    def kill_point(self, site: str) -> None:
        """Hard-exit (``os._exit``) at the configured crossing of ``site``.
        One-shot per fault via a latch file in ``state_dir``."""
        if not self.enabled:
            return
        for idx, spec in enumerate(self.specs):
            if spec.get("kind") != "kill" or spec.get("site") != site:
                continue
            count = self._next_attempt(site, None, idx)
            if count != int(spec.get("after", 1)):
                continue
            latch = os.path.join(self.state_dir, f"kill_{idx}.done")
            if os.path.exists(latch):
                continue
            # latch first (atomically), then die: the resumed run must not
            # re-fire even if the exit races other threads
            tmp = latch + f".tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(site)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, latch)
            os._exit(KILL_EXIT_CODE)


# -- module-level singleton ---------------------------------------------------

_injector: Optional[FaultInjector] = None
_singleton_lock = threading.Lock()


def _load_env_config() -> Dict[str, Any]:
    raw = os.environ.get(ENV_VAR, "").strip()
    if not raw:
        return {}
    if raw.startswith("{"):
        return json.loads(raw)
    with open(raw) as f:
        return json.load(f)


def get_injector() -> FaultInjector:
    """The process-wide injector; configured lazily from ``CTT_FAULTS``."""
    global _injector
    if _injector is None:
        with _singleton_lock:
            if _injector is None:
                _injector = FaultInjector(_load_env_config())
    return _injector


def configure(config: Optional[Dict[str, Any]]) -> FaultInjector:
    """Install an injector programmatically (tests); pass None to disable."""
    global _injector
    with _singleton_lock:
        _injector = FaultInjector(config)
    return _injector


def reset() -> None:
    """Drop the installed injector; the next ``get_injector`` re-reads the
    environment."""
    global _injector
    with _singleton_lock:
        _injector = None
