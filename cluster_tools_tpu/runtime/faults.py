"""Deterministic fault injection for chaos testing the execution layer.

Long-running multi-chip jobs make preemption, transient IO failure, and
corrupt kernel outputs the *common* case; this module is how we prove the
runtime survives them.  A :class:`FaultInjector` is configured from a JSON
document (programmatically via :func:`configure`, or across process
boundaries via the ``CTT_FAULTS`` environment variable — either inline JSON
or a path to a JSON file) and exposes three hook points that the executor,
task runtime, and container IO layer call at their failure-relevant sites:

- :meth:`FaultInjector.maybe_fail` — raise :class:`InjectedFault` at a load
  / store / io_read / io_write site (transient or persistent, depending on
  ``fail_attempts``),
- :meth:`FaultInjector.corrupt` — poison kernel outputs (NaN for float
  leaves; the NaN-cast garbage values for integer leaves), modelling a
  NaN/inf-producing kernel,
- :meth:`FaultInjector.kill_point` — ``os._exit`` at the N-th crossing of a
  named progress point (``block_done`` / ``task_done``), modelling
  preemption.  A latch file in ``state_dir`` makes the kill one-shot, so a
  resumed run with the *same* ``CTT_FAULTS`` does not die again,
- :meth:`FaultInjector.maybe_hang` — sleep ``seconds`` at a load / store /
  io_read / io_write site (``kind='hang'``), modelling a stuck kernel or a
  wedged filesystem call.  The executor's per-block deadline watchdog is
  what must notice,
- :meth:`FaultInjector.chunk_corrupt` — report that a stored region should
  be silently damaged (``kind='corrupt'``).  At site ``io_write`` the
  container layer bit-flips the chunk *after* recording the region's
  checksum sidecar; at site ``io_read`` it models at-rest bit rot noticed
  at read time — the stored bytes are flipped just before the read
  returns, sidecar untouched, so the verifying reader (``io/verified.py``)
  must detect it and the lineage repair path must heal it.  ``"mode":
  "sidecar"`` deletes the region's digest sidecar instead of flipping
  bytes, exercising the missing-sidecar policy (warn+adopt vs strict),
- :meth:`FaultInjector.lose_job` — swallow a scheduler submission
  (``kind='job_loss'``, site ``submit``): the submitter gets a job id, the
  scheduler keeps reporting it as running, but nothing ever executes —
  only heartbeat supervision (``runtime/cluster.py``) can find it,
- :meth:`FaultInjector.force_spill` — report that an in-memory handoff
  target (``kind='spill'``, site ``publish``; docs/PERFORMANCE.md
  "Task-graph fusion") must be written through to its storage spill path
  instead of living only in host RAM.  The handoff layer
  (``runtime/handoff.py``) queries this at every dataset acquire / array
  publish, so chaos can force the consumer-side fallback-to-storage path
  (and crash-resume from the spilled, checksummed copy) on demand,
- :meth:`FaultInjector.maybe_reject` — force a typed admission rejection
  (``kind='reject'``, site ``admit``; docs/SERVING.md) for a tenant's
  request at the service-mode admission gate (``runtime/server.py``), so
  chaos can prove rejected requests are attributed in ``failures.json``
  and leave no partial markers, manifests, or handoff entries behind.
  Targeted by tenant name (``"tenants": [...]``) instead of block,
- :meth:`FaultInjector.net_fault` — degrade an outbound serve-plane HTTP
  exchange (sites ``net_member`` / ``net_probe`` / ``net_client``; the
  shim in ``runtime/netio.py`` is the single call-through).  Three kinds
  model the gray-failure spectrum (docs/SERVING.md "Gray failures"):
  ``net_delay`` sleeps ``seconds`` before the exchange (congestion, a GC
  pause on the far side), ``net_drop`` raises ``ConnectionResetError``
  mid-exchange (refused/reset connections), and ``net_wedge`` holds the
  accepted connection open without ever answering — the caller's
  *explicit deadline* is the only thing that can save it, which is
  exactly what the gateway's circuit breaker and the CT013 timeout
  audit exist to prove.  Targetable per member/tenant via the
  ``"members"`` spec key,
- :meth:`FaultInjector.torn_append` — tear a submission-journal append
  (``kind='torn'``, site ``journal``; docs/SERVING.md "Durability"): a
  strict prefix of the frame reaches the disk and the process hard-exits
  mid-write (a torn tail only ever exists because its writer died), so
  chaos can prove the restarted reader truncates-and-warns instead of
  refusing to boot.  One-shot via the ``state_dir`` latch like kills;
  ``keep_fraction`` (default 0.5) sets how much of the frame survives.
  The journal's durability boundaries are also kill sites:
  ``journal_append`` (record durable, in-memory state not yet published)
  and ``journal_replay`` (mid-recovery) take ``kind='kill'`` faults.

Resource-exhaustion and preemption classes (docs/ROBUSTNESS.md "Graceful
degradation") ride the same hooks:

- ``kind='oom'`` (sites ``load`` / ``store`` / ``io_read`` / ``io_write`` /
  ``compute``) raises :class:`InjectedOOM` — a real ``MemoryError`` whose
  message carries ``RESOURCE_EXHAUSTED``, so it exercises the executor's
  *typed* resource classification, not a special-cased injection path.  An
  optional ``min_voxels`` gate makes the fault fire only for work units at
  least that large — the physical OOM model: full-size blocks fail, the
  degrade path's smaller sub-blocks fit,
- ``kind='enospc'`` (sites ``store`` / ``io_write``) raises
  :class:`InjectedENOSPC` — an ``OSError`` with ``errno=ENOSPC``, the
  shared-filesystem full condition,
- ``kind='preempt'`` (sites ``block_done`` / ``task_done``, ``after`` like
  kills) delivers a real ``SIGTERM`` to this process at the N-th crossing
  (one-shot via the same ``state_dir`` latch): the drain handler
  (``runtime/supervision.py``) must flip the latch and the runtime must
  drain + exit ``REQUEUE_EXIT_CODE`` instead of dying.

Config schema::

    {
      "seed": 7,                      # drives rate-based faults
      "state_dir": "/scratch/chaos",  # kill latches (required for kills)
      "faults": [
        # transient load failure: block 3 fails its first attempt
        {"site": "load", "kind": "error", "blocks": [3]},
        # persistent store failure: block 5 fails its first 4 attempts
        {"site": "store", "kind": "error", "blocks": [5], "fail_attempts": 4},
        # NaN-producing kernel on block 2 (first attempt only)
        {"site": "kernel", "kind": "nan", "blocks": [2]},
        # random 10% of io reads fail (seeded, deterministic per attempt)
        {"site": "io_read", "kind": "error", "rate": 0.1,
         "fail_attempts": 1000000},
        # hung block: the first load of block 4 sleeps 2 s (past any
        # sub-second block_deadline_s), only in watershed tasks
        {"site": "load", "kind": "hang", "blocks": [4], "seconds": 2.0,
         "tasks": ["watershed"]},
        # silent corruption: block 2's first chunk write is bit-flipped on
        # disk after the checksum sidecar is recorded
        {"site": "io_write", "kind": "corrupt", "blocks": [2]},
        # at-rest rot, noticed at read: block 2's stored bytes are flipped
        # right before its first read returns (sidecar intact) — the
        # verifying reader must raise corrupt:<site>, lineage repair heals
        {"site": "io_read", "kind": "corrupt", "blocks": [2]},
        # sidecar loss: block 2's digest sidecar is deleted at its first
        # read — the per-store missing-sidecar policy decides (adopt/strict)
        {"site": "io_read", "kind": "corrupt", "blocks": [2],
         "mode": "sidecar"},
        # lost scheduler job: the first submission is swallowed
        {"site": "submit", "kind": "job_loss", "fail_attempts": 1},
        # preemption: exit hard at the 3rd completed block
        {"site": "block_done", "kind": "kill", "after": 3},
        # host/device OOM: loads of >= 4096-voxel work units fail (smaller
        # split sub-blocks pass) for the first 1e6 attempts
        {"site": "load", "kind": "oom", "min_voxels": 4096,
         "fail_attempts": 1000000},
        # full filesystem: block 2's first two store attempts hit ENOSPC
        {"site": "store", "kind": "enospc", "blocks": [2],
         "fail_attempts": 2},
        # graceful preemption: a real SIGTERM at the 5th completed block
        {"site": "block_done", "kind": "preempt", "after": 5},
        # forced handoff spill: every in-memory handoff target of watershed
        # tasks is written through to its storage spill path (set
        # fail_attempts high — the hook counts one attempt per publish)
        {"site": "publish", "kind": "spill", "fail_attempts": 1000000,
         "tasks": ["watershed"]},
        # service mode: tenant-b's first 2 submissions to the resident
        # server are rejected with a typed backpressure error
        {"site": "admit", "kind": "reject", "tenants": ["tenant-b"],
         "fail_attempts": 2},
        # durable journal: the 3rd journal append is torn — half the frame
        # lands, the process dies; replay must truncate-and-warn
        {"site": "journal", "kind": "torn", "after": 3,
         "keep_fraction": 0.5},
        # gray failure: member m1 wedges — the gateway's first 4 calls to
        # it are accepted but never answered (the request deadline fires,
        # the breaker opens within one timeout)
        {"site": "net_member", "kind": "net_wedge", "members": ["m1"],
         "fail_attempts": 4, "seconds": 30.0},
        # flaky network: 20% of client submissions see a connection reset
        {"site": "net_client", "kind": "net_drop", "rate": 0.2,
         "fail_attempts": 1000000},
        # slow path: every health probe of m0 is delayed 0.5 s
        {"site": "net_probe", "kind": "net_delay", "members": ["m0"],
         "seconds": 0.5, "fail_attempts": 1000000}
      ]
    }

Attempt counting is per ``(site, block, fault)`` and in-memory: the N-th
call of a hook for a given block is the N-th attempt, so ``fail_attempts``
models transient (1–2) versus persistent (> the executor's retry budget)
failures, and retries/quarantine re-attempts eventually pass.  Rate-based
faults hash ``(seed, site, block, attempt)`` so they are reproducible
without shared state.

Targeting: ``blocks`` gates on the executor's block id — call sites that
don't know it (the container IO layer) inherit it from the executor through
:func:`block_context` (thread-local, set around every per-block load/store).
``tasks`` gates on the running task's uid prefix (:func:`set_current_task`,
process-global — one task runs at a time per process), so one fault spec can
target ``watershed`` blocks without also firing in ``graph``.
"""

from __future__ import annotations

import contextlib
import errno as errno_mod
import json
import os
import signal
import threading
import time
import zlib
from typing import Any, Dict, Optional

import numpy as np

#: Exit code used by kill faults — chaos tests assert on it to distinguish
#: an injected kill from a genuine crash.
KILL_EXIT_CODE = 113

ENV_VAR = "CTT_FAULTS"

#: "solve" is the sharded-global-solve site (parallel/reduce_tree.py): an
#: error there models a lost reduce hop or a dying solver worker — the
#: entry point must degrade to the single-host solve (resolution
#: "degraded:unsharded_solve").  Inside a reduce-tree worker the same hook
#: (block-targeted by worker id) escalates to a real SIGKILL, so chaos can
#: kill one worker of the group and prove the driver's fallback.
#: "hop" is the collective reduce plane's exchange site
#: (parallel/reduce_tree.py, docs/PERFORMANCE.md "Collective reduce
#: plane"): an error there models a failed device collective (init
#: refused, a peer dropping out of the gather), a hang a wedged
#: interconnect hop — either must degrade the level to the filesystem
#: packet plane (resolution "degraded:packet_plane") with bit-identical
#: labels.
_ERROR_SITES = ("load", "store", "io_read", "io_write", "submit", "task",
                "solve", "hop")
#: "journal_append" / "journal_replay" are the durable-journal boundaries
#: (runtime/journal.py, docs/SERVING.md "Durability"): a kill at the
#: former models dying after the fsync'd ack record but before the
#: in-memory state is published; at the latter, dying mid-recovery —
#: either way the restarted replay must reconstruct every acknowledged
#: request.
_KILL_SITES = ("block_done", "task_done", "journal_append",
               "journal_replay")
#: "journal" is the torn-append site (kind='torn'): the submission
#: journal's write is cut mid-frame and the process dies, leaving the
#: torn tail the reader must truncate-and-warn past.
_TORN_SITES = ("journal",)
#: "dispatch" is the batch-grain site of the sharded sweep (one compiled
#: program per Morton batch, docs/PERFORMANCE.md "Sharded sweeps"): an oom
#: there models the whole sharded program exceeding device memory, a hang a
#: wedged device stalling it — either must fall the batch back to per-block
#: execution (resolution "degraded:unsharded"), which this site exercises.
#: Ragged paged batches (docs/PERFORMANCE.md "Ragged sweeps") — mixed-shape
#: main batches AND the degrade ladder's sub-block batches — dispatch
#: through the same site, so the same faults prove their fallback.
#: "hop" hangs model a wedged collective on the reduce plane — the hop
#: deadline must fire and degrade the solve to the packet plane.
_HANG_SITES = ("load", "store", "io_read", "io_write", "dispatch", "hop")
#: silent-corruption sites (kind='corrupt'): at ``io_write`` the flip lands
#: after the write's sidecar is recorded; at ``io_read`` the stored bytes
#: rot just before the read returns (at-rest damage surfacing at the read
#: site, the verifying reader's to catch).  ``mode='sidecar'`` deletes the
#: region's digest sidecar instead — the missing-sidecar-policy drill.
_CORRUPT_SITES = ("io_write", "io_read")
_CORRUPT_MODES = ("flip", "sidecar")
#: "h2d" is the device-pool staging site (parallel/device_pool.py): an oom
#: there models the resident HBM page pool failing to hold a batch's pages
#: — the stage must ride the degrade ladder (evict + retry, then per-batch
#: host staging, resolution "degraded:host_staged").  "publish" doubles as
#: an oom site for the DEVICE handoff rung (runtime/handoff.py): an oom at
#: a device-array publish must fall the payload back to the host memory
#: rung with the same attribution, bit-identically.
_OOM_SITES = ("load", "store", "io_read", "io_write", "compute", "dispatch",
              "h2d", "publish")
_ENOSPC_SITES = ("store", "io_write")
#: "publish" is the handoff-layer site (runtime/handoff.py): the moment a
#: task declares an in-memory target for a dataset or artifact.  A spill
#: fault there forces the write-through to the storage spill path, so chaos
#: can prove consumers fall back to the stored (checksummed) copy and that
#: crash-resume consumes it bit-identically.
_SPILL_SITES = ("publish",)
#: "admit" is the service-mode admission site (runtime/server.py): the
#: moment a tenant's request asks to be queued.  A ``reject`` fault there
#: forces a typed admission rejection (``rejected:fault``), so chaos can
#: prove a rejected request is attributed in failures.json and leaves no
#: partial markers, manifests, or handoff entries behind.  Targeting is by
#: *tenant* (the ``tenants`` spec key), not block — admission has no
#: blocks.
_REJECT_SITES = ("admit",)
#: serve-plane network sites (runtime/netio.py, docs/SERVING.md "Gray
#: failures"): ``net_member`` is the gateway's data-path call to a member
#: (submit/lookup/adopt), ``net_probe`` the health loop's /healthz probe,
#: ``net_client`` the ServeClient's call to a server or gateway.  The
#: net_* kinds fire here: ``net_delay`` (latency), ``net_drop``
#: (reset/refused), ``net_wedge`` (accepted, never answered — only an
#: explicit deadline notices).
_NET_SITES = ("net_member", "net_probe", "net_client")
#: maybe_fail kinds: all raise at the same hook, with their own exception
#: types so the executor's *typed* classification is what gets exercised
_FAIL_KINDS = ("error", "oom", "enospc")


# -- fault-targeting context --------------------------------------------------
# Block ids are thread-local (the executor's IO pool works many blocks at
# once); the current task is process-global (build() runs one task at a
# time per process, and the remote cluster runner is single-task anyway).

_tls = threading.local()
_current_task: Optional[str] = None


@contextlib.contextmanager
def block_context(block_id: Optional[int]):
    """Tag this thread's container-level IO with a block id, so io_read /
    io_write faults (and checksum corruption) can target blocks even though
    the storage layer never sees one."""
    prev = getattr(_tls, "block_id", None)
    _tls.block_id = block_id
    try:
        yield
    finally:
        _tls.block_id = prev


def current_block_id() -> Optional[int]:
    return getattr(_tls, "block_id", None)


def set_current_task(name: Optional[str]) -> None:
    global _current_task
    _current_task = name


def current_task() -> Optional[str]:
    return _current_task


class InjectedFault(RuntimeError):
    """The exception raised by ``kind='error'`` faults."""

    def __init__(self, site: str, block_id: Optional[int], attempt: int):
        self.site = site
        self.block_id = block_id
        self.attempt = attempt
        super().__init__(
            f"injected {site} fault"
            + (f" on block {block_id}" if block_id is not None else "")
            + f" (attempt {attempt})"
        )


class InjectedOOM(MemoryError):
    """``kind='oom'``: a real MemoryError (message mentions
    RESOURCE_EXHAUSTED, like an XLA allocator failure) so the executor's
    typed resource classification — not injection special-casing — routes
    it to the degrade policy."""

    def __init__(self, site: str, block_id: Optional[int], attempt: int):
        self.site = site
        self.block_id = block_id
        self.attempt = attempt
        super().__init__(
            f"injected RESOURCE_EXHAUSTED (oom) at {site}"
            + (f" on block {block_id}" if block_id is not None else "")
            + f" (attempt {attempt})"
        )


class InjectedENOSPC(OSError):
    """``kind='enospc'``: an OSError carrying ``errno=ENOSPC`` — the
    shared-filesystem full condition, classified by errno like the real
    thing."""

    def __init__(self, site: str, block_id: Optional[int], attempt: int):
        self.site = site
        self.block_id = block_id
        self.attempt = attempt
        super().__init__(
            errno_mod.ENOSPC,
            f"injected ENOSPC at {site}"
            + (f" on block {block_id}" if block_id is not None else "")
            + f" (attempt {attempt}): no space left on device",
        )


def _poison_leaf(a):
    """Model a NaN-producing kernel: float leaves become NaN; integer
    leaves get the value a NaN cast yields (INT_MIN for signed, max for
    unsigned) — the garbage that reaches storage when nobody validates."""
    a = np.asarray(a)
    if a.dtype.kind == "f":
        return np.full_like(a, np.nan)
    if a.dtype.kind == "i":
        return np.full_like(a, np.iinfo(a.dtype).min)
    if a.dtype.kind == "u":
        return np.full_like(a, np.iinfo(a.dtype).max)
    return a


class FaultInjector:
    """Seeded, deterministic fault injector.  With no faults configured
    every hook is a cheap no-op, so the hooks stay permanently wired into
    the production paths."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        config = dict(config or {})
        self.seed = int(config.get("seed", 0))
        self.state_dir = config.get("state_dir")
        self.specs = [dict(s) for s in config.get("faults", [])]
        self.enabled = bool(self.specs)
        self._counts: Dict[tuple, int] = {}
        self._lock = threading.Lock()
        for spec in self.specs:
            kind = spec.get("kind")
            site = spec.get("site")
            if kind in ("kill", "preempt"):
                if site not in _KILL_SITES:
                    raise ValueError(
                        f"{kind} fault site must be one of {_KILL_SITES}, "
                        f"got {site!r}"
                    )
                if not self.state_dir:
                    raise ValueError(
                        f"{kind} faults require 'state_dir' (the one-shot "
                        "latch must survive the process they interrupt)"
                    )
            elif kind == "nan":
                if site != "kernel":
                    raise ValueError("nan faults only apply to site='kernel'")
            elif kind == "error":
                if site not in _ERROR_SITES:
                    raise ValueError(
                        f"error fault site must be one of {_ERROR_SITES}, "
                        f"got {site!r}"
                    )
            elif kind == "oom":
                if site not in _OOM_SITES:
                    raise ValueError(
                        f"oom fault site must be one of {_OOM_SITES}, "
                        f"got {site!r}"
                    )
            elif kind == "enospc":
                if site not in _ENOSPC_SITES:
                    raise ValueError(
                        f"enospc fault site must be one of {_ENOSPC_SITES}, "
                        f"got {site!r}"
                    )
            elif kind == "spill":
                if site not in _SPILL_SITES:
                    raise ValueError(
                        f"spill fault site must be one of {_SPILL_SITES}, "
                        f"got {site!r}"
                    )
            elif kind == "reject":
                if site not in _REJECT_SITES:
                    raise ValueError(
                        f"reject fault site must be one of {_REJECT_SITES}, "
                        f"got {site!r}"
                    )
            elif kind == "torn":
                if site not in _TORN_SITES:
                    raise ValueError(
                        f"torn fault site must be one of {_TORN_SITES}, "
                        f"got {site!r}"
                    )
                if not self.state_dir:
                    raise ValueError(
                        "torn faults require 'state_dir' (the torn write "
                        "kills the process; the latch keeps the restarted "
                        "journal from re-tearing)"
                    )
            elif kind == "hang":
                if site not in _HANG_SITES:
                    raise ValueError(
                        f"hang fault site must be one of {_HANG_SITES}, "
                        f"got {site!r}"
                    )
            elif kind == "corrupt":
                if site not in _CORRUPT_SITES:
                    raise ValueError(
                        f"corrupt fault site must be one of {_CORRUPT_SITES} "
                        f"(write-time flip vs at-rest rot at read), got "
                        f"{site!r}"
                    )
                if spec.get("mode", "flip") not in _CORRUPT_MODES:
                    raise ValueError(
                        f"corrupt fault mode must be one of {_CORRUPT_MODES},"
                        f" got {spec.get('mode')!r}"
                    )
            elif kind in ("net_delay", "net_drop", "net_wedge"):
                if site not in _NET_SITES:
                    raise ValueError(
                        f"{kind} fault site must be one of {_NET_SITES}, "
                        f"got {site!r}"
                    )
            elif kind == "job_loss":
                if site != "submit":
                    raise ValueError(
                        "job_loss faults only apply to site='submit'"
                    )
            else:
                raise ValueError(f"unknown fault kind {kind!r}")
        if self.state_dir:
            os.makedirs(self.state_dir, exist_ok=True)

    # -- internals ---------------------------------------------------------
    def _unit(self, *parts) -> float:
        key = ":".join(str(p) for p in (self.seed,) + parts)
        return zlib.crc32(key.encode()) / 0xFFFFFFFF

    def _next_attempt(self, site, block_id, idx) -> int:
        with self._lock:
            key = (site, block_id, idx)
            attempt = self._counts.get(key, 0) + 1
            self._counts[key] = attempt
            return attempt

    def _active(
        self, idx, spec, site, block_id, kind, voxels=None
    ) -> Optional[int]:
        """Attempt number if this spec fires for (site, block), else None.
        Calling this *counts* an attempt for matching specs.  ``min_voxels``
        gates on the caller-reported work-unit size (resource faults: big
        blocks fail, split sub-blocks fit) — unsized calls never match a
        sized spec."""
        if spec.get("kind") != kind or spec.get("site") != site:
            return None
        blocks = spec.get("blocks")
        if blocks is not None:
            if block_id is None or int(block_id) not in {int(b) for b in blocks}:
                return None
        tasks = spec.get("tasks")
        if tasks is not None:
            cur = current_task() or ""
            if not any(cur.startswith(str(t)) for t in tasks):
                return None
        min_voxels = spec.get("min_voxels")
        if min_voxels is not None:
            if voxels is None or int(voxels) < int(min_voxels):
                return None
        attempt = self._next_attempt(site, block_id, idx)
        if attempt > int(spec.get("fail_attempts", 1)):
            return None
        rate = spec.get("rate")
        if rate is not None and self._unit(site, block_id, attempt) >= float(rate):
            return None
        return attempt

    # -- hook points ---------------------------------------------------------
    def maybe_fail(
        self,
        site: str,
        block_id: Optional[int] = None,
        voxels: Optional[int] = None,
    ) -> None:
        """Raise :class:`InjectedFault` / :class:`InjectedOOM` /
        :class:`InjectedENOSPC` if an error / oom / enospc fault fires here.
        ``voxels`` is the caller's work-unit size, used by the ``min_voxels``
        gate of resource faults."""
        if not self.enabled:
            return
        for idx, spec in enumerate(self.specs):
            kind = spec.get("kind")
            if kind not in _FAIL_KINDS:
                continue
            attempt = self._active(idx, spec, site, block_id, kind, voxels)
            if attempt is None:
                continue
            if kind == "oom":
                raise InjectedOOM(site, block_id, attempt)
            if kind == "enospc":
                raise InjectedENOSPC(site, block_id, attempt)
            raise InjectedFault(site, block_id, attempt)

    def corrupt(self, site: str, block_id: Optional[int], tree):
        """Return ``tree`` with every array leaf poisoned if a nan fault
        fires here, else ``tree`` unchanged."""
        if not self.enabled:
            return tree
        for idx, spec in enumerate(self.specs):
            if self._active(idx, spec, site, block_id, "nan") is not None:
                import jax

                return jax.tree_util.tree_map(_poison_leaf, tree)
        return tree

    def maybe_hang(self, site: str, block_id: Optional[int] = None) -> None:
        """Sleep ``seconds`` (default 1.0) if a hang fault fires here —
        modelling a stuck kernel / wedged IO call that only a wall-clock
        deadline can notice.  The sleep is finite so test runs terminate;
        the watchdog must have declared the block hung long before it ends."""
        if not self.enabled:
            return
        for idx, spec in enumerate(self.specs):
            attempt = self._active(idx, spec, site, block_id, "hang")
            if attempt is not None:
                time.sleep(float(spec.get("seconds", 1.0)))

    def chunk_corrupt(
        self, site: str, block_id: Optional[int] = None
    ) -> Optional[str]:
        """Corruption mode for a stored region at this site, or None.
        ``"flip"`` (truthy, the default — existing boolean callers keep
        working): silently bit-flip the stored bytes, sidecar untouched.
        ``"sidecar"``: delete the region's digest sidecar instead, so the
        missing-sidecar policy (``io/verified.py``) is what gets tested.
        At ``io_write`` the damage lands after the write; at ``io_read``
        it models at-rest rot surfacing at the read site."""
        if not self.enabled:
            return None
        for idx, spec in enumerate(self.specs):
            if self._active(idx, spec, site, block_id, "corrupt") is not None:
                return str(spec.get("mode", "flip"))
        return None

    def force_spill(self) -> bool:
        """True if an in-memory handoff target being declared right now
        (site ``publish``) must be written through to its storage spill
        path (``kind='spill'``).  The attempt counter ticks once per
        publish, so ``fail_attempts`` bounds how many targets spill; use a
        large value to force every handoff of a run.  ``tasks`` gates on
        the producing task's uid prefix as usual."""
        if not self.enabled:
            return False
        for idx, spec in enumerate(self.specs):
            if self._active(idx, spec, "publish", None, "spill") is not None:
                return True
        return False

    def maybe_reject(self, tenant: Optional[str] = None) -> bool:
        """True if this admission (site ``admit``, kind ``reject``) must
        be rejected with a typed backpressure error — the service mode's
        seeded per-tenant admission failure (docs/SERVING.md).  The
        ``tenants`` spec key gates on the submitting tenant's name (no
        key: every tenant); attempts count per ``(site, tenant)``, so
        ``fail_attempts`` bounds how many of one tenant's submissions are
        rejected and ``rate`` draws a seeded per-attempt coin."""
        if not self.enabled:
            return False
        for idx, spec in enumerate(self.specs):
            if spec.get("kind") != "reject" or spec.get("site") != "admit":
                continue
            tenants = spec.get("tenants")
            if tenants is not None:
                if tenant is None or str(tenant) not in {
                    str(t) for t in tenants
                }:
                    continue
            attempt = self._next_attempt("admit", tenant, idx)
            if attempt > int(spec.get("fail_attempts", 1)):
                continue
            rate = spec.get("rate")
            if rate is not None and self._unit(
                "admit", tenant, attempt
            ) >= float(rate):
                continue
            return True
        return False

    def net_fault(
        self, site: str, member: Optional[str] = None
    ) -> Optional[tuple]:
        """``(kind, seconds)`` if a net fault fires for this outbound HTTP
        exchange (sites ``net_member`` / ``net_probe`` / ``net_client``),
        else None.  The ``members`` spec key gates on the far side's name
        (a fleet member or, for ``net_client``, a tenant; no key: every
        exchange at the site); attempts count per ``(site, member)``, so
        ``fail_attempts`` bounds how many exchanges degrade and ``rate``
        draws a seeded per-attempt coin.  The shim (``runtime/netio.py``)
        acts on the verdict: ``net_delay`` sleeps ``seconds`` then
        proceeds, ``net_drop`` raises ``ConnectionResetError``,
        ``net_wedge`` blocks until the caller's deadline fires."""
        if not self.enabled:
            return None
        for idx, spec in enumerate(self.specs):
            kind = spec.get("kind")
            if kind not in ("net_delay", "net_drop", "net_wedge") \
                    or spec.get("site") != site:
                continue
            members = spec.get("members")
            if members is not None:
                if member is None or str(member) not in {
                    str(m) for m in members
                }:
                    continue
            attempt = self._next_attempt(site, member, idx)
            if attempt > int(spec.get("fail_attempts", 1)):
                continue
            rate = spec.get("rate")
            if rate is not None and self._unit(
                site, member, attempt
            ) >= float(rate):
                continue
            return (kind, float(spec.get("seconds", 1.0)))
        return None

    def torn_append(self) -> Optional[float]:
        """Fraction of the current journal frame to keep if a ``torn``
        fault (site ``journal``) fires on this append, else None.  The
        journal writes that prefix, fsyncs it, and calls
        :func:`hard_exit` — a torn tail only ever exists because its
        writer died mid-append, so the fault models exactly that.
        One-shot across restarts via the ``state_dir`` latch (the
        resumed server's journal must not re-tear); ``after`` picks the
        N-th append like kill faults."""
        if not self.enabled:
            return None
        for idx, spec in enumerate(self.specs):
            if spec.get("kind") != "torn" or spec.get("site") != "journal":
                continue
            count = self._next_attempt("journal", None, idx)
            if count != int(spec.get("after", 1)):
                continue
            latch = os.path.join(self.state_dir, f"torn_{idx}.done")
            if os.path.exists(latch):
                continue
            tmp = latch + f".tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write("journal")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, latch)
            return float(spec.get("keep_fraction", 0.5))
        return None

    def lose_job(self) -> bool:
        """True if this scheduler submission should be swallowed: the caller
        fabricates a job id the scheduler will keep reporting as running,
        and nothing ever executes — heartbeat supervision must find it."""
        if not self.enabled:
            return False
        for idx, spec in enumerate(self.specs):
            if self._active(idx, spec, "submit", None, "job_loss") is not None:
                return True
        return False

    def kill_point(self, site: str) -> None:
        """Act at the configured crossing of ``site``: ``kind='kill'``
        hard-exits (``os._exit``), ``kind='preempt'`` delivers a real
        SIGTERM to this process (the drain handler must turn it into a
        graceful drain + requeue exit).  One-shot per fault via a latch
        file in ``state_dir``."""
        if not self.enabled:
            return
        for idx, spec in enumerate(self.specs):
            kind = spec.get("kind")
            if kind not in ("kill", "preempt") or spec.get("site") != site:
                continue
            count = self._next_attempt(site, None, idx)
            if count != int(spec.get("after", 1)):
                continue
            latch = os.path.join(self.state_dir, f"kill_{idx}.done")
            if os.path.exists(latch):
                continue
            # latch first (atomically), then act: the resumed run must not
            # re-fire even if the exit races other threads
            tmp = latch + f".tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(site)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, latch)
            if kind == "preempt":
                os.kill(os.getpid(), signal.SIGTERM)
            else:
                os._exit(KILL_EXIT_CODE)


def hard_exit() -> None:
    """``os._exit(KILL_EXIT_CODE)`` — the injector's crash primitive,
    shared by kill faults and the journal's torn-append path.  Lives here
    because CT006 allows ``os._exit`` only in this module: everywhere
    else it would skip the drain protocol's flushes."""
    os._exit(KILL_EXIT_CODE)


# -- module-level singleton ---------------------------------------------------

_injector: Optional[FaultInjector] = None
_singleton_lock = threading.Lock()


def _load_env_config() -> Dict[str, Any]:
    raw = os.environ.get(ENV_VAR, "").strip()
    if not raw:
        return {}
    if raw.startswith("{"):
        return json.loads(raw)
    with open(raw) as f:
        return json.load(f)


def get_injector() -> FaultInjector:
    """The process-wide injector; configured lazily from ``CTT_FAULTS``."""
    global _injector
    if _injector is None:
        with _singleton_lock:
            if _injector is None:
                _injector = FaultInjector(_load_env_config())
    return _injector


def configure(config: Optional[Dict[str, Any]]) -> FaultInjector:
    """Install an injector programmatically (tests); pass None to disable."""
    global _injector
    with _singleton_lock:
        _injector = FaultInjector(config)
    return _injector


def reset() -> None:
    """Drop the installed injector; the next ``get_injector`` re-reads the
    environment."""
    global _injector
    with _singleton_lock:
        _injector = None
