"""Serving fleet: a gateway/router + journal-handoff failover
(docs/SERVING.md "Fleet").

PRs 12-15 made the serving story durable, fair, cached, and self-healing
on exactly ONE box.  This module is the fleet layer (ROADMAP item 1):
:class:`FleetGateway` is a lightweight stdlib-HTTP router fronting M
:class:`~cluster_tools_tpu.runtime.server.PipelineServer` processes.

**Placement** is tenant-affine: a tenant sticks to the member that served
it last, so the member's compiled-program cache, decompressed-chunk cache,
and resident device/handoff state keep paying (BENCH_r10's 5.38x
cold-to-warm split is the prize).  When the affine member is dead,
draining, or over its queue cap, placement falls back to the
least-queue-depth member — safe because submission is idempotent per
``(request_id, payload-fingerprint)`` on every member, so a client retry
that lands on a different member can never double-run an acknowledged
request.  When NO member is placeable the gateway answers with its own
typed backpressure (:data:`~cluster_tools_tpu.runtime.admission.
REJECT_FLEET_NO_MEMBER` → 503, :data:`~cluster_tools_tpu.runtime.
admission.REJECT_FLEET_BACKLOG` → 429), attributed in the gateway's
``failures.json`` like every member-side rejection.

**Failover** is a journal handoff.  The gateway health-checks members
(``/healthz`` + heartbeat freshness + pid liveness); when one dies, the
PR-13 journal under its base dir is already a complete, fsync'd record of
every acknowledged request — precisely the primitive that turns
single-server crash-recovery into cross-server failover.  A surviving
member *adopts* the dead member's journal: the gateway takes an exclusive
**adoption claim** (an ``O_CREAT|O_EXCL`` claim file in the dead member's
base dir, ``fu.file_lock`` style with a dead-pid stale-break — exactly one
of N contenders can ever win), then POSTs ``/adopt`` to the adopter, which
folds the peer's journal through the ordinary boot-replay machinery:
completed requests become idempotently-answerable records, acknowledged-
but-incomplete ones re-enter the adopter's queue with their original
tenant/payload and finish bit-identically, with ZERO client resubmission.
The claim file stays behind as the adoption record, so no second server
can ever adopt the same journal (:func:`read_peer_journal` is the only
sanctioned read of a peer's journal, and it refuses without the claim —
ctlint CT012).  With no survivor, a ``spawn`` callback (the fleet CLI
wires one) restarts a member on the dead base dir instead, and plain boot
replay does the rest.

**Gray-failure defense** (docs/SERVING.md "Gray failures"): the dead-member
story above only covers members that are *gone*.  A member that is
alive-but-wedged (SIGSTOP, GC pause, wedged disk) answers nothing yet
trips no pid-death check, and a member *falsely* declared dead can wake
after a survivor adopted its journal.  Three layers close that class:
every outbound HTTP exchange goes through :mod:`.netio` with an explicit
deadline (and the ``net_delay``/``net_drop``/``net_wedge`` fault shim); a
per-member :class:`CircuitBreaker` counts consecutive connection-level
failures and shifts traffic off a wedged member within ~one request
deadline (typed :data:`~cluster_tools_tpu.runtime.admission.
REJECT_FLEET_BREAKER` while open, half-open trial after the cooldown),
with **hedged submission** re-routing an idempotent request to a second
member after a p99-derived delay; and every adoption **mints a fence
epoch** (:func:`~cluster_tools_tpu.runtime.journal.mint_fence`, under the
exclusive claim, *before* the journal scan) so a SIGCONT'd zombie's next
journal append or handoff flush raises
:class:`~cluster_tools_tpu.runtime.journal.Fenced` instead of forking the
truth — split-brain is structurally impossible, not merely improbable.

**Lock discipline** (ctlint CT012): ``_placement_lock`` guards pure
bookkeeping — the member table, the tenant-affinity map, the
request-route table, counters.  Every HTTP call, health probe, journal
read, and state-file write happens outside it; one slow member probed
under the placement lock would head-of-line block every submit.

**Scale hooks**: ``fleet_state.json`` (rendered by ``scripts/progress.py``)
aggregates per-member queue depth / replay backlog / scrub pressure from
each member's ``server_state.json``, and :meth:`FleetGateway.
drain_emptiest` SIGTERMs the emptiest member (→ rc 114) for scale-down.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import threading
import time
import uuid
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import function_utils as fu
from . import admission as admission_mod
from . import journal as journal_mod
from . import netio
from . import trace as trace_mod
from .server import ENDPOINT_FILENAME, SERVER_UID, STATE_FILENAME
from .supervision import (
    DrainInterrupt,
    HeartbeatWriter,
    drain_reason,
    drain_requested,
    pid_alive,
    read_heartbeat,
)

GATEWAY_UID = "gateway"

#: the gateway's operator-facing state file (scripts/progress.py fleet view)
FLEET_STATE_FILENAME = "fleet_state.json"

#: the exclusive adoption claim in a dead member's base dir.  Present =
#: this journal's failover fate is decided (an adopter finished it, or a
#: respawn is booting on it); absent = the journal is still its owner's.
CLAIM_FILENAME = "adoption.claim"

#: failures.json resolution recorded for a completed journal adoption
ADOPTION_RESOLUTION = "adopted:journal"

#: adoption events kept in fleet_state.json (oldest dropped)
_MAX_ADOPTION_EVENTS = 64

#: request-id -> member routes kept in memory (oldest pruned; a pruned
#: route degrades to the broadcast lookup, never to a lost answer)
_MAX_ROUTES = 4096


class AdoptionRefused(RuntimeError):
    """A journal adoption that must not proceed: no claim, a claim held
    by someone else, or a self-adoption.  Mapped to HTTP 409 by the
    member's ``/adopt`` handler."""


# -- the adoption claim protocol ----------------------------------------------
#
# Exactly-once semantics, not mutual exclusion: fu.file_lock waits and
# eventually *steals* from a live holder (its callers guard best-effort
# bookkeeping), but two servers replaying one journal would double-run
# acknowledged work — so a live holder is NEVER stolen from here.  Only a
# claim whose recorded holder pid is provably dead on this host is broken
# (atomic rename first: one of N contenders wins the rename, so two can
# never both break the same claim and then break each other's).


def adoption_claim_path(base_dir: str) -> str:
    return os.path.join(os.path.abspath(base_dir), CLAIM_FILENAME)


def read_adoption_claim(base_dir: str) -> Optional[Dict[str, Any]]:
    """The current claim document, or None (unclaimed / torn)."""
    return fu.read_json_if_valid(adoption_claim_path(base_dir))


def acquire_adoption_claim(base_dir: str, by: str,
                           pid: int) -> Optional[Dict[str, Any]]:
    """Try to claim ``base_dir``'s journal for adoption by ``(by, pid)``.

    Returns the claim document on success, None when another holder has
    it (no waiting, no stealing from the living — double adoption is a
    correctness bug, not a liveness problem).  A claim whose holder pid
    is dead on this host is stale-broken and re-contended.
    """
    path = adoption_claim_path(base_dir)
    doc = {
        "by": str(by),
        "pid": int(pid),
        "host": socket.gethostname(),
        "time": trace_mod.walltime(),
        "token": uuid.uuid4().hex,
    }
    payload = json.dumps(doc, sort_keys=True).encode()
    for _ in range(16):  # bounded: each lap is a create attempt or a break
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            held = fu.read_json_if_valid(path)
            if held is None:
                # mid-write or torn: give the writer one beat, re-read;
                # still unreadable -> err on the side of NOT adopting
                time.sleep(0.01)
                held = fu.read_json_if_valid(path)
                if held is None and os.path.exists(path):
                    return None
                if held is None:
                    continue  # holder released between exists and read
            if (
                held.get("host") == socket.gethostname()
                and not pid_alive(held.get("pid", -1))
            ):
                # stale-break on a dead holder: rename first, so exactly
                # one of N contenders wins the break (fu.file_lock idiom)
                grave = f"{path}.stale.{os.getpid()}.{threading.get_ident()}"
                try:
                    os.rename(path, grave)
                    os.unlink(grave)
                except OSError:
                    pass  # another contender broke it first; re-contend
                continue
            return None  # a live holder owns this journal's fate
        try:
            os.write(fd, payload)
            os.fsync(fd)
        finally:
            os.close(fd)
        return doc
    return None


def release_adoption_claim(base_dir: str,
                           doc: Optional[Dict[str, Any]]) -> None:
    """Withdraw OUR claim (adoption attempt failed / respawn finished
    booting).  Token-checked like ``fu.file_lock``'s release: a holder
    whose stale claim was broken must not remove the new holder's claim.
    A claim that *consummated* an adoption is never released — it stays
    behind as the adoption record."""
    path = adoption_claim_path(base_dir)
    cur = fu.read_json_if_valid(path)
    if cur is not None and doc is not None \
            and cur.get("token") == doc.get("token"):
        try:
            os.unlink(path)
        except OSError:
            pass


def verify_adoption_claim(peer_base_dir: str, pid: Optional[int] = None,
                          by: Optional[str] = None) -> Dict[str, Any]:
    """The adopter-side gate: raise :class:`AdoptionRefused` unless a
    claim exists on ``peer_base_dir`` and (when given) names this
    ``pid``/``by`` on this host.  Servers call this before touching a
    peer's journal, so a stray ``/adopt`` (or a second would-be adopter
    racing the winner) can never read a journal it does not own."""
    doc = read_adoption_claim(peer_base_dir)
    if doc is None:
        raise AdoptionRefused(
            f"no adoption claim under {peer_base_dir!r}; "
            "acquire_adoption_claim first"
        )
    if pid is not None and (
        int(doc.get("pid") or -1) != int(pid)
        or doc.get("host") != socket.gethostname()
    ):
        raise AdoptionRefused(
            f"adoption claim on {peer_base_dir!r} is held by "
            f"{doc.get('by')!r} (pid {doc.get('pid')} on "
            f"{doc.get('host')}), not pid {pid} on this host"
        )
    if by is not None and doc.get("by") != by:
        raise AdoptionRefused(
            f"adoption claim on {peer_base_dir!r} names "
            f"{doc.get('by')!r}, not {by!r}"
        )
    return doc


def read_peer_journal(peer_base_dir: str, pid: Optional[int] = None,
                      by: Optional[str] = None) -> List[Dict[str, Any]]:
    """The ONLY sanctioned read of a peer's journal (ctlint CT012):
    verifies the adoption claim, then scans read-only.  Never
    ``Journal.recover()`` on a peer — recover opens for append and
    truncates torn tails, and the dead member's journal must stay
    byte-identical for post-mortems; a torn tail was never acknowledged,
    so ``scan``'s intact prefix is the whole promise."""
    verify_adoption_claim(peer_base_dir, pid=pid, by=by)
    records, _, _ = journal_mod.scan(journal_mod.journal_path(peer_base_dir))
    return records


# -- circuit breaker ----------------------------------------------------------


class CircuitBreaker:
    """Per-member circuit breaker (docs/SERVING.md "Gray failures").

    Counts CONSECUTIVE connection-level failures — timeouts, resets,
    refusals, from data calls and health probes alike — and opens at
    ``threshold``, taking the member out of placement within roughly one
    request deadline (heartbeat staleness needs ``member_stale_s``; a
    wedged-but-alive member never goes pid-dead at all).  After
    ``cooldown_s`` the breaker half-opens: exactly ONE trial call is
    admitted; its success closes the breaker, its failure re-opens and
    restarts the cooldown.  Any success anywhere (including a health
    probe) closes — the member is demonstrably answering again.

    Bookkeeping only, under its own tiny lock; the caller does the IO and
    reports outcomes via :meth:`record` (CT012: never IO under a lock).
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, threshold: int = 2, cooldown_s: float = 2.0):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = max(0.05, float(cooldown_s))
        self._lock = threading.Lock()
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.last_transition = time.monotonic()
        self.opened_total = 0
        self._trial_inflight = False

    def _transition(self, state: str) -> None:
        if state != self.state:
            self.state = state
            self.last_transition = time.monotonic()
            if state == self.OPEN:
                self.opened_total += 1

    def allow(self) -> bool:
        """Data-path gate: True in CLOSED; past the cooldown the caller
        takes the single half-open trial slot (and MUST then
        :meth:`record` the outcome to free it)."""
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN:
                if time.monotonic() - self.last_transition \
                        < self.cooldown_s:
                    return False
                self._transition(self.HALF_OPEN)
                self._trial_inflight = True
                return True
            # HALF_OPEN: one trial at a time
            if self._trial_inflight:
                return False
            self._trial_inflight = True
            return True

    def record(self, ok: bool) -> None:
        """Report one call's connection-level outcome (HTTP answers of
        any status count as ``ok`` — the member is responsive)."""
        with self._lock:
            self._trial_inflight = False
            if ok:
                self.consecutive_failures = 0
                self._transition(self.CLOSED)
            else:
                self.consecutive_failures += 1
                if self.state == self.HALF_OPEN \
                        or self.consecutive_failures >= self.threshold:
                    self._transition(self.OPEN)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self.state,
                "consecutive_failures": int(self.consecutive_failures),
                "since_transition_s": round(
                    time.monotonic() - self.last_transition, 3
                ),
                "opened_total": int(self.opened_total),
            }


# -- the gateway --------------------------------------------------------------


class FleetGateway:
    """The fleet's router: tenant-affinity placement with least-queue
    fallback, member health tracking, journal-handoff failover, typed
    gateway backpressure, and the ``fleet_state.json`` operator view.
    See the module docstring and docs/SERVING.md "Fleet".

    Knobs: ``affinity`` (tenant stickiness on/off), ``health_interval_s``
    (probe cadence), ``member_stale_s`` (heartbeat age past which an
    unreachable member is declared dead), ``max_member_queue`` (per-member
    queued+inflight cap before placement skips it), ``failover``
    (``"adopt"`` = surviving member adopts the journal; ``"respawn"`` =
    always restart on the dead base dir via ``spawn``), ``spawn`` (the
    no-survivor fallback: ``spawn(name, base_dir) -> pid|None``),
    ``breaker_threshold`` / ``breaker_cooldown_s`` (consecutive
    connection failures before a member's circuit opens / seconds before
    the half-open trial), ``hedge`` + ``hedge_min_delay_s`` /
    ``hedge_max_delay_s`` (idempotent-submit hedging and the clamp on
    its p99-derived trigger delay).
    """

    def __init__(
        self,
        base_dir: str,
        member_dirs: List[str],
        host: str = "127.0.0.1",
        port: int = 0,
        affinity: bool = True,
        health_interval_s: float = 1.0,
        member_stale_s: float = 6.0,
        max_member_queue: int = 64,
        call_timeout_s: float = 10.0,
        failover: str = "adopt",
        spawn: Optional[Callable[[str, str], Optional[int]]] = None,
        breaker_threshold: int = 2,
        breaker_cooldown_s: float = 2.0,
        hedge: bool = True,
        hedge_min_delay_s: float = 0.05,
        hedge_max_delay_s: float = 2.0,
        incarnation: int = 1,
    ):
        self.base_dir = os.path.abspath(base_dir)
        os.makedirs(self.base_dir, exist_ok=True)
        self.failures_path = fu.failures_path(self.base_dir)
        self.host = host
        self.port = int(port)
        self.affinity = bool(affinity)
        self.health_interval_s = max(0.05, float(health_interval_s))
        self.member_stale_s = max(0.1, float(member_stale_s))
        self.max_member_queue = max(1, int(max_member_queue))
        self.call_timeout_s = float(call_timeout_s)
        if failover not in ("adopt", "respawn"):
            raise ValueError(f"unknown failover policy {failover!r}")
        self.failover = failover
        self._spawn = spawn
        self.breaker_threshold = max(1, int(breaker_threshold))
        self.breaker_cooldown_s = max(0.05, float(breaker_cooldown_s))
        self.hedge = bool(hedge)
        self.hedge_min_delay_s = max(0.0, float(hedge_min_delay_s))
        self.hedge_max_delay_s = max(
            self.hedge_min_delay_s, float(hedge_max_delay_s)
        )
        #: which gateway life this is — the supervisor bumps it on every
        #: restart, so "incarnation increments exactly once per kill" is
        #: externally checkable from fleet_state.json
        self.incarnation = max(1, int(incarnation))
        self.started_at = trace_mod.walltime()
        #: pure-bookkeeping lock (ctlint CT012): member table, affinity
        #: map, route table, counters — never any IO under it
        self._placement_lock = threading.Lock()
        self._members: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        for i, d in enumerate(member_dirs):
            d = os.path.abspath(d)
            name = os.path.basename(d.rstrip(os.sep)) or f"m{i}"
            if name in self._members:
                name = f"{name}-{i}"
            self._members[name] = {
                "name": name, "base_dir": d, "host": None, "port": 0,
                "pid": None, "hostname": None, "alive": False,
                "ever_alive": False, "dead": False, "draining": False,
                "adopted_by": None, "queued": 0, "inflight": 0,
                "replay_backlog": 0, "scrub": None, "heartbeat_age_s": None,
            }
        if not self._members:
            raise ValueError("a fleet needs at least one member dir")
        self._breakers: Dict[str, CircuitBreaker] = {
            n: CircuitBreaker(self.breaker_threshold,
                              self.breaker_cooldown_s)
            for n in self._members
        }
        #: recent successful submit latencies (s) — the hedge delay is
        #: their p99, clamped to [hedge_min_delay_s, hedge_max_delay_s]
        self._submit_latencies: deque = deque(maxlen=128)
        self._hedge_stats = {
            "launched": 0, "won_primary": 0, "won_secondary": 0,
        }
        self._affinity_map: Dict[str, str] = {}
        self._affinity_hits = 0
        self._affinity_misses = 0
        self._affinity_cold = 0
        self._routes: "OrderedDict[str, str]" = OrderedDict()
        self._rejections: Dict[str, int] = {}
        self._adoptions: List[Dict[str, Any]] = []
        self._adopting: set = set()
        self._reject_seq = 0
        self._draining = False
        self._stop = threading.Event()
        self._heartbeat: Optional[HeartbeatWriter] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._health_thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "FleetGateway":
        """One synchronous member sweep (so the first submit already sees
        live members), then bind, start the health loop + heartbeat, and
        write the endpoint file — the same ``server.json`` contract as a
        member, so ``ServeClient.from_endpoint_file(gateway_dir)`` routes
        through the gateway unchanged.

        A restarted gateway (the supervisor's crash-only contract) calls
        :meth:`_rebuild_from_disk` first: routes, affinity, adoption
        bookkeeping, and the dead-member grace all come back from what is
        durably on disk, so incarnation N+1 serves exactly what N
        acknowledged."""
        self._rebuild_from_disk()
        self._check_members()
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          _GatewayHandler)
        self._httpd.gateway = self  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-http", daemon=True,
        )
        self._http_thread.start()
        self._heartbeat = HeartbeatWriter(
            self.base_dir, GATEWAY_UID, interval_s=2.0
        ).start()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="fleet-health", daemon=True,
        )
        self._health_thread.start()
        fu.atomic_write_json(
            os.path.join(self.base_dir, ENDPOINT_FILENAME),
            {
                "host": self.host,
                "port": self.port,
                "pid": os.getpid(),
                "hostname": socket.gethostname(),
                "time": trace_mod.walltime(),
                "role": "gateway",
                "incarnation": self.incarnation,
            },
        )
        self._write_state()
        return self

    def _rebuild_from_disk(self) -> None:
        """Cold-start state rebuild (docs/SERVING.md "Supervision"): the
        gateway is crash-only, so everything it routes by must be
        recoverable from member truth — endpoint files, each member's
        ``server_state.json``, and the adoption claims.  The previous
        incarnation's ``fleet_state.json`` is a HINT at most (it breaks
        affinity ties); a stale or torn copy is never trusted over what
        the members themselves say.

        Rebuilt here: ``ever_alive`` (a member with an endpoint file has
        booted once, so its death is detectable — without this a
        restarted gateway would wait out the cold-boot grace and never
        adopt an already-dead member), ``adopted_by`` (consumed adoption
        claims whose ``by`` names a peer, not a ``respawn:`` holder),
        the tenant-affinity map, and the request route table."""
        with self._placement_lock:
            snaps = [(n, m["base_dir"]) for n, m in self._members.items()]
        names = {n for n, _ in snaps}
        hint = fu.read_json_if_valid(
            os.path.join(self.base_dir, FLEET_STATE_FILENAME)
        ) or {}
        hint_aff = dict(((hint.get("affinity") or {}).get("map") or {}))
        # all file IO outside the placement lock (ctlint CT012)
        ever: set = set()
        adopted: Dict[str, str] = {}
        tenant_seen: Dict[str, List[Tuple[int, str]]] = {}
        routes_terminal: List[Tuple[str, str]] = []
        routes_open: List[Tuple[str, str]] = []
        for name, base in snaps:
            if fu.read_json_if_valid(
                os.path.join(base, ENDPOINT_FILENAME)
            ) is not None:
                ever.add(name)
            claim = read_adoption_claim(base)
            by = str((claim or {}).get("by") or "")
            if by and not by.startswith("respawn:") and by != name:
                adopted[name] = by
            state = fu.read_json_if_valid(
                os.path.join(base, STATE_FILENAME)
            ) or {}
            for tenant, t in (state.get("tenants") or {}).items():
                if int(t.get("submitted") or 0) > 0:
                    tenant_seen.setdefault(tenant, []).append(
                        (int(t["submitted"]), name)
                    )
            for rid, rec in (state.get("requests") or {}).items():
                if rec.get("state") in journal_mod.TERMINAL_TYPES or (
                    rec.get("state") == journal_mod.DRAINED
                ):
                    routes_terminal.append((rid, name))
                else:
                    routes_open.append((rid, name))

        def owner(name: str) -> str:
            # follow the adoption chain so rebuilt routes/affinity point
            # at whoever holds the journal now
            hops = 0
            while name in adopted and hops < len(names) + 1:
                name = adopted[name]
                hops += 1
            return name

        affinity: Dict[str, str] = {}
        for tenant, cands in tenant_seen.items():
            hinted = hint_aff.get(tenant)
            if hinted in {owner(n) for _, n in cands}:
                affinity[tenant] = hinted  # hint breaks the tie, no more
            else:
                cands.sort(key=lambda c: (-c[0], c[1]))
                affinity[tenant] = owner(cands[0][1])
        with self._placement_lock:
            for name in names:
                m = self._members.get(name)
                if m is None:
                    continue
                if name in ever:
                    m["ever_alive"] = True
                if name in adopted:
                    m["adopted_by"] = adopted[name]
            for tenant, name in affinity.items():
                if name in self._members:
                    self._affinity_map.setdefault(tenant, name)
            # terminal routes first: the FIFO route-table trim evicts
            # oldest-inserted, so open requests survive the cap
            for rid, name in routes_terminal + routes_open:
                name = owner(name)
                if name in self._members:
                    self._routes[rid] = name
            while len(self._routes) > _MAX_ROUTES:
                self._routes.popitem(last=False)

    # -- membership (the supervisor's scale/respawn hooks) -----------------
    def add_member(self, name: str, base_dir: str) -> Optional[Dict]:
        """Register a new member (scale-up, or respawned capacity on a
        fresh dir).  The dir may be empty — the member is "starting"
        until its first healthy probe, so registration never trips a
        spurious adoption.  Returns the member doc, or None when the
        name is taken."""
        base_dir = os.path.abspath(base_dir)
        os.makedirs(base_dir, exist_ok=True)
        with self._placement_lock:
            if name in self._members:
                return None
            self._members[name] = {
                "name": name, "base_dir": base_dir, "host": None,
                "port": 0, "pid": None, "hostname": None, "alive": False,
                "ever_alive": False, "dead": False, "draining": False,
                "adopted_by": None, "queued": 0, "inflight": 0,
                "replay_backlog": 0, "scrub": None, "heartbeat_age_s": None,
            }
            self._breakers[name] = CircuitBreaker(
                self.breaker_threshold, self.breaker_cooldown_s
            )
            doc = dict(self._members[name])
        trace_mod.instant("fleet.member_added", member=name)
        self._write_state()
        return doc

    def retire_member(self, name: str) -> bool:
        """Drop a member from the table: scale-down after its drain, or
        an adopted-away dir whose capacity respawned elsewhere.  Refused
        for a live, unadopted, undraining member — capacity never
        vanishes silently.  The tenant re-places on next submit; routes
        to an adopted journal were already remapped at adoption time."""
        with self._placement_lock:
            m = self._members.get(name)
            if m is None:
                return False
            if m["alive"] and not m["draining"] and not m.get("adopted_by"):
                return False
            del self._members[name]
            self._breakers.pop(name, None)
            self._adopting.discard(name)
            for tenant, o in list(self._affinity_map.items()):
                if o == name:
                    del self._affinity_map[tenant]
            for rid, o in list(self._routes.items()):
                if o == name:
                    del self._routes[rid]
        trace_mod.instant("fleet.member_retired", member=name)
        self._write_state()
        return True

    def serve_until_drained(self, poll_s: float = 0.2) -> None:
        """Block until the drain latch flips (SIGTERM/SIGUSR1), then stop
        routing and raise :class:`DrainInterrupt` for the entry point to
        map to ``REQUEUE_EXIT_CODE`` — the fleet CLI drains the members
        behind the same signal (docs/SERVING.md "Fleet")."""
        while not drain_requested():
            time.sleep(poll_s)
        self._draining = True
        self._write_state()
        self._teardown()
        raise DrainInterrupt(drain_reason() or "drain requested")

    def stop(self) -> None:
        """Cooperative shutdown for embedders/tests (no drain
        semantics)."""
        self._draining = True
        self._write_state()
        self._teardown()

    def _teardown(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(
                timeout=2 * self.health_interval_s + 5.0
            )
        if self._heartbeat is not None:
            self._heartbeat.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    # -- member HTTP (never under the placement lock) ----------------------
    def _breaker_for(self, name: Optional[str]) -> Optional[CircuitBreaker]:
        if not name:
            return None
        with self._placement_lock:
            br = self._breakers.get(name)
            if br is None:
                br = self._breakers[name] = CircuitBreaker(
                    self.breaker_threshold, self.breaker_cooldown_s
                )
        return br

    def _member_call(self, member: Dict[str, Any], method: str, path: str,
                     body: Optional[Dict[str, Any]] = None,
                     timeout_s: Optional[float] = None,
                     site: str = "net_member") -> Tuple[int, Dict]:
        """One deadline-bounded exchange with a member via :mod:`.netio`
        (fault sites ``net_member`` / ``net_probe``), reporting the
        connection-level outcome to the member's circuit breaker — any
        HTTP answer counts as responsive, only timeouts/resets/refusals
        count against it."""
        name = member.get("name")
        br = self._breaker_for(name)
        try:
            status, doc = netio.http_json_call(
                member["host"], int(member["port"]), method, path, body,
                timeout_s=float(timeout_s if timeout_s is not None
                                else self.call_timeout_s),
                site=site, member=name,
            )
        except (OSError, ValueError):
            if br is not None:
                br.record(False)
            raise
        if br is not None:
            br.record(True)
        return status, doc

    # -- health ------------------------------------------------------------
    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_interval_s):
            try:
                self._check_members()
            except Exception:
                pass  # the health loop must outlive one flaky probe

    def _check_members(self) -> None:
        with self._placement_lock:
            names = list(self._members)
        newly_dead = []
        for name in names:
            with self._placement_lock:
                snap = dict(self._members[name])
            update = self._probe_member(snap)  # all IO outside the lock
            with self._placement_lock:
                m = self._members.get(name)
                if m is None:
                    continue
                m.update(update)
                if (
                    m["dead"] and m.get("adopted_by") is None
                    and name not in self._adopting
                ):
                    newly_dead.append(name)
        for name in newly_dead:
            self._failover(name)
        self._write_state()

    def _probe_member(self, m: Dict[str, Any]) -> Dict[str, Any]:
        """One member's health snapshot: endpoint + /healthz + heartbeat
        age + pid liveness + the queue/replay/scrub pressure from its
        ``server_state.json``.  Dead = unreachable AND (pid provably dead,
        or heartbeat stale past ``member_stale_s``) — a member that has
        simply not booted yet (never seen alive) is "starting", not dead,
        so a slow cold boot never triggers a spurious adoption."""
        base = m["base_dir"]
        ep = fu.read_json_if_valid(
            os.path.join(base, ENDPOINT_FILENAME)
        ) or {}
        host = ep.get("host") or m.get("host")
        port = int(ep.get("port") or m.get("port") or 0)
        pid = ep.get("pid") or m.get("pid")
        hostname = ep.get("hostname") or m.get("hostname")
        ok, health = False, {}
        if host and port:
            try:
                status, health = self._member_call(
                    {"name": m.get("name"), "host": host, "port": port},
                    "GET", "/healthz",
                    timeout_s=min(2.0, max(0.2, self.member_stale_s / 2)),
                    site="net_probe",
                )
                ok = status == 200
            except (OSError, ValueError):
                ok = False
        hb = read_heartbeat(base, SERVER_UID) or {}
        hb_age = None
        if hb.get("time") is not None:
            hb_age = max(0.0, trace_mod.walltime() - float(hb["time"]))
        state = fu.read_json_if_valid(os.path.join(base, STATE_FILENAME))
        state = state or {}
        queued = inflight = 0
        for t in (state.get("tenants") or {}).values():
            queued += int(t.get("queued") or 0)
            inflight += int(t.get("inflight") or 0)
        journal = state.get("journal") or {}
        sc = state.get("scrub") or {}
        pid_dead = bool(
            pid
            and hostname == socket.gethostname()
            and int(pid) != os.getpid()
            and not pid_alive(pid)
        )
        hb_stale = hb_age is None or hb_age > self.member_stale_s
        ever = bool(m.get("ever_alive")) or ok
        return {
            "host": host, "port": port, "pid": pid, "hostname": hostname,
            "alive": ok,
            "ever_alive": ever,
            "dead": (not ok) and ever and (pid_dead or hb_stale),
            "draining": (
                bool(health.get("draining")) if ok else m.get("draining")
            ),
            "queued": queued,
            "inflight": inflight,
            "replay_backlog": int(journal.get("replay_backlog") or 0),
            "scrub": (
                {k: sc.get(k) for k in ("passes", "found_corrupt",
                                        "repaired", "unrepairable")}
                if sc else None
            ),
            "heartbeat_age_s": (
                round(hb_age, 3) if hb_age is not None else None
            ),
        }

    # -- failover ----------------------------------------------------------
    def _failover(self, name: str) -> None:
        """One dead member's journal handoff: claim exclusively, then let
        the least-loaded survivor adopt (or respawn when there is none).
        Re-entered by every health tick until the member is adopted, so a
        failed attempt (adopter crashed mid-adopt, claim released) is
        retried instead of abandoned."""
        with self._placement_lock:
            m = self._members.get(name)
            if (
                m is None or m.get("adopted_by") is not None
                or name in self._adopting
            ):
                return
            self._adopting.add(name)
            dead = dict(m)
            survivors = [
                dict(x) for x in self._members.values()
                if x["name"] != name and x["alive"] and not x["draining"]
                and x.get("adopted_by") is None
            ]
        try:
            if self.failover == "respawn" or not survivors:
                self._respawn_failover(dead)
                return
            adopter = min(
                survivors,
                key=lambda x: (x["queued"] + x["inflight"], x["name"]),
            )
            claim = acquire_adoption_claim(
                dead["base_dir"], by=adopter["name"], pid=adopter["pid"],
            )
            if claim is None:
                # someone else (a racing gateway / a respawn) owns this
                # journal's fate; never double-adopt
                trace_mod.instant(
                    "fleet.adopt_contended", member=name,
                )
                return
            # fence FIRST, scan after: minting a higher epoch under the
            # exclusive claim means the old incarnation — even one merely
            # wedged, not dead — can never append another journal byte or
            # flush another store (Journal.append and the server's flush
            # path re-check the epoch and raise Fenced).  The adopter's
            # journal scan below therefore reads the complete, FINAL
            # record of the member's promises: split-brain is closed
            # before any peer byte is read.
            fence_epoch = journal_mod.mint_fence(
                dead["base_dir"], by=f"adopt:{adopter['name']}",
            )
            try:
                status, doc = self._member_call(
                    adopter, "POST", "/adopt",
                    {"base_dir": dead["base_dir"]},
                )
            except (OSError, ValueError):
                status, doc = 0, {}
            if status != 200:
                # adoption did not happen: withdraw so the next tick (or
                # another contender) can retry against a clean slate
                release_adoption_claim(dead["base_dir"], claim)
                return
            event = {
                "time": trace_mod.walltime(),
                "kind": "adopt",
                "member": name,
                "adopter": adopter["name"],
                "fence_epoch": fence_epoch,
                "completed": int(doc.get("completed") or 0),
                "reenqueued": int(doc.get("reenqueued") or 0),
                "quarantined": int(doc.get("quarantined") or 0),
            }
            with self._placement_lock:
                dm = self._members.get(name)
                if dm is not None:
                    dm["adopted_by"] = adopter["name"]
                for rid, owner in list(self._routes.items()):
                    if owner == name:
                        self._routes[rid] = adopter["name"]
                for tenant, owner in list(self._affinity_map.items()):
                    if owner == name:
                        self._affinity_map[tenant] = adopter["name"]
                self._adoptions.append(event)
                del self._adoptions[:-_MAX_ADOPTION_EVENTS]
                self._reject_seq += 1
                seq = self._reject_seq
            trace_mod.instant(
                "fleet.adopt", member=name, adopter=adopter["name"],
                reenqueued=event["reenqueued"], completed=event["completed"],
                fence_epoch=fence_epoch,
            )
            try:
                fu.record_failures(
                    self.failures_path,
                    "fleet.failover",
                    [{
                        "block_id": f"adopt:{name}:{seq}",
                        "sites": {"failover": 1},
                        "error": (
                            f"member {name} died; journal adopted by "
                            f"{adopter['name']}"
                        ),
                        "quarantined": False,
                        "resolved": True,
                        "resolution": ADOPTION_RESOLUTION,
                        "member": name,
                        "adopter": adopter["name"],
                    }],
                )
            except Exception:
                pass  # attribution is best-effort; the adoption stands
            self._write_state()
        finally:
            self._adopting.discard(name)

    def _respawn_failover(self, dead: Dict[str, Any]) -> None:
        """No survivor (or ``failover='respawn'``): restart a server on
        the dead base dir — its own boot replay finishes the acknowledged
        work.  The claim is held across the spawn so a late-arriving
        survivor cannot adopt a journal a fresh server is booting on, and
        released after (the new incarnation owns its journal again)."""
        if self._spawn is None:
            return
        name = dead["name"]
        claim = acquire_adoption_claim(
            dead["base_dir"], by=f"respawn:{name}", pid=os.getpid(),
        )
        if claim is None:
            return
        # fence the old incarnation before the new one boots: a wedged
        # predecessor waking mid-respawn must not interleave appends with
        # its successor.  The fresh server reads the bumped epoch at boot
        # and owns the journal under it.
        fence_epoch = journal_mod.mint_fence(
            dead["base_dir"], by=f"respawn:{name}",
        )
        try:
            pid = self._spawn(name, dead["base_dir"])
        finally:
            release_adoption_claim(dead["base_dir"], claim)
        if pid is None:
            return
        event = {
            "time": trace_mod.walltime(),
            "kind": "respawn",
            "member": name,
            "pid": int(pid),
            "fence_epoch": fence_epoch,
        }
        with self._placement_lock:
            m = self._members.get(name)
            if m is not None:
                m["pid"] = int(pid)
                m["dead"] = False
                m["ever_alive"] = False  # re-arm the cold-boot grace
            self._adoptions.append(event)
            del self._adoptions[:-_MAX_ADOPTION_EVENTS]
            self._reject_seq += 1
            seq = self._reject_seq
        trace_mod.instant("fleet.respawn", member=name, pid=int(pid))
        try:
            fu.record_failures(
                self.failures_path,
                "fleet.respawn",
                [{
                    "block_id": f"respawn:{name}:{seq}",
                    "sites": {"failover": 1},
                    "error": (
                        f"member {name} died with no adoptable survivor; "
                        f"respawned on its own dir as pid {int(pid)}"
                    ),
                    "quarantined": False,
                    "resolved": True,
                    "resolution": "respawned:own_journal",
                    "member": name,
                }],
            )
        except Exception:
            pass  # attribution is best-effort; the respawn stands
        self._write_state()

    # -- placement ---------------------------------------------------------
    def _place(self, tenant: str, exclude=()) -> Tuple[
            Optional[Dict[str, Any]], Optional[str], bool]:
        """Pick a member for one submission: the tenant's affine member
        when placeable (warm caches pay), else least queue depth (and the
        affinity map follows — the tenant sticks to wherever it lands).
        Returns ``(member, reject_code, affinity_hit)``.  Pure
        bookkeeping under the placement lock (ctlint CT012)."""
        with self._placement_lock:
            usable = [
                m for m in self._members.values()
                if m["alive"] and not m["draining"]
                and m.get("adopted_by") is None
                and m["name"] not in exclude
            ]
            if not usable:
                return None, admission_mod.REJECT_FLEET_NO_MEMBER, False
            placeable = [
                m for m in usable
                if m["queued"] + m["inflight"] < self.max_member_queue
            ]
            if not placeable:
                return None, admission_mod.REJECT_FLEET_BACKLOG, False
            want = (
                self._affinity_map.get(tenant) if self.affinity else None
            )
            target, hit = None, False
            for m in placeable:
                if m["name"] == want:
                    target, hit = m, True
                    break
            if target is None:
                target = min(
                    placeable,
                    key=lambda m: (m["queued"] + m["inflight"], m["name"]),
                )
            if self.affinity:
                self._affinity_map[tenant] = target["name"]
            if hit:
                self._affinity_hits += 1
            elif want is None:
                # a first-touch tenant has no affine member to hit — that
                # is a cold pin, not a miss.  Counting it as a miss let
                # new-tenant probe bursts (the bench's heal phase) drag
                # hit_rate down without any affinity ever being broken
                # (the BENCH_r13 0.89 → r15 0.75 investigation).
                self._affinity_cold += 1
            else:
                self._affinity_misses += 1
            return dict(target), None, hit

    def _hedge_delay(self) -> float:
        """The hedge trigger: p99 of recent successful submit latencies,
        clamped to [hedge_min_delay_s, hedge_max_delay_s] — too few
        samples and the max applies (hedge rarely until the tail is
        known)."""
        with self._placement_lock:
            lats = sorted(self._submit_latencies)
        if len(lats) >= 8:
            delay = lats[min(len(lats) - 1, int(0.99 * len(lats)))]
        else:
            delay = self.hedge_max_delay_s
        return min(self.hedge_max_delay_s,
                   max(self.hedge_min_delay_s, delay))

    def _submit_hedged(
        self, member: Dict[str, Any], payload: Dict[str, Any],
        tenant: str, tried: set,
    ) -> Tuple[int, Dict[str, Any], str]:
        """One placement's submit with a hedge: the primary call runs in
        a helper thread; past the p99-derived delay with no answer, the
        same request is re-routed to a second member, and the first 200
        wins.  Safe ONLY for requests carrying an explicit ``request_id``
        (the caller gates on that): every member dedupes on
        ``(request_id, payload-fingerprint)``, and an adopted journal
        skips already-known ids, so the loser is answered idempotently,
        never double-run.  Returns ``(status, doc, via_member_name)`` or
        raises the connection error when neither side answered."""
        box: Dict[str, Any] = {}
        done = threading.Event()

        def call_primary() -> None:
            try:
                box["res"] = self._member_call(
                    member, "POST", "/submit", payload,
                )
            except (OSError, ValueError) as e:
                box["err"] = e
            finally:
                done.set()

        threading.Thread(
            target=call_primary, name="fleet-hedge-primary", daemon=True,
        ).start()
        if done.wait(self._hedge_delay()):
            if "res" in box:
                st, doc = box["res"]
                return st, doc, member["name"]
            raise box["err"]
        # the primary is past p99 with no answer — the wedge signature.
        second, _code, _hit = self._place(
            tenant, exclude=set(tried) | {member["name"]},
        )
        if second is not None:
            br = self._breaker_for(second["name"])
            if br is not None and not br.allow():
                second = None
        if second is None:
            # nowhere to hedge: wait out the primary's own deadline
            done.wait(self.call_timeout_s + 1.0)
            if "res" in box:
                st, doc = box["res"]
                return st, doc, member["name"]
            raise box.get("err") or TimeoutError(
                f"{member['name']}: no answer within the deadline"
            )
        with self._placement_lock:
            self._hedge_stats["launched"] += 1
        trace_mod.instant(
            "fleet.hedge", tenant=tenant, primary=member["name"],
            secondary=second["name"],
        )
        try:
            st2, doc2 = self._member_call(
                second, "POST", "/submit", payload,
            )
        except (OSError, ValueError):
            st2, doc2 = None, None
        if st2 == 200:
            with self._placement_lock:
                self._hedge_stats["won_secondary"] += 1
            return st2, doc2, second["name"]
        # the secondary could not win either — fall back to the primary
        done.wait(self.call_timeout_s + 1.0)
        if "res" in box:
            st, doc = box["res"]
            with self._placement_lock:
                self._hedge_stats["won_primary"] += 1
            return st, doc, member["name"]
        if st2 is not None:
            return st2, doc2, second["name"]  # the typed answer we have
        raise box.get("err") or TimeoutError(
            f"{member['name']}: no answer within the deadline"
        )

    def submit(self, payload: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        """Route one submission: place, forward, record the route.  A
        member behind an OPEN circuit breaker is skipped without a call
        (all skipped → typed ``rejected:fleet_breaker_open``); a member
        that drops the connection mid-submit is marked suspect and the
        next member tried (idempotency makes the ambiguous retry safe);
        a request with an explicit ``request_id`` is hedged to a second
        member past the p99 delay; typed member rejections pass through
        verbatim; when no member is placeable the gateway's own typed
        backpressure answers (``rejected:fleet_*``)."""
        tenant = str(payload.get("tenant") or "default")
        if self._draining or drain_requested():
            return self._reject(
                tenant, admission_mod.REJECT_DRAINING, "gateway draining",
            )
        hedgeable = bool(self.hedge and payload.get("request_id"))
        tried: set = set()
        last_err = ""
        breaker_blocked = False
        with self._placement_lock:
            n_members = len(self._members)
        for _ in range(n_members):
            member, code, _hit = self._place(tenant, exclude=tried)
            if member is None:
                if breaker_blocked \
                        and code == admission_mod.REJECT_FLEET_NO_MEMBER:
                    code = admission_mod.REJECT_FLEET_BREAKER
                return self._reject(
                    tenant, code,
                    last_err or ("circuit breaker open"
                                 if breaker_blocked else ""),
                )
            br = self._breaker_for(member["name"])
            if br is not None and not br.allow():
                tried.add(member["name"])
                breaker_blocked = True
                continue
            t0 = time.monotonic()
            try:
                if hedgeable:
                    status, doc, via = self._submit_hedged(
                        member, payload, tenant, tried,
                    )
                else:
                    status, doc = self._member_call(
                        member, "POST", "/submit", payload,
                    )
                    via = member["name"]
            except (OSError, ValueError) as e:
                tried.add(member["name"])
                last_err = f"{member['name']}: {e}"
                with self._placement_lock:
                    live = self._members.get(member["name"])
                    if live is not None:
                        live["alive"] = False  # suspect; health confirms
                continue
            if status == 200 and doc.get("request_id"):
                rid = str(doc["request_id"])
                with self._placement_lock:
                    self._submit_latencies.append(time.monotonic() - t0)
                    self._routes[rid] = via
                    while len(self._routes) > _MAX_ROUTES:
                        self._routes.popitem(last=False)
                    live = self._members.get(via)
                    if live is not None:
                        # provisional until the next probe refreshes it:
                        # keeps least-queue placement honest in bursts
                        live["queued"] += 1
                doc = dict(doc)
                doc["member"] = via
                return status, doc
            return status, doc  # the member's typed answer, verbatim
        if breaker_blocked and not last_err:
            return self._reject(
                tenant, admission_mod.REJECT_FLEET_BREAKER,
                "every placeable member behind an open breaker",
            )
        return self._reject(
            tenant, admission_mod.REJECT_FLEET_NO_MEMBER,
            f"every member unreachable; last: {last_err}",
        )

    def _reject(self, tenant: str, code: str,
                detail: str = "") -> Tuple[int, Dict[str, Any]]:
        """Typed gateway backpressure, attributed exactly like a member's
        rejection (failures.json + trace instant), outside all locks."""
        with self._placement_lock:
            self._reject_seq += 1
            seq = self._reject_seq
            self._rejections[code] = self._rejections.get(code, 0) + 1
        try:
            fu.record_failures(
                self.failures_path,
                f"fleet.{tenant}",
                [{
                    "block_id": f"route:{tenant}:{os.getpid()}:{seq}",
                    "sites": {"route": 1},
                    "error": detail or None,
                    "quarantined": False,
                    "resolved": True,
                    "resolution": code,
                    "tenant": tenant,
                }],
            )
        except Exception:
            pass  # attribution is best-effort; the rejection stands
        trace_mod.instant("fleet.reject", tenant=tenant, code=code)
        self._write_state()
        http = 503 if code in (
            admission_mod.REJECT_DRAINING,
            admission_mod.REJECT_FLEET_NO_MEMBER,
            admission_mod.REJECT_FLEET_BREAKER,
        ) else 429
        return http, {"error": code, "tenant": tenant, "detail": detail}

    # -- lookup ------------------------------------------------------------
    def lookup(self, request_id: str) -> Tuple[int, Dict[str, Any]]:
        """Find a request's record: the routed owner first (post-failover
        routes already point at the adopter), then every live member (a
        gateway restart loses the route table, the broadcast does not
        lose answers).  A known owner that nobody can answer for is the
        failover window: a typed 503 the client's ``wait`` backs off on,
        never a terminal-looking document."""
        with self._placement_lock:
            owner = self._routes.get(request_id)
            members = [dict(m) for m in self._members.values()]
        ordered = [m for m in members if m["name"] == owner]
        ordered += [
            m for m in members
            if m["alive"] and m["name"] != owner
        ]
        seen_answer = False
        for m in ordered:
            if not (m["alive"] or m["name"] == owner):
                continue
            try:
                status, doc = self._member_call(
                    m, "GET", f"/request/{request_id}",
                )
            except (OSError, ValueError):
                continue
            if status == 200:
                return 200, doc
            seen_answer = True
        if owner is not None and not seen_answer:
            return 503, {
                "error": admission_mod.REJECT_FLEET_NO_MEMBER,
                "request_id": request_id,
                "detail": (
                    f"owner {owner} unreachable; journal adoption pending"
                ),
            }
        return 404, {"error": "unknown_request"}

    # -- drain policy ------------------------------------------------------
    def drain_emptiest(
        self, member: Optional[str] = None,
    ) -> Optional[Dict[str, Any]]:
        """The scale-down hook: SIGTERM the emptiest live member (or the
        named one) so it drains through the standard protocol — in-flight
        work finishes, queued work stays journaled, the process exits
        ``REQUEUE_EXIT_CODE`` (114).  Returns the chosen member, or None
        when nothing is drainable."""
        with self._placement_lock:
            candidates = [
                dict(m) for m in self._members.values()
                if m["alive"] and not m["draining"]
                and m.get("adopted_by") is None
                and (member is None or m["name"] == member)
            ]
            if not candidates:
                return None
            target = min(
                candidates,
                key=lambda m: (m["queued"] + m["inflight"], m["name"]),
            )
            live = self._members.get(target["name"])
            if live is not None:
                live["draining"] = True
        pid = target.get("pid")
        delivered = False
        if (
            pid and int(pid) != os.getpid()
            and target.get("hostname") == socket.gethostname()
        ):
            try:
                os.kill(int(pid), signal.SIGTERM)
                delivered = True
            except OSError:
                delivered = False
        trace_mod.instant(
            "fleet.drain", member=target["name"],
            pid=int(pid) if pid else 0,
        )
        try:
            fu.record_failures(
                self.failures_path,
                "fleet.drain",
                [{
                    "block_id": f"drain:{target['name']}",
                    "sites": {},
                    "error": (
                        f"member {target['name']} drained (scale-down)"
                    ),
                    "quarantined": False,
                    "resolved": True,
                    "resolution": "drained:scale_down",
                    "member": target["name"],
                }],
            )
        except Exception:
            pass  # attribution is best-effort; the drain stands
        self._write_state()
        return {
            "member": target["name"],
            "pid": pid,
            "signalled": delivered,
        }

    # -- introspection -----------------------------------------------------
    def _state_doc(self) -> Dict[str, Any]:
        with self._placement_lock:
            members = {
                n: {
                    k: m.get(k)
                    for k in ("base_dir", "host", "port", "pid", "hostname",
                              "alive", "ever_alive", "dead", "draining",
                              "adopted_by", "queued", "inflight",
                              "replay_backlog", "scrub", "heartbeat_age_s")
                }
                for n, m in self._members.items()
            }
            hits, misses = self._affinity_hits, self._affinity_misses
            cold_pins = self._affinity_cold
            affinity_map = dict(self._affinity_map)
            adoptions = list(self._adoptions)
            rejections = dict(self._rejections)
            n_routes = len(self._routes)
            breakers = dict(self._breakers)
            hedge_stats = dict(self._hedge_stats)
        # breaker snapshots + fence epochs OUTSIDE the placement lock:
        # each breaker has its own lock, and the fence read is file IO
        for n, m in members.items():
            br = breakers.get(n)
            m["breaker"] = br.snapshot() if br is not None else None
            m["fence_epoch"] = int(
                journal_mod.read_fence(m["base_dir"])["epoch"]
            )
        total = hits + misses
        return {
            "version": 1,
            "role": "gateway",
            "uid": GATEWAY_UID,
            "incarnation": self.incarnation,
            "pid": os.getpid(),
            "hostname": socket.gethostname(),
            "host": self.host,
            "port": self.port,
            "time": trace_mod.walltime(),
            "started": self.started_at,
            "draining": self._draining or drain_requested(),
            "failover": self.failover,
            "members": members,
            "affinity": {
                "enabled": self.affinity,
                "hits": hits,
                "misses": misses,
                # first-ever placements: no affinity existed to hit or
                # break, reported separately so probe-tenant bursts don't
                # pollute hit_rate's denominator
                "cold_pins": cold_pins,
                "hit_rate": round(hits / total, 4) if total else None,
                "map": affinity_map,
            },
            "routes": n_routes,
            "rejections": rejections,
            "adoptions": adoptions,
            "hedge": {
                "enabled": self.hedge,
                "delay_s": round(self._hedge_delay(), 4),
                **{k: int(v) for k, v in hedge_stats.items()},
            },
            "dead_unadopted": sorted(
                n for n, m in members.items()
                if m.get("dead") and not m.get("adopted_by")
            ),
        }

    def _write_state(self) -> None:
        """Atomically refresh ``fleet_state.json`` — the file the
        ``scripts/progress.py`` fleet view renders.  Best-effort; the
        gateway must outlive a full disk."""
        try:
            fu.atomic_write_json(
                os.path.join(self.base_dir, FLEET_STATE_FILENAME),
                self._state_doc(),
            )
        except OSError:
            pass

    def status(self) -> Dict[str, Any]:
        """The ``/status`` document: the fleet state plus an ``rc`` that
        preserves the operator contract — 1 when a member is dead and
        unadopted (acknowledged requests are stranded until the failover
        completes)."""
        doc = self._state_doc()
        return {"fleet": doc, "rc": 1 if doc["dead_unadopted"] else 0}

    def healthz(self) -> Dict[str, Any]:
        doc = self._state_doc()
        return {
            "ok": True,
            "role": "gateway",
            "incarnation": doc["incarnation"],
            "draining": doc["draining"],
            "members": {
                n: {
                    k: m.get(k)
                    for k in ("alive", "dead", "draining", "adopted_by",
                              "queued", "inflight", "replay_backlog",
                              "breaker", "fence_epoch")
                }
                for n, m in doc["members"].items()
            },
            "affinity": doc["affinity"],
            "dead_unadopted": doc["dead_unadopted"],
        }


# -- HTTP plumbing ------------------------------------------------------------


class _GatewayHandler(BaseHTTPRequestHandler):
    """The gateway's JSON-over-HTTP surface, a superset-shape of the
    member handler so existing clients work unchanged: POST /submit,
    GET /status, GET /request/<id>, GET /healthz, plus the fleet-only
    POST /drain (the scale-down hook) and POST /members (the
    supervisor's add/retire membership hooks)."""

    server_version = "ctt-fleet/1"

    @property
    def gateway(self) -> FleetGateway:
        return self.server.gateway  # type: ignore[attr-defined]

    def log_message(self, fmt, *args):  # quiet: the state file is the log
        pass

    def _reply(self, code: int, doc: Dict[str, Any]) -> None:
        body = json.dumps(doc).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.rstrip("/")
        try:
            length = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, OSError) as e:
            self._reply(400, {"error": "bad_request", "detail": str(e)})
            return
        if path == "/submit":
            status, doc = self.gateway.submit(payload)
            self._reply(status, doc)
        elif path == "/drain":
            doc = self.gateway.drain_emptiest(payload.get("member"))
            if doc is None:
                self._reply(409, {"error": "no_drainable_member"})
            else:
                self._reply(200, doc)
        elif path == "/members":
            # the supervisor's membership hooks: register respawned /
            # scaled-up capacity, retire drained or adopted-away dirs
            op = payload.get("op")
            name = str(payload.get("name") or "")
            if op == "add" and name and payload.get("base_dir"):
                doc = self.gateway.add_member(
                    name, str(payload["base_dir"])
                )
                if doc is None:
                    self._reply(409, {"error": "member_exists"})
                else:
                    self._reply(200, {"member": name, "added": True})
            elif op == "retire" and name:
                if self.gateway.retire_member(name):
                    self._reply(200, {"member": name, "retired": True})
                else:
                    self._reply(409, {"error": "not_retirable"})
            else:
                self._reply(400, {"error": "bad_member_op"})
        else:
            self._reply(404, {"error": "not_found"})

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.rstrip("/")
        if path == "/healthz":
            self._reply(200, self.gateway.healthz())
        elif path == "/status":
            self._reply(200, self.gateway.status())
        elif path.startswith("/request/"):
            status, doc = self.gateway.lookup(path[len("/request/"):])
            self._reply(status, doc)
        else:
            self._reply(404, {"error": "not_found"})
