"""Typed in-memory targets: task-graph fusion without a storage round-trip.

Every producer -> consumer hop of a workflow DAG historically paid a full
store+load round-trip through chunked storage — watershed stored its label
volume so graph extraction could read it back, graph stored npz artifacts so
costs could load them, and so on ("Composing Distributed Computations
Through Task and Kernel Fusion", PAPERS.md, names these materialization
boundaries as where distributed-runtime speedups live).  This module is the
registry behind the :class:`~cluster_tools_tpu.runtime.task.MemoryTarget`
layer (docs/PERFORMANCE.md "Task-graph fusion"): a producer task declares an
output as in-memory, publishes the array(s) here keyed by the *dataset
identity* the consumer would have opened from storage, and a downstream task
resolves the identity to the live host-RAM handle instead of reading the
store — zero intermediate storage writes on the happy path.

Spill-to-storage is the universal fallback, routed through the PR-4 degrade
ladder:

- **byte-budget admission** — a handoff whose bytes do not fit the process
  budget (``CTT_HANDOFF_BYTES``, default ``min(2 GiB, MemAvailable/4)`` off
  the same headroom probe as the executor's admission control) is written
  through to its storage spill path from birth,
- **headroom pressure** — the executor's admission gate calls
  :func:`spill_for_headroom` when host memory runs low; completed handoffs
  are flushed to storage oldest-first and their RAM is released,
- **forced spill** — a ``kind='spill'`` fault at site ``publish``
  (``runtime/faults.py``) forces the write-through, so chaos can prove the
  fallback on demand.

Spilled bytes go through the ordinary container write path, so they get the
PR-3 CRC32 digest sidecars like any chunk write (artifact spills get a
``.crc.json`` sidecar verified on fallback loads), and every spill is
attributed in ``failures.json`` as ``resolution="degraded:spilled"``.  A
consumer that finds no live handle — process restart, spill, a cluster
target crossing a host boundary — transparently falls back to the stored
copy; a producer whose success manifest records a *memory-only* output that
is no longer live is treated as not-done by the DAG engine and re-runs
(:meth:`~cluster_tools_tpu.runtime.task.BaseTask.complete`).

``CTT_HANDOFF=0`` is the kill switch; the per-task ``memory_handoffs``
config knob (default off) is what call sites gate on.  Counters
(``handoffs_published`` / ``handoffs_served`` / ``handoffs_spilled`` /
``handoff_fallbacks`` / ``bytes_not_stored`` / ``bytes_spilled``) follow the
chunk-cache snapshot/delta pattern: the task runtime snapshots around each
task and merges the delta into ``io_metrics.json``, rendered by
``scripts/failures_report.py``.

**The device rung** (docs/PERFORMANCE.md "Device-resident data plane"):
above the memory rung sits ``kind="device_arrays"`` — the payload is a
dict of live *jax* arrays, so a fused consumer resolves its producer's
output without even the host copy (:func:`publish_device_arrays` /
:func:`resolve_device_arrays`; the per-task ``device_handoffs`` knob and
the ``CTT_DEVICE_POOL=0`` kill switch gate it).  The ladder reads device
-> memory -> storage: device-budget pressure (the shared
``device_pool_bytes`` / ``CTT_DEVICE_POOL_BYTES`` envelope) demotes the
oldest device entries to the memory rung (one d2h copy, counted
``d2h_bytes``), a host-side consumer demotes on resolve, and an injected
RESOURCE_EXHAUSTED at site ``publish`` falls the publish itself back to
the memory rung, attributed ``resolution="degraded:host_staged"``.  CRC32
digests are computed at the demotion boundary — the FIRST point the bytes
materialize on host — and verified when the entry later spills to
storage, so the device rung keeps the PR-3 integrity contract without
ever checksumming device memory.  Device entries are excluded from
:func:`live_bytes` / :func:`spill_for_headroom` (they hold HBM, not host
RAM — demoting them under *host* pressure would make that pressure
worse); ``device_live_bytes`` tracks their footprint separately.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
import zlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils import function_utils as fu

#: identity of THIS process for the marker-epoch sentinel: block markers
#: written alongside a live in-memory output are only trustworthy inside
#: the process that holds the memory (pid alone is reuse-prone)
_PROCESS_TOKEN = f"{os.getpid()}.{uuid.uuid4().hex[:12]}"

#: counter names, fixed so snapshots/deltas stay schema-stable
STAT_KEYS = (
    "handoffs_published",
    "handoffs_served",
    "handoffs_spilled",
    "handoff_fallbacks",
    "bytes_not_stored",
    "bytes_spilled",
    "device_handoffs_published",
    "device_handoffs_served",
    "device_handoffs_demoted",
)


def handoff_enabled() -> bool:
    """In-memory handoff targets (default on at the process level;
    ``CTT_HANDOFF=0`` is the kill switch).  Tasks additionally gate on
    their ``memory_handoffs`` config knob, which defaults to off — the
    process switch exists so cluster workers (whose memory dies with them
    before the submitter-side consumer runs) can be forced to storage
    regardless of config."""
    return os.environ.get("CTT_HANDOFF", "1").lower() not in (
        "0", "false", "off",
    )


def handoff_budget() -> int:
    """Byte budget for live in-memory handoffs (``CTT_HANDOFF_BYTES``,
    default ``min(2 GiB, MemAvailable/4)`` via the PR-4 headroom probe)."""
    env = os.environ.get("CTT_HANDOFF_BYTES")
    if env:
        return max(0, int(env))
    avail = None
    try:
        from .supervision import host_mem_available_bytes

        avail = host_mem_available_bytes()
    except Exception:  # pragma: no cover - probe is /proc-based
        avail = None
    if avail:
        return int(min(2 << 30, avail // 4))
    return 512 << 20


def _request_namespace() -> Optional[str]:
    """The running service request's id (docs/SERVING.md), or None in
    batch mode.  Handoff identities are namespaced by it so two concurrent
    requests over the SAME dataset paths can never resolve each other's
    in-flight intermediates."""
    from . import admission

    ctx = admission.current_request()
    return None if ctx is None else ctx.request_id


def _namespaced(base: str) -> str:
    ns = _request_namespace()
    return f"req:{ns}::{base}" if ns else base


def identity_namespace(identity: str) -> Optional[str]:
    """The request id an identity was namespaced under, or None."""
    identity = str(identity)
    if identity.startswith("req:") and "::" in identity:
        return identity[len("req:"):identity.index("::")]
    return None


def in_current_namespace(identity) -> bool:
    """Whether ``identity`` belongs to THIS thread's request namespace
    (both None in batch mode).  The resume contract depends on it: a
    manifest recording a memory-only output from a *different* request's
    namespace is unreachable for the current consumer and must re-run."""
    return identity_namespace(str(identity)) == _request_namespace()


def dataset_identity(path: str, key: str) -> str:
    """Stable identity of a chunked dataset handoff: the same (container
    path, key) a storage consumer would open — prefixed with the service
    request's namespace when one is active."""
    return _namespaced(f"{os.path.abspath(path)}:{key}")


def artifact_identity(path: str) -> str:
    """Stable identity of an array-artifact handoff (an npz/npy path),
    request-namespaced like :func:`dataset_identity`."""
    return _namespaced(os.path.abspath(path))


class _Entry:
    """One live or spilled handoff.  ``obj`` is the in-memory payload (a
    HandoffDataset, or a dict of read-only arrays) and is dropped on spill
    — after a spill, storage is the single source of truth."""

    __slots__ = (
        "kind", "identity", "path", "key", "obj", "nbytes", "complete",
        "spilled", "spilling", "spill_reason", "producer", "failures_path",
        "recorded", "device_crcs",
    )

    def __init__(self, kind, identity, path, key, obj, nbytes, producer,
                 failures_path):
        self.kind = kind                # "dataset" | "arrays" | "device_arrays"
        self.identity = identity
        self.path = path
        self.key = key
        self.obj = obj
        self.nbytes = int(nbytes)
        self.complete = False
        self.spilled = False
        self.spilling = False        # claimed by an in-progress spill
        self.spill_reason: Optional[str] = None
        self.producer = producer
        self.failures_path = failures_path
        self.recorded = False           # degraded:spilled written once
        # per-array CRC32s stamped when a device entry's bytes FIRST
        # materialize on host (demotion); verified at the storage spill
        self.device_crcs: Optional[Dict[str, int]] = None


class HandoffRegistry:
    """Process-wide registry of in-memory handoff targets."""

    def __init__(self):
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._lock = threading.Lock()
        self._stats: Dict[str, float] = {k: 0 for k in STAT_KEYS}

    # -- counters ----------------------------------------------------------
    def bump(self, key: str, n: float = 1) -> None:
        with self._lock:
            self._stats[key] += n

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._stats)

    # -- bookkeeping -------------------------------------------------------
    def live_bytes(self) -> int:
        """Bytes of payloads currently resident in host RAM.  Device-rung
        entries are HBM, not host RAM — they count in
        :meth:`device_live_bytes` instead (and demoting one under host
        pressure would *add* host bytes, so they must not look like
        reclaimable headroom here)."""
        with self._lock:
            return sum(
                e.nbytes for e in self._entries.values()
                if not e.spilled and e.kind != "device_arrays"
            )

    def device_live_bytes(self) -> int:
        """Bytes of device-rung payloads currently resident in HBM."""
        with self._lock:
            return sum(
                e.nbytes for e in self._entries.values()
                if e.kind == "device_arrays" and not e.spilled
                and e.obj is not None
            )

    def claim_spill(self, entry: _Entry) -> bool:
        """Atomically claim ``entry`` for spilling.  Exactly one caller
        wins; everyone else sees an in-progress or finished spill and
        backs off — ``spilled`` must never be observable before the
        storage copy is actually complete."""
        with self._lock:
            if entry.spilled or entry.spilling or entry.obj is None \
                    or not entry.complete:
                # incomplete = a producer owns (or re-acquired) the
                # payload and is still writing: spilling now would copy a
                # torn snapshot
                return False
            entry.spilling = True
            return True

    def finish_spill(self, entry: _Entry, ok: bool, reason: str) -> None:
        """Release a spill claim: on success the entry flips to spilled
        (payload dropped, storage is the truth); on failure it stays live
        — the memory copy is still the only copy."""
        with self._lock:
            entry.spilling = False
            if ok:
                entry.spilled = True
                entry.spill_reason = reason
                entry.obj = None

    def is_live(self, identity: str) -> bool:
        with self._lock:
            e = self._entries.get(identity)
            return e is not None and e.complete and not e.spilled

    def get(self, identity: str) -> Optional[_Entry]:
        with self._lock:
            return self._entries.get(identity)

    def put(self, entry: _Entry) -> None:
        with self._lock:
            self._entries[entry.identity] = entry
            self._entries.move_to_end(entry.identity)
        # timeline crossing (docs/OBSERVABILITY.md): every published target
        # lands as an instant, so fused handoffs are visible between the
        # producer's and consumer's task.run spans
        from . import trace as trace_mod

        trace_mod.instant(
            "handoff.publish", identity=entry.identity,
            nbytes=int(entry.nbytes), spilled=bool(entry.spilled),
            kind=entry.kind,
        )

    def entries_of(self, producer: str) -> List[_Entry]:
        with self._lock:
            return [
                e for e in self._entries.values() if e.producer == producer
            ]

    def spill_candidates(self) -> List[_Entry]:
        """Complete, still-resident, unclaimed entries, oldest first (the
        LRU order a headroom spill should flush).  Device-rung entries are
        excluded: spilling exists to free host RAM, and a device entry
        holds none until demoted (see :meth:`live_bytes`)."""
        with self._lock:
            return [
                e for e in self._entries.values()
                if e.complete and not e.spilled and not e.spilling
                and e.kind != "device_arrays"
            ]

    def demotion_candidates(self) -> List[_Entry]:
        """Live device-rung entries, oldest first — the order
        device-budget pressure walks when demoting to the memory rung."""
        with self._lock:
            return [
                e for e in self._entries.values()
                if e.kind == "device_arrays" and e.complete
                and not e.spilled and not e.spilling and e.obj is not None
            ]


_registry: Optional[HandoffRegistry] = None
_registry_lock = threading.Lock()


def get_registry() -> HandoffRegistry:
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                _registry = HandoffRegistry()
    return _registry


def reset() -> None:
    """Drop every live handoff and producer registration (tests)."""
    global _registry
    with _registry_lock:
        _registry = HandoffRegistry()


def snapshot() -> Dict[str, float]:
    """Current process-wide handoff counters (monotonic; diff two
    snapshots with :func:`delta`)."""
    return get_registry().snapshot()


def delta(snap: Dict[str, float]) -> Dict[str, float]:
    cur = snapshot()
    return {k: cur[k] - snap.get(k, 0) for k in cur}


def live_bytes() -> int:
    return get_registry().live_bytes()


def device_live_bytes() -> int:
    """HBM bytes held by live device-rung handoffs."""
    return get_registry().device_live_bytes()


def live_entries() -> int:
    """Number of registry entries (any state).  The resident server
    publishes this in ``server_state.json`` so the chaos suite can assert
    from OUTSIDE the process that terminal requests released their
    namespaces — no orphaned handoff entries accrete."""
    reg = get_registry()
    with reg._lock:
        return len(reg._entries)


# -- marker-epoch sentinel ----------------------------------------------------
# A producer whose output lives in THIS process's memory stamps its marker
# directory with the process token; any later run (same knob, knob off,
# spill-at-birth — whatever path it takes) that finds a sentinel from a
# DIFFERENT process clears the block markers before trusting them: they
# describe data that died with that process.


def _sentinel_path(tmp_folder: str, uid: str) -> str:
    return os.path.join(
        tmp_folder, "markers", uid, ".memory_outputs.json"
    )


def mark_memory_producer(tmp_folder: str, uid: str) -> None:
    """Stamp ``uid``'s markers as backed by this process's memory.  Call
    AFTER :func:`invalidate_stale_markers` — the stamp makes this process's
    own markers look current."""
    path = _sentinel_path(tmp_folder, uid)
    doc = fu.read_json_if_valid(path)
    if doc and doc.get("token") == _PROCESS_TOKEN:
        return
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fu.atomic_write_json(path, {"token": _PROCESS_TOKEN})


def invalidate_stale_markers(tmp_folder: str, uid: str) -> bool:
    """Clear ``uid``'s block markers if they were stamped by ANOTHER
    process's in-memory run (the data is gone with that process).  The
    sentinel is removed too, so a storage-backed re-run does not keep
    re-clearing.  Returns whether markers were invalidated.  Cheap no-op
    when no sentinel exists — called from ``BaseTask.blocks_done``."""
    path = _sentinel_path(tmp_folder, uid)
    doc = fu.read_json_if_valid(path)
    if doc is None:
        if os.path.exists(path):
            # torn sentinel: provenance unknown, treat as stale
            doc = {}
        else:
            return False
    if doc.get("token") == _PROCESS_TOKEN:
        return False
    fu.clear_block_markers(tmp_folder, uid)
    try:
        os.remove(path)
    except OSError:
        pass
    return True


def is_live(identity: str) -> bool:
    return get_registry().is_live(identity)


def discard(identity: str) -> None:
    """Drop a registry entry outright: a producer about to (re)write the
    same identity through the STORAGE path (handoffs off for this run)
    must not leave a previous run's live payload shadowing the fresh
    bytes for consumers."""
    reg = get_registry()
    with reg._lock:
        reg._entries.pop(identity, None)


def is_resolvable(identity: str) -> bool:
    """True when a consumer CAN resolve ``identity`` in this process: a
    completed registry entry — live in memory, or spilled (storage holds
    the checksummed copy consumers fall back to).  A producer manifest
    recording a memory-only output stays valid in either state; only a
    missing/incomplete entry (process restart) means the data is gone."""
    entry = get_registry().get(identity)
    return entry is not None and entry.complete


def _file_reader(path: str, mode: str = "a"):
    from ..io import open_container

    return open_container(path, mode=mode)


def _force_spill() -> bool:
    from . import faults as faults_mod

    return faults_mod.get_injector().force_spill()


def _mem_headroom_ok(nbytes: int) -> bool:
    """Admission headroom probe: a handoff bigger than half of what the
    host has available cannot responsibly live in RAM."""
    try:
        from .supervision import host_mem_available_bytes

        avail = host_mem_available_bytes()
    except Exception:  # pragma: no cover
        avail = None
    return avail is None or nbytes <= avail // 2


def _admit(nbytes: int) -> Optional[str]:
    """None when ``nbytes`` may live in memory, else the spill reason.
    Tries to make room by flushing completed elders first — the byte-budget
    admission leg of the PR-4 degrade ladder."""
    budget = handoff_budget()
    if budget <= 0 or nbytes > budget:
        return "admission:budget"
    if not _mem_headroom_ok(nbytes):
        return "admission:headroom"
    if live_bytes() + nbytes > budget:
        spill_for_headroom(need_bytes=nbytes)
        if live_bytes() + nbytes > budget:
            return "admission:budget"
    return None


# -- dataset handoffs ---------------------------------------------------------


def acquire_dataset(
    path: str,
    key: str,
    shape,
    chunks,
    dtype,
    producer: str,
    failures_path: Optional[str] = None,
    fill_value: int = 0,
) -> Tuple[Any, _Entry]:
    """Producer-side acquire of a dataset handoff target.

    Returns ``(dataset, entry)``: the dataset the task should write
    through (an in-memory
    :class:`~cluster_tools_tpu.io.containers.HandoffDataset`, or the real
    storage dataset when the target spills at birth — admission rejection,
    a forced ``spill`` fault, or a spilled predecessor at the same
    identity) and the registry entry backing the declared target.
    """
    from ..io.containers import HandoffDataset, _check_existing

    reg = get_registry()
    identity = dataset_identity(path, key)
    entry = reg.get(identity)
    if entry is not None and entry.kind == "dataset":
        # an in-flight headroom spill owns the payload: wait it out (the
        # flush is bounded) instead of handing the producer a memory
        # handle whose already-copied regions would silently lose writes
        while entry.spilling:
            time.sleep(0.01)
        if not entry.spilled and entry.obj is not None:
            ds = entry.obj
            _check_existing(
                key, ds.shape, ds.dtype, shape, dtype,
                have_chunks=ds.chunks, want_chunks=chunks,
            )
            entry.producer = producer
            entry.complete = False  # a new producer is writing again
            if failures_path:
                entry.failures_path = failures_path
            return ds, entry
        # spilled predecessor: storage is the source of truth now — the new
        # producer writes through (pass-one spilled => pass-two must too,
        # or pass-two reads of pass-one labels would see zeros)
        store = _file_reader(path).require_dataset(
            key, shape=shape, chunks=chunks, dtype=dtype
        )
        entry.producer = producer
        if failures_path:
            entry.failures_path = failures_path
        return store, entry

    nbytes = int(np.prod([int(s) for s in shape], dtype=np.int64)) * np.dtype(
        dtype
    ).itemsize
    reason = "fault" if _force_spill() else _admit(nbytes)
    if reason is not None:
        # spill-at-birth: every block lands on storage through the normal
        # (checksummed) write path; block-grain resume stays valid
        store = _file_reader(path).require_dataset(
            key, shape=shape, chunks=chunks, dtype=dtype
        )
        entry = _Entry("dataset", identity, path, key, None, nbytes,
                       producer, failures_path)
        entry.spilled = True
        entry.spill_reason = reason
        reg.put(entry)
        reg.bump("handoffs_published")
        reg.bump("handoffs_spilled")
        reg.bump("bytes_spilled", nbytes)
        return store, entry

    def _store_factory():
        return _file_reader(path).require_dataset(
            key, shape=shape, chunks=chunks, dtype=dtype
        )

    ds = HandoffDataset(
        shape=shape, chunks=chunks, dtype=dtype,
        store_factory=_store_factory, label=f"handoff://{identity}",
        fill_value=fill_value,
    )
    entry = _Entry("dataset", identity, path, key, ds, nbytes, producer,
                   failures_path)
    reg.put(entry)
    reg.bump("handoffs_published")
    return ds, entry


def resolve_dataset(path: str, key: str):
    """Consumer-side resolve: the live in-memory handle when a completed
    handoff exists for ``(path, key)`` (counted ``handoffs_served``), the
    stored copy when it spilled (counted ``handoff_fallbacks``), else the
    plain storage dataset."""
    from . import trace as trace_mod

    reg = get_registry()
    identity = dataset_identity(path, key)
    entry = reg.get(identity)
    if entry is not None and entry.kind == "dataset":
        obj = entry.obj
        if not entry.spilled and obj is not None:
            reg.bump("handoffs_served")
            trace_mod.instant(
                "handoff.resolve", identity=identity, served="memory"
            )
            return obj
        reg.bump("handoff_fallbacks")
        trace_mod.instant(
            "handoff.resolve", identity=identity, served="fallback"
        )
    return _file_reader(path)[key]


# -- array-artifact handoffs --------------------------------------------------


def _freeze(arrays: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    out = {}
    for name, a in arrays.items():
        a = np.asarray(a).copy()
        a.setflags(write=False)
        out[name] = a
    return out


def _views(arrays: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    # views of read-only arrays stay read-only: consumers cannot mutate a
    # producer's published payload in place
    return {name: a.view() for name, a in arrays.items()}


def _crc_sidecar_path(path: str) -> str:
    return path + ".crc.json"


def _is_npy(path: str) -> bool:
    return path.endswith(".npy")


def _write_artifact(
    path: str,
    arrays: Dict[str, np.ndarray],
    expected_crcs: Optional[Dict[str, int]] = None,
) -> None:
    """Spill one artifact: atomic npz/npy write + a CRC32 sidecar, so a
    fallback load can verify the stored bytes like any chunk read.

    ``expected_crcs`` (device-rung entries only) are the digests stamped
    when the payload first materialized on host — a mismatch here means
    the host copy rotted between demotion and spill, and the spill must
    fail loudly (the memory copy stays the only copy) rather than
    checksum-bless corrupt bytes."""
    crcs = {
        name: zlib.crc32(np.ascontiguousarray(a).tobytes())
        for name, a in arrays.items()
    }
    if expected_crcs:
        from ..io.containers import ChunkCorruptionError

        for name, want in expected_crcs.items():
            if name in crcs and crcs[name] != want:
                raise ChunkCorruptionError(
                    f"{path}[{name}]", (), want, crcs[name]
                )
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "wb") as f:
        if _is_npy(path):
            (arr,) = arrays.values()
            np.save(f, arr)
        else:
            np.savez(f, **arrays)
    os.replace(tmp, path)
    fu.atomic_write_json(
        _crc_sidecar_path(path),
        {"algo": "crc32", "arrays": crcs},
    )


def _verify_artifact(path: str, arrays: Dict[str, np.ndarray]) -> bool:
    """CRC-check ``arrays`` against the spill sidecar; True when a sidecar
    was present (i.e. the file is a spilled handoff artifact).  No sidecar
    (a pre-handoff plain file) verifies vacuously as False."""
    doc = fu.read_json_if_valid(_crc_sidecar_path(path))
    if not doc:
        return False
    from ..io.containers import ChunkCorruptionError

    want = doc.get("arrays") or {}
    for name, a in arrays.items():
        if name not in want:
            continue
        crc = zlib.crc32(np.ascontiguousarray(a).tobytes())
        if crc != want[name]:
            raise ChunkCorruptionError(
                f"{path}[{name}]", (), want[name], crc
            )
    return True


def publish_arrays(
    path: str,
    arrays: Dict[str, np.ndarray],
    producer: str,
    failures_path: Optional[str] = None,
) -> _Entry:
    """Producer-side publish of named arrays under an artifact path (the
    npz/npy file a storage consumer would have loaded).  Arrays are frozen
    read-only; a forced ``spill`` fault or admission rejection writes the
    file (+ CRC sidecar) instead and keeps storage as the source of truth.
    Complete immediately — artifacts have no block grain."""
    reg = get_registry()
    identity = artifact_identity(path)
    frozen = _freeze(arrays)
    nbytes = sum(a.nbytes for a in frozen.values())
    reason = "fault" if _force_spill() else _admit(nbytes)
    entry = _Entry("arrays", identity, path, None, None, nbytes, producer,
                   failures_path)
    entry.complete = True
    if reason is not None:
        _write_artifact(path, frozen)
        entry.spilled = True
        entry.spill_reason = reason
        reg.put(entry)
        reg.bump("handoffs_published")
        reg.bump("handoffs_spilled")
        reg.bump("bytes_spilled", nbytes)
        return entry
    entry.obj = frozen
    reg.put(entry)
    reg.bump("handoffs_published")
    reg.bump("bytes_not_stored", nbytes)
    return entry


# -- the device rung ----------------------------------------------------------


def _record_host_staged(producer, failures_path, identity, reason,
                        err=None) -> None:
    """One ``degraded:host_staged`` failures.json record per fallen-back
    device publish — the device rung's attribution contract (the task key
    is ``<producer>.device_handoff`` so it can never merge-collide with
    the memory rung's ``<producer>.handoff`` spill records)."""
    if not failures_path:
        return
    try:
        fu.record_failures(
            failures_path,
            f"{producer}.device_handoff",
            [{
                "block_id": None,
                "sites": {"publish": 1},
                "error": None if err is None else fu.cap_traceback(str(err)),
                "quarantined": False,
                "resolved": True,
                "resolution": "degraded:host_staged",
                "handoff": identity,
                "reason": reason,
            }],
        )
    except Exception:
        pass  # attribution is best-effort; the fallback itself landed


def publish_device_arrays(
    path: str,
    arrays: Dict[str, Any],
    producer: str,
    failures_path: Optional[str] = None,
) -> _Entry:
    """Producer-side publish on the DEVICE rung: ``arrays`` (jax arrays —
    typically still resident from the producing computation — or host
    arrays uploaded here, counted ``h2d_bytes``) stay live in HBM under
    the artifact identity, so a fused consumer's
    :func:`resolve_device_arrays` serves them with ZERO host bytes.

    The ladder down: kill switch (``CTT_DEVICE_POOL=0``) off -> the memory
    rung verbatim; a resource failure (an injected oom at site
    ``publish``, a real RESOURCE_EXHAUSTED while uploading, or the shared
    device byte budget rejecting even after demoting elder entries) ->
    one d2h copy + the memory rung, attributed
    ``resolution="degraded:host_staged"`` — consumers keep resolving
    bit-identically either way."""
    from ..parallel import device_pool as device_pool_mod

    def _host(reason, err=None):
        host = {}
        for name, a in arrays.items():
            h = np.asarray(a)
            if not isinstance(a, np.ndarray):
                # the payload was device-resident: falling back is a real
                # d2h copy, attributed like any other
                device_pool_mod.record_d2h(h.nbytes)
            host[name] = h
        entry = publish_arrays(path, host, producer, failures_path)
        if reason is not None:
            device_pool_mod.bump("host_staged_fallbacks")
            _record_host_staged(producer, failures_path, entry.identity,
                                reason, err=err)
        return entry

    if not device_pool_mod.device_pool_enabled():
        return _host(None)

    from . import faults as faults_mod
    from .executor import classify_resource_error

    reg = get_registry()
    identity = artifact_identity(path)
    try:
        faults_mod.get_injector().maybe_fail("publish", None)
        import jax

        held: Dict[str, Any] = {}
        nbytes = 0
        for name, a in arrays.items():
            if not isinstance(a, jax.Array):
                a = np.asarray(a)
                device_pool_mod.record_h2d(a.nbytes)
                a = jax.device_put(a)
            held[name] = a
            nbytes += int(a.nbytes)
        # device-budget admission (the HBM envelope shared with the page
        # pool): demote the oldest device entries first, and if the new
        # payload still does not fit, ride the resource ladder below
        budget = device_pool_mod.device_pool_budget()
        if device_live_bytes() + nbytes > budget:
            demote_for_device_headroom(need_bytes=nbytes)
        if device_live_bytes() + nbytes > budget:
            raise MemoryError(
                f"device handoff budget RESOURCE_EXHAUSTED: {nbytes} B "
                f"payload over the {budget} B device envelope"
            )
    except Exception as e:
        if classify_resource_error(e) is None:
            raise
        return _host("oom", err=e)
    entry = _Entry("device_arrays", identity, path, None, held, nbytes,
                   producer, failures_path)
    entry.complete = True
    reg.put(entry)
    reg.bump("handoffs_published")
    reg.bump("device_handoffs_published")
    reg.bump("bytes_not_stored", nbytes)
    return entry


def resolve_device_arrays(path: str) -> Dict[str, Any]:
    """Consumer-side resolve on the device rung: the live jax arrays when
    the device entry is live (zero host bytes — counted
    ``device_handoffs_served`` and, in the device-plane counters,
    ``bytes_not_staged``), else the memory/storage rungs via
    :func:`load_arrays` (host arrays the consumer may re-upload)."""
    from . import trace as trace_mod
    from ..parallel import device_pool as device_pool_mod

    reg = get_registry()
    entry = reg.get(artifact_identity(path))
    if entry is not None and entry.kind == "device_arrays" \
            and not entry.spilled and entry.obj is not None:
        reg.bump("handoffs_served")
        reg.bump("device_handoffs_served")
        device_pool_mod.bump("device_handoffs_served")
        device_pool_mod.bump("bytes_not_staged", entry.nbytes)
        trace_mod.instant(
            "handoff.resolve", identity=entry.identity, served="device"
        )
        return dict(entry.obj)
    return load_arrays(path)


def _demote_device_entry(entry: _Entry, reason: str) -> int:
    """Demote one device-rung entry to the memory rung: ONE d2h copy
    (counted ``d2h_bytes``), frozen read-only, CRC32s stamped here — the
    first point the bytes exist on host — for the storage spill boundary
    to verify.  Returns the HBM bytes released (0 when another thread
    holds the claim).  The entry stays resolvable throughout: consumers
    see either the device payload or the finished host copy."""
    from . import trace as trace_mod
    from ..parallel import device_pool as device_pool_mod

    reg = get_registry()
    if not reg.claim_spill(entry):
        return 0
    ok = False
    try:
        with trace_mod.span(
            "handoff.demote", identity=entry.identity, reason=reason,
            nbytes=int(entry.nbytes),
        ):
            host = {}
            for name, a in entry.obj.items():
                h = np.asarray(a)
                device_pool_mod.record_d2h(h.nbytes)
                host[name] = h
            frozen = _freeze(host)
            crcs = {
                name: zlib.crc32(np.ascontiguousarray(a).tobytes())
                for name, a in frozen.items()
            }
        with reg._lock:
            entry.obj = frozen
            entry.device_crcs = crcs
            entry.kind = "arrays"
        ok = True
    finally:
        # release the claim WITHOUT flipping spilled: the entry is now an
        # ordinary memory-rung artifact, eligible for normal spilling
        reg.finish_spill(entry, False, reason)
    if not ok:
        return 0
    reg.bump("device_handoffs_demoted")
    return entry.nbytes


def demote_for_device_headroom(need_bytes: Optional[int] = None) -> int:
    """Demote live device-rung entries to the memory rung, oldest first,
    until ``need_bytes`` fits the device byte budget (None: demote
    everything).  The device analogue of :func:`spill_for_headroom` —
    HBM pressure resolves downward to host RAM, never sideways.  Returns
    HBM bytes released."""
    from ..parallel import device_pool as device_pool_mod

    budget = device_pool_mod.device_pool_budget()
    freed = 0
    for entry in get_registry().demotion_candidates():
        if need_bytes is not None \
                and device_live_bytes() + need_bytes <= budget:
            break
        freed += _demote_device_entry(entry, "device_budget")
    return freed


def load_arrays(path: str) -> Dict[str, np.ndarray]:
    """Consumer-side load of an array artifact: the live in-memory payload
    when one exists (``handoffs_served``), else the file — verified against
    its CRC sidecar when the artifact was spilled (``handoff_fallbacks``).
    Plain files published before the handoff layer load unchanged."""
    from . import trace as trace_mod

    reg = get_registry()
    entry = reg.get(artifact_identity(path))
    if entry is not None and entry.kind == "device_arrays":
        # a HOST consumer of a device-rung entry: demote it (the one d2h
        # copy, stamping the CRCs) and serve the host views below
        _demote_device_entry(entry, "host_consumer")
    if entry is not None and entry.kind == "arrays":
        obj = entry.obj
        if not entry.spilled and obj is not None:
            reg.bump("handoffs_served")
            trace_mod.instant(
                "handoff.resolve", identity=entry.identity, served="memory"
            )
            return _views(obj)
        reg.bump("handoff_fallbacks")
        trace_mod.instant(
            "handoff.resolve", identity=entry.identity, served="fallback"
        )
    if _is_npy(path):
        arr = np.load(path)
        out = {"data": arr}
    else:
        with np.load(path) as f:
            out = {name: f[name] for name in f.files}
    # verify UNCONDITIONALLY: a crash-resumed process has an empty
    # registry, and the restart case is exactly what the sidecar exists
    # for.  A sidecar also identifies the file as a spilled handoff, so
    # restart-time fallback reads are counted too.
    was_spilled = _verify_artifact(path, out)
    if entry is None and was_spilled:
        reg.bump("handoff_fallbacks")
    return out


def load_array(path: str) -> np.ndarray:
    """Single-array twin of :func:`load_arrays` for ``.npy`` artifacts."""
    arrays = load_arrays(path)
    (arr,) = arrays.values()
    return arr


def forget_artifact(path: str) -> None:
    """A producer is about to write ``path`` as a PLAIN file (handoffs off
    for this run): drop any previous run's live payload and the spill CRC
    sidecar — a stale sidecar would flag the fresh bytes as corruption."""
    discard(artifact_identity(path))
    try:
        os.remove(_crc_sidecar_path(path))
    except OSError:
        pass


def array_exists(path: str) -> bool:
    """True when the artifact is resolvable — live in memory or on disk."""
    entry = get_registry().get(artifact_identity(path))
    if entry is not None and entry.kind in ("arrays", "device_arrays") \
            and not entry.spilled and entry.obj is not None:
        return True
    return os.path.exists(path)


# -- spill machinery ----------------------------------------------------------


def _spill_entry(entry: _Entry, reason: str) -> int:
    """Flush one live entry to its storage spill path and release its RAM.
    Returns the bytes freed (0 when the entry was already spilled/being
    spilled by another thread, or the spill failed — a failed spill keeps
    the memory copy, which is still the only copy).

    The claim protocol matters: ``spilled`` must never be observable
    before the storage copy is COMPLETE, or a concurrent consumer's
    fallback would read a half-written dataset.  Exactly one thread wins
    the claim; the flags flip (under the registry lock) only after the
    copy landed."""
    from . import trace as trace_mod

    reg = get_registry()
    if not reg.claim_spill(entry):
        return 0
    obj = entry.obj
    freed = 0
    ok = False
    try:
        # the spill is real storage IO mid-pipeline: a span, not an
        # instant, so the timeline shows the stall it caused
        with trace_mod.span(
            "handoff.spill", identity=entry.identity, reason=reason,
            nbytes=int(entry.nbytes),
        ):
            if entry.kind == "dataset":
                freed = obj.spill()
            else:
                # demoted device entries carry the CRCs stamped when their
                # bytes first hit host RAM: the spill boundary verifies them
                _write_artifact(entry.path, obj,
                                expected_crcs=entry.device_crcs)
                freed = entry.nbytes
        ok = True
    except Exception:
        ok = False
    finally:
        reg.finish_spill(entry, ok, reason)
    if not ok:
        return 0
    reg.bump("handoffs_spilled")
    reg.bump("bytes_spilled", freed)
    # reconcile the "never stored" figure: these bytes DID reach storage
    # after all (datasets track their accumulated write bytes; artifacts
    # counted their payload once at publish)
    if entry.kind == "dataset":
        not_stored = int(getattr(obj, "not_stored_bytes", 0))
    else:
        not_stored = entry.nbytes
    if not_stored:
        reg.bump("bytes_not_stored", -not_stored)
    _record_spill(entry)
    return freed


def _record_spill(entry: _Entry) -> None:
    """One ``degraded:spilled`` failures.json record per spilled target —
    the degrade ladder's attribution contract (docs/ROBUSTNESS.md)."""
    if entry.recorded or not entry.failures_path:
        return
    entry.recorded = True
    try:
        fu.record_failures(
            entry.failures_path,
            f"{entry.producer}.handoff",
            [{
                "block_id": None,
                "sites": {"spill": 1},
                "error": None,
                "quarantined": False,
                "resolved": True,
                "resolution": "degraded:spilled",
                "handoff": entry.identity,
                "reason": entry.spill_reason,
            }],
        )
    except Exception:
        pass  # attribution is best-effort; the spill itself already landed


def spill_for_headroom(need_bytes: Optional[int] = None) -> int:
    """Flush completed in-memory handoffs to storage, oldest first.
    Called by the executor's admission gate when host-memory headroom runs
    low (no ``need_bytes``: flush everything — the pressure is real RAM)
    and by :func:`_admit` to make room for one new target (``need_bytes``:
    stop as soon as it fits the budget, so one marginal admission does not
    force every remaining consumer onto the fallback-read path).  Returns
    bytes freed."""
    budget = handoff_budget()
    freed = 0
    for entry in get_registry().spill_candidates():
        if need_bytes is not None and live_bytes() + need_bytes <= budget:
            break
        freed += _spill_entry(entry, "headroom")
    return freed


def _namespace_entries(request_id: str) -> List[_Entry]:
    prefix = f"req:{request_id}::"
    reg = get_registry()
    with reg._lock:
        return [
            e for e in reg._entries.values()
            if e.identity.startswith(prefix)
        ]


def flush_namespace(request_id: str, datasets_only: bool = True) -> int:
    """Write a completed service request's live *dataset* handoffs back to
    their storage paths (docs/SERVING.md): once the server reports a
    request done, every client-visible chunked dataset must exist on
    storage — later requests (or a restarted server) read it through the
    ordinary fallback path.  Artifact intermediates (npz/npy inside the
    request's tmp_folder) are private to the request and die with its
    namespace, which is what preserves the fusion layer's
    zero-intermediate-storage headline under service mode.  Returns bytes
    flushed.  The write-back is a planned completion step, not a degrade,
    so it is NOT attributed as ``degraded:spilled`` in failures.json."""
    flushed = 0
    for entry in _namespace_entries(request_id):
        if datasets_only and entry.kind != "dataset":
            continue
        if entry.spilled or entry.obj is None or not entry.complete:
            continue
        entry.recorded = True  # suppress the degrade attribution
        flushed += _spill_entry(entry, "service:finalize")
    return flushed


def release_request(request_id: str) -> int:
    """Drop every registry entry of a request's namespace (terminal
    states: done, failed, drained).  A resident server process must not
    accrete dead request state, and a rejected/failed request must leave
    no orphaned handoff entries behind — the chaos suite asserts this.
    Returns the number of entries released."""
    prefix = f"req:{request_id}::"
    reg = get_registry()
    with reg._lock:
        doomed = [k for k in reg._entries if k.startswith(prefix)]
        for k in doomed:
            reg._entries.pop(k, None)
    return len(doomed)


def finalize_task(targets, uid: str) -> List[Dict[str, Any]]:
    """Producer-task completion: mark this task's targets complete and emit
    the success-manifest records the DAG engine validates on resume
    (:meth:`~cluster_tools_tpu.runtime.task.BaseTask.complete`).  Spilled
    targets (at-birth or since) get their ``degraded:spilled`` attribution
    here if not already recorded."""
    records = []
    seen = set()
    for target in targets:
        entry = target.entry
        if entry.identity in seen:
            continue
        seen.add(entry.identity)
        entry.complete = True
        if entry.spilled:
            _record_spill(entry)
        records.append({
            "identity": entry.identity,
            "path": entry.path,
            "key": entry.key,
            "kind": entry.kind,
            "stored": bool(entry.spilled),
            "bytes": int(entry.nbytes),
        })
    return records
