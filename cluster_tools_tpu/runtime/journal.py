"""Durable submission journal for service mode (docs/SERVING.md
"Durability").

PR 12's durability story stopped at the graceful drain: a SIGTERM finished
in-flight work and exited 114, but queued and accepted requests died with
the process — on a preemptible fleet, where the common failure is an
abrupt ``kill -9`` and not a polite drain, that pushed exactly-once
bookkeeping onto every client.  This module makes ``/submit``'s 200 a
durable promise: every request lifecycle transition (``accepted`` →
``dispatched`` → ``completed`` / ``failed`` / ``rejected`` /
``quarantined``) is an fsync'd, CRC-framed, append-only record written
*before* the state is acknowledged over HTTP, and a restarted
:class:`~cluster_tools_tpu.runtime.server.PipelineServer` replays the
journal to reconstruct exactly what it promised:

- **completed** requests are served idempotently — a duplicate resubmit of
  a done id answers from the recorded result instead of re-running (or
  bouncing ``rejected:duplicate``);
- **acknowledged-but-incomplete** requests (accepted / dispatched /
  drained) are re-enqueued with their original tenant + payload and re-run
  through the ordinary resume machinery — block markers plus the
  namespace-stale handoff invalidation make the rerun bit-identical;
- a replayed request that crashes the server ``max_replay_attempts`` times
  (the attempt count is itself journaled as ``dispatched`` records) is
  **quarantined** with a typed ``quarantined:crash_loop`` record instead
  of wedging the server in a crash loop.

Frame format (append-only, binary)::

    MAGIC(4 = b"CTJ1") | payload_len(u32 LE) | crc32(payload)(u32 LE) | payload

``payload`` is compact JSON.  The reader (:func:`scan`) walks frames from
the start and stops at the FIRST inconsistency — short header, short
payload, bad magic, CRC mismatch, unparseable JSON — treating everything
after it as a torn tail: :meth:`Journal.recover` truncates the file back
to the last intact frame and warns, it never refuses to boot (the same
truncate-and-warn posture the atomic-write discipline CT002 gives JSON
manifests).  A torn tail can only be a *suffix* because appends are
serialized under the journal lock, every append is fsync'd before the
state it records becomes observable, and a deliberately torn write (the
injected ``torn`` fault at site ``journal``) hard-exits the process — a
torn record mid-file therefore cannot be followed by intact ones.

Lock discipline (ctlint CT010): all appends go through
:meth:`Journal.append` (raw writes to the journal file anywhere else are
a lint finding), the append path must show fsync evidence, and journal IO
— an fsync is a disk round trip — must never run under the server's
admission/request locks.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..utils import function_utils as fu
from . import faults as faults_mod
from . import trace as trace_mod

#: the journal file, next to ``server_state.json`` / ``failures.json``
JOURNAL_FILENAME = "journal.log"

#: the fence-epoch file, next to the journal it guards (docs/SERVING.md
#: "Gray failures").  Minted (monotonically bumped) by whoever wins the
#: adoption claim for this member's journal; the member itself re-checks
#: it before every journal append and handoff flush, so a falsely-
#: declared-dead zombie that wakes after adoption can never fork the
#: truth a survivor now owns.
FENCE_FILENAME = "fence.json"

MAGIC = b"CTJ1"
_HEADER = struct.Struct("<4sII")  # magic, payload length, crc32(payload)

#: a frame claiming a payload larger than this is framing damage, not a
#: record (the journal holds request metadata, never array data)
MAX_RECORD_BYTES = 16 << 20

#: lifecycle record types (the ``type`` field of every journal record)
ACCEPTED = "accepted"
DISPATCHED = "dispatched"
COMPLETED = "completed"
FAILED = "failed"
REJECTED = "rejected"
QUARANTINED = "quarantined"
DRAINED = "drained"

#: types that end a request's lifecycle; anything else at replay time is an
#: acknowledged-but-incomplete request the restarted server must finish
TERMINAL_TYPES = (COMPLETED, FAILED, REJECTED, QUARANTINED)


#: rotation threshold (bytes) applied on clean boot; 0 disables.  One
#: ``.old`` segment is kept — rotation stops unbounded growth (the PR-13
#: residual); in-segment redundancy compaction stays future work.
DEFAULT_ROTATE_BYTES = 8 << 20


def rotate_bytes_default() -> int:
    """Boot-time rotation threshold (``CTT_JOURNAL_ROTATE_BYTES``)."""
    try:
        return int(os.environ.get("CTT_JOURNAL_ROTATE_BYTES", "") or
                   DEFAULT_ROTATE_BYTES)
    except ValueError:
        return DEFAULT_ROTATE_BYTES


def journal_path(base_dir: str) -> str:
    return os.path.join(base_dir, JOURNAL_FILENAME)


# -- fencing epochs (docs/SERVING.md "Gray failures") -------------------------
#
# The adoption claim (runtime/fleet.py) proves at most one ADOPTER; fencing
# proves the ADOPTED member can no longer write.  Protocol:
#
#   1. the survivor wins ``adoption.claim`` (O_CREAT|O_EXCL),
#   2. it MINTS a new fence epoch next to the victim's journal
#      (:func:`mint_fence` — read-bump-atomic-replace, strictly monotonic
#      because the replace is atomic and minting happens only under the
#      exclusive claim),
#   3. only THEN does it scan the journal (``read_peer_journal``) and adopt.
#
# Every member boots owning the epoch it finds (:func:`read_fence`) and
# re-validates through a :class:`FenceGuard` — one ``os.stat`` per check,
# re-reading the JSON only when (mtime_ns, size, ino) moved — immediately
# before each journal append (inside :meth:`Journal.append`, under the
# journal lock) and each handoff flush.  A SIGSTOP'd zombie is frozen for
# the whole mint-then-scan window, so its first instruction after SIGCONT
# that could touch the journal re-checks the (changed) fence file, sees the
# higher epoch, and raises :class:`Fenced` — structurally before any byte
# of the old epoch reaches a journal the survivor owns.

class Fenced(RuntimeError):
    """This process's fence epoch has been superseded: a survivor holds
    the adoption claim and owns the journal now.  The only safe move is
    to stop writing and self-drain (``fenced:adopted_away``)."""

    def __init__(self, own_epoch: int, current_epoch: int,
                 minted_by: Optional[str] = None):
        self.own_epoch = int(own_epoch)
        self.current_epoch = int(current_epoch)
        self.minted_by = minted_by
        super().__init__(
            f"fenced: epoch {self.own_epoch} superseded by "
            f"{self.current_epoch}"
            + (f" (minted by {minted_by})" if minted_by else "")
        )


def fence_path(base_dir: str) -> str:
    return os.path.join(base_dir, FENCE_FILENAME)


def read_fence(base_dir: str) -> Dict[str, Any]:
    """The current fence doc: ``{"epoch", "minted_by", "time"}``.  A
    missing or unparseable file reads as epoch 0 — safe because the file
    is only ever installed by atomic replace, so a torn final file cannot
    arise from a crash (the property test crashes the mint at every byte
    offset to prove the epoch never regresses)."""
    try:
        with open(fence_path(base_dir), "r") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {"epoch": 0, "minted_by": None, "time": None}
    if not isinstance(doc, dict):
        return {"epoch": 0, "minted_by": None, "time": None}
    try:
        epoch = int(doc.get("epoch") or 0)
    except (TypeError, ValueError):
        epoch = 0
    return {"epoch": epoch, "minted_by": doc.get("minted_by"),
            "time": doc.get("time")}


def mint_fence(base_dir: str, by: Optional[str] = None) -> int:
    """Bump the fence epoch by one and return the new value.

    Write discipline mirrors every manifest in the repo (CT002): full doc
    to a tmp file, flush + fsync, then ONE ``os.replace`` — a crash at any
    byte offset of the tmp write leaves the old fence intact, so epochs
    are strictly monotonic across arbitrary adopt/respawn/re-adopt
    interleavings.  Monotonicity across *concurrent* minters is the
    adoption claim's job: mint only while holding ``adoption.claim``.
    """
    new_epoch = int(read_fence(base_dir)["epoch"]) + 1
    doc = {"epoch": new_epoch, "minted_by": by,
           "time": trace_mod.walltime()}
    path = fence_path(base_dir)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # dir-entry durability is best-effort
    return new_epoch


class FenceGuard:
    """Cheap membership re-validation: holds the epoch this process booted
    with and raises :class:`Fenced` the moment a higher one appears.

    ``check()`` is one ``os.stat`` on the hot path; the JSON is re-read
    only when the file's (mtime_ns, size, ino) signature moves — i.e.
    exactly once per adoption, however many appends happen between.
    """

    def __init__(self, base_dir: str, own_epoch: Optional[int] = None):
        self.base_dir = base_dir
        self.path = fence_path(base_dir)
        self.own_epoch = int(
            read_fence(base_dir)["epoch"] if own_epoch is None else own_epoch
        )
        self._lock = threading.Lock()
        self._cached_sig: Optional[Tuple[int, int, int]] = None
        self._cached_epoch = self.own_epoch
        self._cached_by: Optional[str] = None
        self.checks = 0
        self.rereads = 0

    def current(self) -> int:
        """The last epoch observed (refreshing the cache), without
        raising — the state-doc / progress view uses this."""
        try:
            self.check()
        except Fenced as exc:
            return exc.current_epoch
        return self._cached_epoch

    def check(self) -> None:
        """Raise :class:`Fenced` iff a higher epoch has been minted."""
        try:
            st = os.stat(self.path)
        except OSError:
            return  # never minted: nobody has ever adopted this journal
        sig = (st.st_mtime_ns, st.st_size, st.st_ino)
        with self._lock:
            self.checks += 1
            if sig != self._cached_sig:
                doc = read_fence(self.base_dir)
                self._cached_sig = sig
                self._cached_epoch = max(
                    int(doc["epoch"]), self._cached_epoch
                )
                self._cached_by = doc.get("minted_by")
                self.rereads += 1
            epoch, by = self._cached_epoch, self._cached_by
        if epoch > self.own_epoch:
            raise Fenced(self.own_epoch, epoch, by)


def _frame(record: Dict[str, Any]) -> bytes:
    payload = json.dumps(
        record, separators=(",", ":"), sort_keys=True, default=str
    ).encode()
    return _HEADER.pack(MAGIC, len(payload), zlib.crc32(payload)) + payload


def snapshot_records(ent: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The minimal record sequence that folds back to one request's
    ``fold()`` entry — what rotation writes into the fresh segment so a
    later replay reconstructs the same promises from a bounded file."""
    state = ent.get("state")
    tenant = ent.get("tenant") or "default"
    rid = ent["request_id"]
    out: List[Dict[str, Any]] = []
    if state == REJECTED:
        if ent.get("payload") is not None:
            out.append({"type": ACCEPTED, "request_id": rid,
                        "tenant": tenant, "payload": ent.get("payload"),
                        "fingerprint": ent.get("fingerprint")})
        out.append({"type": REJECTED, "request_id": rid, "tenant": tenant,
                    "code": ent.get("code")})
        return out
    out.append({"type": ACCEPTED, "request_id": rid, "tenant": tenant,
                "payload": ent.get("payload"),
                "fingerprint": ent.get("fingerprint")})
    if ent.get("attempts"):
        out.append({"type": DISPATCHED, "request_id": rid, "tenant": tenant,
                    "attempt": int(ent["attempts"])})
    if state == DRAINED:
        out.append({"type": DRAINED, "request_id": rid, "tenant": tenant})
    elif state in (COMPLETED, FAILED, QUARANTINED):
        out.append({"type": state, "request_id": rid, "tenant": tenant,
                    "record": ent.get("record")})
    return out


def scan(path: str) -> Tuple[List[Dict[str, Any]], int, int]:
    """Read every intact record of ``path`` in append order.

    Returns ``(records, intact_bytes, torn_bytes)``: ``intact_bytes`` is
    the offset of the last frame that framed, CRC'd, and parsed;
    ``torn_bytes`` is whatever trails it (0 for a clean journal).  Missing
    file = ``([], 0, 0)``.  Pure function, stdlib only — the report
    tooling mirrors this framing without importing the runtime.
    """
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return [], 0, 0
    records: List[Dict[str, Any]] = []
    off = 0
    while True:
        header = data[off:off + _HEADER.size]
        if len(header) < _HEADER.size:
            break
        magic, length, crc = _HEADER.unpack(header)
        if magic != MAGIC or length > MAX_RECORD_BYTES:
            break
        payload = data[off + _HEADER.size:off + _HEADER.size + length]
        if len(payload) < length or zlib.crc32(payload) != crc:
            break
        try:
            rec = json.loads(payload)
        except ValueError:
            break
        if not isinstance(rec, dict):
            break
        records.append(rec)
        off += _HEADER.size + length
    return records, off, len(data) - off


def fold(records) -> "OrderedDict[str, Dict[str, Any]]":
    """Collapse the record stream into per-request final state, in first-
    acknowledgement order.

    Each entry: ``{"request_id", "tenant", "payload", "fingerprint",
    "state", "attempts", "record", "code"}`` where ``state`` is the last
    lifecycle type seen, ``attempts`` counts ``dispatched`` records (the
    crash-loop budget), and ``record`` is the terminal request record for
    completed/failed/quarantined entries (the idempotent-answer source).
    A fresh ``accepted`` after a terminal state starts a new incarnation
    of the id — the typed-backpressure protocol is back-off-and-resubmit
    the same id, so a rejected/failed id must be re-acceptable.
    """
    reqs: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
    for rec in records:
        rid = rec.get("request_id")
        typ = rec.get("type")
        if not rid or not typ:
            continue
        ent = reqs.get(rid)
        if typ == ACCEPTED:
            if ent is None or ent["state"] in TERMINAL_TYPES:
                reqs[rid] = {
                    "request_id": rid,
                    "tenant": rec.get("tenant") or "default",
                    "payload": rec.get("payload"),
                    "fingerprint": rec.get("fingerprint"),
                    "state": ACCEPTED,
                    "attempts": 0,
                    "record": None,
                    "code": None,
                }
            # a duplicate accepted for a LIVE id is the racing-submit /
            # client-retry case: the first acknowledgement stands
            continue
        if ent is None:
            if typ == REJECTED:
                # rejected at admission (quota / injected fault): the only
                # transition journaled without a prior accepted
                reqs[rid] = {
                    "request_id": rid,
                    "tenant": rec.get("tenant") or "default",
                    "payload": None,
                    "fingerprint": None,
                    "state": REJECTED,
                    "attempts": 0,
                    "record": None,
                    "code": rec.get("code"),
                }
            continue
        if typ == DISPATCHED:
            ent["state"] = DISPATCHED
            ent["attempts"] = max(
                ent["attempts"] + 1, int(rec.get("attempt") or 0)
            )
        elif typ == DRAINED:
            ent["state"] = DRAINED
            # a graceful drain PROVES the dispatch did not crash the
            # server — rolling SIGTERM restarts of a long-running request
            # must never accrue toward the crash-loop budget, or routine
            # redeploys would quarantine innocent work
            ent["attempts"] = 0
        elif typ in (COMPLETED, FAILED, QUARANTINED):
            ent["state"] = typ
            ent["record"] = rec.get("record")
        elif typ == REJECTED:
            ent["state"] = REJECTED
            ent["code"] = rec.get("code")
    return reqs


class Journal:
    """The append side: one fsync'd CRC-framed record per lifecycle
    transition, serialized under the journal's own lock (never the
    server's admission/request locks — CT010)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fh = None
        #: optional :class:`FenceGuard` — when set, every append re-checks
        #: the fence epoch under the journal lock, immediately before the
        #: write, and raises :class:`Fenced` instead of forking a journal
        #: a survivor owns (docs/SERVING.md "Gray failures")
        self.fence_guard: Optional[FenceGuard] = None
        # stats for /healthz + server_state.json (docs/SERVING.md)
        self.appended = 0
        self.bytes = 0
        self.torn_bytes_truncated = 0
        self.rotations = 0
        self.rotated_from_bytes = 0
        self._last_fsync_mono: Optional[float] = None

    # -- recovery ----------------------------------------------------------
    def recover(self) -> List[Dict[str, Any]]:
        """Read every intact record, truncate a torn tail back to the last
        intact frame (warn, never refuse to boot), and open the file for
        appending.  Must be called before the first :meth:`append`."""
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        records, good, torn = scan(self.path)
        if torn:
            fu.log(
                f"journal {self.path}: torn tail ({torn} byte(s) after "
                f"{len(records)} intact record(s)) — truncating to the "
                "last intact frame"
            )
            with open(self.path, "r+b") as f:
                f.truncate(good)
                f.flush()
                os.fsync(f.fileno())
            self.torn_bytes_truncated = torn
        with self._lock:
            self._fh = open(self.path, "ab")
            self.bytes = good
        return records

    def maybe_rotate(self, folded, max_bytes: Optional[int] = None,
                     keep_terminal: int = 512) -> bool:
        """Size guard, run on clean boot after replay: past ``max_bytes``
        (default :func:`rotate_bytes_default`; <=0 disables), snapshot the
        folded live state into a fresh segment and move the old file to
        ``<path>.old``.  The snapshot (one compact record sequence per
        request, :func:`snapshot_records`) folds back to the same per-
        request promises, so a crash right after rotation replays
        identically — no acknowledged request is ever only in the
        archived segment.  Redundancy collapses (repeat dispatches,
        drain/replay churn, superseded incarnations become one sequence),
        and terminal entries beyond ``keep_terminal`` — the server's
        answerable-record cap; older ids are pruned from its memory and
        cannot be answered idempotently anyway — are dropped, oldest
        first.  One ``.old`` is kept (a later rotation replaces it):
        unbounded growth stops here; richer in-segment compaction stays
        future work (docs/SERVING.md "Durability")."""
        limit = rotate_bytes_default() if max_bytes is None else int(max_bytes)
        if limit <= 0:
            return False
        with self._lock:
            if self._fh is None:  # pragma: no cover - misuse guard
                raise RuntimeError("journal.maybe_rotate before recover()")
            old_bytes = self.bytes
        if old_bytes <= limit:
            return False
        ents = list((folded or {}).values())
        terminal = [e for e in ents if e.get("state") in TERMINAL_TYPES]
        if keep_terminal is not None and len(terminal) > int(keep_terminal):
            drop = {id(e) for e in terminal[:-int(keep_terminal)]}
            ents = [e for e in ents if id(e) not in drop]
        tmp = f"{self.path}.rotate.{os.getpid()}"
        n = 0
        with open(tmp, "wb") as f:
            for ent in ents:
                for rec in snapshot_records(ent):
                    f.write(_frame(rec))
                    n += 1
            f.flush()
            os.fsync(f.fileno())
        with self._lock:
            self._fh.close()
            # crash-window discipline: journal.log must EXIST with either
            # the old or the new content at every instant.  Archive via a
            # hard link (the old inode gains the .old name while keeping
            # the journal name), then ONE atomic replace installs the
            # snapshot — there is no window with the journal missing, so
            # a kill mid-rotation replays identically from the old file.
            old = self.path + ".old"
            try:
                os.remove(old)
            except FileNotFoundError:
                pass
            os.link(self.path, old)
            os.replace(tmp, self.path)
            try:
                dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:
                pass  # dir-entry durability is best-effort
            self._fh = open(self.path, "ab")
            self.bytes = os.path.getsize(self.path)
            self.rotations += 1
            self.rotated_from_bytes = old_bytes
        fu.log(
            f"journal {self.path}: rotated {old_bytes} byte(s) to .old on "
            f"boot (> {limit}); fresh segment holds {n} snapshot record(s)"
        )
        return True

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # -- the one append path (CT010) ---------------------------------------
    def append(self, record: Dict[str, Any]) -> None:
        """Frame, append, and fsync one lifecycle record.  Returns only
        once the record is durable — callers acknowledge state over HTTP
        strictly after this returns, so an acknowledgement always has a
        journal record behind it (SIGKILL included)."""
        frame = _frame(record)
        inj = faults_mod.get_injector()
        with self._lock:
            if self._fh is None:  # pragma: no cover - misuse guard
                raise RuntimeError("journal.append before recover()")
            if self.fence_guard is not None:
                # last possible instant before bytes move: a zombie that
                # was adopted away raises Fenced here, with the frame
                # still un-written
                self.fence_guard.check()
            keep = inj.torn_append()
            if keep is not None:
                # the injected torn write (kind='torn', site='journal'):
                # a strict prefix of the frame reaches the disk and the
                # process dies mid-append — the only way a torn tail
                # arises.  The restarted reader must truncate-and-warn.
                self._fh.write(frame[:max(1, int(len(frame) * keep))])
                self._fh.flush()
                os.fsync(self._fh.fileno())
                faults_mod.hard_exit()
            self._fh.write(frame)
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self.appended += 1
            self.bytes += len(frame)
            self._last_fsync_mono = time.monotonic()
        # crash-after-ackable-write: the record is durable, the in-memory
        # state that mirrors it is not yet published — replay must
        # reconstruct it (chaos kills here to prove that)
        inj.kill_point("journal_append")

    def append_transition(self, typ: str, request_id: str,
                          **fields: Any) -> None:
        """``append`` with the envelope every lifecycle record shares."""
        rec = {"type": typ, "request_id": request_id,
               "time": trace_mod.walltime()}
        rec.update(fields)
        self.append(rec)

    # -- introspection -----------------------------------------------------
    def health(self) -> Dict[str, Any]:
        """The journal block of ``/healthz`` / ``server_state.json``:
        byte size, appended-record count, last-fsync age, and the torn
        bytes recovery truncated at boot."""
        with self._lock:
            last = self._last_fsync_mono
            return {
                "path": self.path,
                "bytes": int(self.bytes),
                "appended": int(self.appended),
                "last_fsync_age_s": (
                    round(time.monotonic() - last, 3)
                    if last is not None else None
                ),
                "torn_bytes_truncated": int(self.torn_bytes_truncated),
                "rotations": int(self.rotations),
                "rotated_from_bytes": int(self.rotated_from_bytes),
            }
