"""The serve plane's one outbound-HTTP doorway (docs/SERVING.md "Gray
failures").

Every HTTP exchange the serving stack makes — the gateway's data-path
calls to members, the health loop's probes, and the client's calls to a
server or gateway — goes through :func:`http_json_call`, which enforces
the two properties gray-failure defense depends on:

- **an explicit deadline on every exchange** (``timeout_s`` is required;
  ctlint CT013 flags any ``HTTPConnection``/``urlopen`` in the package
  that bypasses this module without one).  A wedged far side — SIGSTOP,
  GC pause, dead disk under the accept queue — holds a connection open
  forever; only a wall-clock deadline turns that into a typed, countable
  failure the circuit breaker can act on.
- **the network fault shim** (``runtime/faults.py`` sites ``net_member``
  / ``net_probe`` / ``net_client``): the injector's verdict degrades the
  exchange before any bytes move — ``net_delay`` sleeps, ``net_drop``
  raises ``ConnectionResetError``, ``net_wedge`` blocks until the
  caller's own deadline fires and then raises ``TimeoutError`` — so
  chaos can wedge exactly one member of a fleet and prove the breaker,
  hedging, and fencing layers respond.

:func:`retry_connection` is the shared connection-level retry/backoff
loop the client and gateway previously half-duplicated: it retries ONLY
``OSError``/``ConnectionError`` (the restart/failover window), never
HTTP-level answers — typed rejection codes are the caller's protocol.
"""

from __future__ import annotations

import http.client
import json
import time
from typing import Any, Callable, Dict, Optional, Tuple

from ..utils import function_utils as fu
from . import faults as faults_mod


def http_json_call(
    host: str,
    port: int,
    method: str,
    path: str,
    body: Optional[Dict[str, Any]] = None,
    *,
    timeout_s: float,
    site: str = "net_client",
    member: Optional[str] = None,
) -> Tuple[int, Dict[str, Any]]:
    """One JSON-over-HTTP exchange with an explicit deadline.

    ``timeout_s`` is keyword-required on purpose: an unbounded serve-plane
    wait is exactly the gray failure this PR exists to kill.  ``site`` /
    ``member`` name the exchange for the fault shim (and ``member`` is
    the breaker's key on the gateway side).  Raises ``OSError`` subtypes
    for every connection-level failure — refused, reset, and the deadline
    firing — so callers classify with one ``except (OSError, ValueError)``.
    """
    timeout_s = float(timeout_s)
    verdict = faults_mod.get_injector().net_fault(site, member=member)
    if verdict is not None:
        kind, seconds = verdict
        if kind == "net_delay":
            # congestion / a GC pause on the far side: late, not lost
            time.sleep(seconds)
        elif kind == "net_drop":
            raise ConnectionResetError(
                f"injected net_drop at {site}"
                + (f" (member {member})" if member else "")
            )
        elif kind == "net_wedge":
            # an accepted connection that never answers: nothing moves
            # until the caller's own deadline fires — the sleep is capped
            # at that deadline so the model is exact and tests terminate
            time.sleep(min(seconds, timeout_s))
            raise TimeoutError(
                f"injected net_wedge at {site}"
                + (f" (member {member})" if member else "")
                + f": no answer within {timeout_s:g}s"
            )
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout_s)
    try:
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"} if data else {}
        conn.request(method, path, body=data, headers=headers)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def retry_connection(
    fn: Callable[[], Tuple[int, Dict[str, Any]]],
    retry_s: Optional[float],
    on_retry: Optional[Callable[[], None]] = None,
    base_s: float = 0.05,
    cap_s: float = 1.0,
) -> Tuple[int, Dict[str, Any]]:
    """Run ``fn`` (one :func:`http_json_call`-shaped exchange), retrying
    connection-level failures with capped backoff for up to ``retry_s``
    seconds.  ``on_retry`` runs between attempts (the client re-reads its
    endpoint file there — a restarted server binds a fresh port).  With
    no budget the first failure propagates; HTTP answers never retry."""
    deadline = None if not retry_s else time.monotonic() + float(retry_s)
    attempt = 0
    while True:
        try:
            return fn()
        except (OSError, ConnectionError):
            if deadline is None or time.monotonic() >= deadline:
                raise
            time.sleep(fu.backoff_delay(attempt, base_s, cap_s))
            attempt += 1
            if on_retry is not None:
                on_retry()
