"""Lineage-driven block repair: recompute a corrupt product block from its
producing task's inputs (docs/SERVING.md "Self-healing").

Detection without repair only converts silent corruption into loud
corruption.  The task DAG already knows each block's lineage — the
executor's store path holds the exact triple (``load_fn``, kernel,
``store_fn``) that produced every verified block, and the host scaffold
(``host_block_map``) holds the equivalent ``process(block_id)`` — so after
each verified store those layers register a **producer**: a recompute
closure keyed by ``(dataset label, region)``.  When the verifying reader
(:mod:`cluster_tools_tpu.io.verified`) or the resident scrubber
(:mod:`cluster_tools_tpu.runtime.scrub`) detects a digest mismatch, the
repair engine re-runs that closure — re-loading the producing task's
inputs at block grain, re-executing the kernel, re-publishing through the
ordinary store path (fresh digest sidecar recorded atomically with the
region write, cache coherence included) — then re-verifies the stored
bytes against the new sidecar.

Degrade ladder: a repair whose recompute fails (the producing task's own
inputs are damaged, the kernel faults, or the re-stored bytes *still*
mismatch) burns one unit of the region's **repair budget**
(``CTT_REPAIR_BUDGET``, default 2).  An exhausted budget quarantines the
region — ``quarantined:unrepairable`` in ``failures.json`` (unresolved:
the data is damaged beyond the lineage's reach and an operator must act)
— and further reads fail fast with the typed ``corrupt:<site>`` instead
of looping.  Corrupt *inputs* read during a recompute recurse into their
own producers (lineage repair cascades up the DAG); a region already
being repaired on this thread is never re-entered.

The registry is process-resident and bounded (``CTT_REPAIR_REGISTRY_MAX``
entries, LRU): closures pin their task's captured state, so under a
resident server old requests' producers age out instead of accreting.  A
restarted process has an empty registry — at-rest corruption found after
a restart is unrepairable until the producing task re-runs, which is the
recompute-from-markers story, not this module's.

Every outcome is attributed: ``repaired:lineage`` (resolved) /
``quarantined:unrepairable`` records in the producing task's
``failures.json``, matching trace instants on the unified timeline, and
:func:`stats` counters for ``/healthz`` and ``failures_report.py --json``.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

from ..utils import function_utils as fu
from . import trace as trace_mod

#: failures.json resolution strings (docs/ROBUSTNESS.md)
REPAIRED_LINEAGE = "repaired:lineage"
QUARANTINE_UNREPAIRABLE = "quarantined:unrepairable"

_DEFAULT_BUDGET = 2
_DEFAULT_REGISTRY_MAX = 4096

_lock = threading.Lock()
_producers: "OrderedDict[Tuple[str, tuple], Dict[str, Any]]" = OrderedDict()
_failed_attempts: Dict[Tuple[str, tuple], int] = {}
_quarantined: set = set()
_counters: Dict[str, int] = {
    "registered": 0,
    "attempted": 0,
    "repaired": 0,
    "failed": 0,
    "no_lineage": 0,
    "unrepairable": 0,
}
_tls = threading.local()


def repair_budget() -> int:
    """Failed recomputes a region may burn before it is quarantined as
    unrepairable (``CTT_REPAIR_BUDGET``)."""
    try:
        return max(1, int(os.environ.get("CTT_REPAIR_BUDGET", "") or
                          _DEFAULT_BUDGET))
    except ValueError:
        return _DEFAULT_BUDGET


def registry_max() -> int:
    try:
        return max(1, int(os.environ.get("CTT_REPAIR_REGISTRY_MAX", "") or
                          _DEFAULT_REGISTRY_MAX))
    except ValueError:
        return _DEFAULT_REGISTRY_MAX


def _region_of(dataset, bb) -> Optional[tuple]:
    from ..io import containers as _c

    return _c._norm_region(bb, dataset.shape)


def _key_of(dataset, region) -> Optional[Tuple[str, tuple]]:
    label = getattr(dataset, "_label", None)
    if label is None or region is None:
        return None
    return (str(label), tuple(tuple(r) for r in region))


def register_producer(
    dataset,
    bb,
    recompute,
    task: str = "",
    block_id: Optional[int] = None,
    failures_path: Optional[str] = None,
) -> bool:
    """Record block lineage after a verified store: ``recompute()`` must
    re-load the producing task's inputs for this block, re-run its kernel,
    and re-store through the ordinary (sidecar-recording) write path.
    Called by the executor / host scaffold — tasks never wire it.  Returns
    False when the dataset has no stable identity to key on."""
    region = _region_of(dataset, bb)
    key = _key_of(dataset, region)
    if key is None or recompute is None:
        return False
    ent = {
        "recompute": recompute,
        "task": str(task or "unknown"),
        "block_id": block_id,
        "failures_path": failures_path,
    }
    with _lock:
        _producers[key] = ent
        _producers.move_to_end(key)
        while len(_producers) > registry_max():
            _producers.popitem(last=False)
        _counters["registered"] += 1
        # a fresh (re)store is new truth: damage history of the OLD bytes
        # must not pre-quarantine it
        _failed_attempts.pop(key, None)
        _quarantined.discard(key)
    # storage-backed product stores become scrub targets the moment they
    # have lineage — the scrubber can both find AND heal their rot
    from . import scrub as scrub_mod

    scrub_mod.register_target(dataset)
    return True


def _attribute(ent: Dict[str, Any], site: str, resolution: str,
               error: Optional[str], resolved: bool,
               quarantined: bool) -> None:
    path = ent.get("failures_path")
    if not path:
        return
    try:
        fu.record_failures(
            path,
            ent.get("task") or "repair",
            [{
                "block_id": ent.get("block_id"),
                "sites": {site: 1},
                "error": error,
                "quarantined": bool(quarantined),
                "resolved": bool(resolved),
                "resolution": resolution,
            }],
        )
    except Exception:
        pass  # attribution is best-effort; the repair outcome stands


def attempt_repair(dataset, region, site: str) -> bool:
    """Recompute one corrupt region from lineage; True when the stored
    bytes verify again.  Never raises — the caller (verifying reader /
    scrubber) owns the typed failure."""
    region = tuple(tuple(r) for r in region)
    key = _key_of(dataset, region)
    if key is None:
        return False
    in_flight = getattr(_tls, "keys", None)
    if in_flight is None:
        in_flight = _tls.keys = set()
    if key in in_flight:
        return False  # recursion guard: this thread is already inside it
    with _lock:
        ent = _producers.get(key)
        if ent is not None:
            _producers.move_to_end(key)
        already_dead = key in _quarantined
        used = _failed_attempts.get(key, 0)
        _counters["attempted"] += 1
        if ent is None:
            _counters["no_lineage"] += 1
    if ent is None or already_dead or used >= repair_budget():
        return False
    bb = tuple(slice(a, b) for a, b in region)
    in_flight.add(key)
    # the recompute IS the producing task's work: fault targeting must see
    # it as such (a task-gated fault armed for the READING task would
    # otherwise fire inside the healing path and rot the producer's
    # inputs), and its attribution belongs to the producer
    from . import faults as faults_mod

    prev_task = faults_mod.current_task()
    try:
        faults_mod.set_current_task(ent.get("task") or None)
        with trace_mod.span(
            "repair.lineage", site=site, task=ent.get("task") or "",
            block=int(ent["block_id"]) if ent.get("block_id") is not None
            else -1,
        ):
            ent["recompute"]()
            verify = getattr(dataset, "verify_region", None)
            if verify is not None:
                verify(bb)
    except Exception as e:
        with _lock:
            used = _failed_attempts.get(key, 0) + 1
            _failed_attempts[key] = used
            _counters["failed"] += 1
            exhausted = used >= repair_budget() and key not in _quarantined
            if exhausted:
                _quarantined.add(key)
                _counters["unrepairable"] += 1
        if exhausted:
            _attribute(
                ent, site, QUARANTINE_UNREPAIRABLE,
                f"repair budget ({repair_budget()}) exhausted for "
                f"{key[0]} region {region}: last error: {e!r}",
                resolved=False, quarantined=True,
            )
            trace_mod.instant(
                QUARANTINE_UNREPAIRABLE, site=site,
                task=ent.get("task") or "",
            )
        return False
    finally:
        faults_mod.set_current_task(prev_task)
        in_flight.discard(key)
    with _lock:
        _failed_attempts.pop(key, None)
        _counters["repaired"] += 1
    _attribute(ent, site, REPAIRED_LINEAGE, None, resolved=True,
               quarantined=False)
    trace_mod.instant(
        REPAIRED_LINEAGE, site=site, task=ent.get("task") or "",
    )
    return True


def stats() -> Dict[str, int]:
    """Repair-engine counters (docs/OBSERVABILITY.md): registered
    producers, repair attempts/successes/failures, corrupt regions with
    no lineage, and regions quarantined as unrepairable."""
    with _lock:
        doc = dict(_counters)
        doc["producers"] = len(_producers)
        return doc


def reset() -> None:
    """Drop all lineage state (tests)."""
    with _lock:
        _producers.clear()
        _failed_attempts.clear()
        _quarantined.clear()
        for k in _counters:
            _counters[k] = 0
