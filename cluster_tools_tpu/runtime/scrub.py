"""The resident scrubber: background verify-at-rest for stored block
products, with lineage repair on mismatch (docs/SERVING.md
"Self-healing").

The verifying reader only checks bytes somebody reads; cold data rots
unobserved.  :class:`Scrubber` is the server-resident loop that walks
digest-sidecar manifests, re-reads a *budgeted* number of bytes per
interval straight from storage (``verify_region`` bypasses the chunk
cache on purpose — the scrub must see the disk), and hands every mismatch
to :mod:`cluster_tools_tpu.runtime.repair`.  Rate limiting is two knobs:
``interval_s`` between scan slices and ``bytes_per_interval`` of region
data verified per slice — the scrub tax on a loaded server stays small
and constant (the <5 % bar of docs/SERVING.md) while still bounding the
time-to-detection for any given corpus size.

Work discovery is two planes, deduplicated by dataset label:

- the **live registry** (:func:`register_target`): every storage-backed
  product store that registers lineage (``repair.register_producer``)
  becomes a scrub target in the same process — these are the datasets the
  scrubber can both find *and* heal;
- **root walking**: directories handed to the scrubber (the server's
  ``base_dir`` plus configured roots) are searched for ``.ctt_checksums``
  sidecar dirs, so at-rest products from *previous* incarnations are
  still verified after a restart (found-but-unrepairable rot is
  attributed, not hidden).

The scrubber pauses while a drain is requested (a SIGTERM'd server spends
its grace period finishing requests, not scrubbing) and reports through
``scrub_state.json`` (next to ``failures.json``), the ``/healthz`` and
``/status`` scrub blocks, and ``make progress``
(docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils import function_utils as fu
from . import repair as repair_mod
from . import trace as trace_mod
from .supervision import drain_requested

STATE_FILENAME = "scrub_state.json"

_DEFAULT_INTERVAL_S = 5.0
_DEFAULT_BYTES_PER_INTERVAL = 16 << 20
_TARGET_MAX = 256
_WALK_DIR_CAP = 2000

_reg_lock = threading.Lock()
#: label -> dataset; storage-backed product stores registered by the
#: repair engine (bounded LRU — a resident server must not accrete
#: handles for every dataset it ever touched)
_targets: "OrderedDict[str, Any]" = OrderedDict()


def register_target(dataset) -> bool:
    """Enlist a dataset for background scrubbing.  Only storage-backed
    sidecar indexes qualify (in-memory handoffs die with their request;
    their spilled copies re-register through the spill's store path)."""
    checks = getattr(dataset, "_checksums", None)
    label = getattr(dataset, "_label", None)
    if checks is None or label is None or getattr(checks, "_dir", None) is None:
        return False
    with _reg_lock:
        _targets[str(label)] = dataset
        _targets.move_to_end(str(label))
        while len(_targets) > _TARGET_MAX:
            _targets.popitem(last=False)
    return True


def registered_targets() -> List[Tuple[str, Any]]:
    with _reg_lock:
        return list(_targets.items())


def reset_targets() -> None:
    """Drop the registry (tests)."""
    with _reg_lock:
        _targets.clear()


def _container_of(sidecar_dir: str) -> Optional[Tuple[str, str]]:
    """Map ``<container>/<key...>/.ctt_checksums`` to (container, key)."""
    from ..io.containers import _ZARR_EXTS

    ds_dir = os.path.dirname(os.path.abspath(sidecar_dir))
    probe = ds_dir
    while True:
        parent = os.path.dirname(probe)
        if probe.lower().endswith(_ZARR_EXTS):
            key = os.path.relpath(ds_dir, probe)
            return (probe, key) if key not in (".", "") else None
        if parent == probe:
            return None
        probe = parent


def discover_targets(roots) -> List[Tuple[str, str]]:
    """(container, key) pairs found by walking ``roots`` for sidecar
    dirs — the at-rest plane that survives process restarts.  The walk is
    capped (``_WALK_DIR_CAP`` dirs) so a pathological tree cannot wedge a
    scrub slice."""
    found: List[Tuple[str, str]] = []
    seen = set()
    budget = _WALK_DIR_CAP
    for root in roots or ():
        if not root or not os.path.isdir(root):
            continue
        for dirpath, dirnames, _files in os.walk(root):
            budget -= 1
            if budget <= 0:
                return found
            if os.path.basename(dirpath) != ".ctt_checksums":
                continue
            dirnames[:] = []
            pair = _container_of(dirpath)
            if pair is not None and pair not in seen:
                seen.add(pair)
                found.append(pair)
    return found


class Scrubber:
    """The server-resident background verifier (see module docstring).

    Thread-owned state only; ``stats()`` snapshots under the lock for the
    health surfaces.  ``scan_once()`` is also the synchronous entry point
    the smoke test and an operator REPL can drive without the thread."""

    def __init__(
        self,
        base_dir: Optional[str] = None,
        interval_s: float = _DEFAULT_INTERVAL_S,
        bytes_per_interval: int = _DEFAULT_BYTES_PER_INTERVAL,
        roots: Optional[List[str]] = None,
        enabled: bool = True,
    ):
        self.base_dir = os.path.abspath(base_dir) if base_dir else None
        self.interval_s = max(0.05, float(interval_s))
        self.bytes_per_interval = max(1, int(bytes_per_interval))
        self.roots = [os.path.abspath(r) for r in (roots or []) if r]
        if self.base_dir and self.base_dir not in self.roots:
            self.roots.append(self.base_dir)
        self.enabled = bool(enabled)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._open_cache: "OrderedDict[Tuple[str, str], Any]" = OrderedDict()
        self._offset = 0
        self._scanned_in_pass = 0
        self._worklist_len = 0
        self._position: Optional[Dict[str, Any]] = None
        self._last_corrupt: Optional[Dict[str, Any]] = None
        self._counts = {
            "passes": 0, "scanned_regions": 0, "scanned_bytes": 0,
            "found_corrupt": 0, "repaired": 0, "unrepairable": 0,
            "errors": 0,
        }

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Scrubber":
        if not self.enabled or self._thread is not None:
            return self
        # the state file exists from boot: report consumers can tell "a
        # scrubber is on, nothing scanned yet" from "no scrubber at all"
        self._write_state()
        self._thread = threading.Thread(
            target=self._loop, name="scrubber", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if drain_requested():
                continue  # the grace period belongs to in-flight requests
            try:
                self.scan_once()
            except Exception:
                with self._lock:
                    self._counts["errors"] += 1

    # -- one budgeted slice ------------------------------------------------
    def _open_dataset(self, container: str, key: str):
        from ..io.containers import open_container

        ck = (container, key)
        ds = self._open_cache.get(ck)
        if ds is None:
            ds = open_container(container, "a")[key]
            self._open_cache[ck] = ds
            while len(self._open_cache) > _TARGET_MAX:
                self._open_cache.popitem(last=False)
        return ds

    def _worklist(self) -> List[Tuple[str, Any, tuple]]:
        """(label, dataset, region) triples across both discovery planes,
        label-deduplicated, in a stable order so the cursor is
        meaningful."""
        by_label: "OrderedDict[str, Any]" = OrderedDict()
        for label, ds in registered_targets():
            by_label[label] = ds
        for container, key in discover_targets(self.roots):
            label = f"{container}:{key}"
            if label in by_label:
                continue
            try:
                by_label[label] = self._open_dataset(container, key)
            except Exception:
                with self._lock:
                    self._counts["errors"] += 1
        work: List[Tuple[str, Any, tuple]] = []
        for label in sorted(by_label):
            ds = by_label[label]
            try:
                regions = sorted(ds.checksum_regions())
            except Exception:
                with self._lock:
                    self._counts["errors"] += 1
                continue
            work.extend((label, ds, tuple(r)) for r in regions)
        return work

    @staticmethod
    def _region_nbytes(ds, bb) -> int:
        entry = None
        probe = getattr(ds, "checksum_entry", None)
        if probe is not None:
            try:
                entry = probe(bb)
            except Exception:
                entry = None
        if not entry:
            return 0
        try:
            return int(
                np.prod(entry.get("shape") or [0], dtype=np.int64)
                * np.dtype(entry.get("dtype") or "u1").itemsize
            )
        except Exception:
            return 0

    def _verify_one(self, label: str, ds, region) -> int:
        from ..io.containers import ChunkCorruptionError

        bb = tuple(slice(a, b) for a, b in region)
        nbytes = self._region_nbytes(ds, bb)
        try:
            ds.verify_region(bb)
        except ChunkCorruptionError:
            try:
                # double-check before crying rot: a live writer can land
                # region bytes a beat before its fresh sidecar (write,
                # then record) — the re-verify re-reads BOTH, so only
                # damage that holds still twice counts as corruption
                ds.verify_region(bb)
                return nbytes
            except ChunkCorruptionError:
                pass
            trace_mod.instant("scrub.corrupt", dataset=label)
            healed = repair_mod.attempt_repair(ds, region, "scrub")
            with self._lock:
                self._counts["found_corrupt"] += 1
                self._counts["repaired" if healed else "unrepairable"] += 1
                self._last_corrupt = {
                    "dataset": label,
                    "region": [list(r) for r in region],
                    "repaired": bool(healed),
                }
        except Exception:
            with self._lock:
                self._counts["errors"] += 1
        return nbytes

    def scan_once(self, budget_bytes: Optional[int] = None) -> int:
        """Verify up to ``budget_bytes`` of recorded regions, resuming at
        the cursor; returns regions scanned.  Wrapping the worklist
        completes a pass (full-corpus coverage)."""
        budget = int(budget_bytes or self.bytes_per_interval)
        work = self._worklist()
        n = len(work)
        with self._lock:
            self._worklist_len = n
            if n == 0:
                self._offset = 0
                self._scanned_in_pass = 0
                self._position = None
        if n == 0:
            self._write_state()
            return 0
        scanned = 0
        with trace_mod.span("scrub.slice", regions=n):
            while budget > 0 and scanned < n and not self._stop.is_set():
                idx = self._offset % n
                label, ds, region = work[idx]
                nbytes = self._verify_one(label, ds, region)
                budget -= max(1, nbytes)
                scanned += 1
                with self._lock:
                    self._counts["scanned_regions"] += 1
                    self._counts["scanned_bytes"] += nbytes
                    self._scanned_in_pass += 1
                    self._offset = idx + 1
                    if self._offset >= n:
                        self._offset = 0
                        self._counts["passes"] += 1
                        self._scanned_in_pass = 0
                    self._position = {
                        "dataset": label,
                        "index": self._offset,
                        "of": n,
                    }
        self._write_state()
        return scanned

    # -- reporting ---------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """The scrub block of ``/healthz`` / ``/status`` /
        ``scrub_state.json``: counters, cursor position, and pass
        coverage, plus the verifying-reader and repair-engine counters it
        cross-checks (docs/OBSERVABILITY.md)."""
        from ..io import verified as verified_mod

        with self._lock:
            doc: Dict[str, Any] = dict(self._counts)
            n = self._worklist_len
            doc.update({
                "enabled": self.enabled,
                "interval_s": self.interval_s,
                "bytes_per_interval": self.bytes_per_interval,
                "targets": len(_targets),
                "known_regions": n,
                "position": dict(self._position) if self._position else None,
                "last_corrupt": (
                    dict(self._last_corrupt) if self._last_corrupt else None
                ),
                "coverage": (
                    round(self._scanned_in_pass / n, 4) if n else None
                ),
            })
        doc["reader"] = verified_mod.stats()
        doc["repair"] = repair_mod.stats()
        return doc

    def _write_state(self) -> None:
        if not self.base_dir:
            return
        doc = {"version": 1, "time": trace_mod.walltime()}
        doc.update(self.stats())
        try:
            fu.atomic_write_json(
                os.path.join(self.base_dir, STATE_FILENAME), doc
            )
        except OSError:
            pass  # best-effort: the scrubber outlives a full disk
