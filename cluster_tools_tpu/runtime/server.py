"""Service mode: a resident, multi-tenant pipeline server (docs/SERVING.md).

The batch CLI pays the cold tax on every invocation — compiled programs,
the decompressed-chunk cache, and in-memory handoffs all die with the
process (BENCH_r06 prices the difference at ~10x for the RAG+solve shape).
:class:`PipelineServer` is the resident owner those assets were waiting
for: one long-lived process that accepts concurrent workflow requests (the
existing task DAGs — watershed, connected_components, multicut, inference)
over a local HTTP endpoint, executes them on a small worker pool, and
keeps every process-wide cache warm across requests.

Admission is per-tenant (``runtime/admission.py``): quotas on queue depth,
concurrent workflows, and bytes in flight, deficit-round-robin dispatch
between tenants, queue deadlines, and typed backpressure errors — every
rejection is recorded in the server's ``failures.json`` (resolution
``rejected:*``), so admission failures are attributed like any other
fault.  Each request runs under an ambient request context: handoff
identities are namespaced by request id (two concurrent requests over the
same dataset paths can never resolve each other's intermediates), the
executor caps its inflight byte budget at the tenant's share, and a
``task.run``-shaped trace span brackets the whole request so a
``CTT_TRACE=<dir>``-pinned server lands every request's spans on one
resident-process timeline.

The operational surface is the planes the runtime already ships:

- **Drain**: SIGTERM flips the PR-4 drain latch — admission stops,
  in-flight requests drain at their safe block/task boundaries (markers +
  manifests flushed), queued ones stay recorded for resubmission, and the
  entry point exits ``REQUEUE_EXIT_CODE`` (114) so rolling restarts ride
  the same protocol as every other preempted job.
- **Status**: ``GET /status`` returns the machine-readable run report
  (the ``failures_report.py --json`` document over the server's base
  directory) plus live per-tenant admission stats; its ``rc`` field
  preserves the report's exit-code semantics (1 on unresolved failures /
  torn manifests).
- **Progress**: the server heartbeats (``heartbeats/server.json``) and
  maintains ``server_state.json`` next to its ``failures.json``;
  ``scripts/progress.py`` renders per-tenant queue depth / in-flight /
  completed alongside the block-marker view (``make progress`` against a
  live server).

Resident-owner handoff policy (docs/PERFORMANCE.md "Task-graph fusion"):
``memory_handoffs`` defaults ON for the in-process workflows the server
owns (cluster targets stay off — their memory dies with the remote job).
When a request completes, its live *dataset* handoffs are written back to
storage (the client-visible durability contract) and every entry of its
namespace is released, so a resident process never accretes dead request
state and rejected/failed requests leave no orphaned handoff entries.

Durability (docs/SERVING.md "Durability"): every request lifecycle
transition is an fsync'd, CRC-framed record in the submission journal
(``runtime/journal.py``) written *before* the state is acknowledged over
HTTP, and :meth:`PipelineServer.start` replays the journal before binding
the endpoint — completed requests answer duplicate resubmits idempotently
from their recorded results, acknowledged-but-incomplete requests are
re-enqueued with their original tenant/payload and resume at block grain,
tenant admission counters are reconstructed, and a request whose replay
keeps crashing the server is quarantined (``quarantined:crash_loop``)
after ``max_replay_attempts`` instead of wedging the restart loop.
"""

from __future__ import annotations

import hashlib
import importlib
import json
import os
import shutil
import socket
import threading
import time
import traceback
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from ..utils import function_utils as fu
from ..utils import task_utils as tu
from . import admission as admission_mod
from . import executor as executor_mod
from . import faults as faults_mod
from . import handoff as handoff_mod
from . import journal as journal_mod
from . import netio
from . import scrub as scrub_mod
from . import trace as trace_mod
from .supervision import (
    DrainInterrupt,
    HeartbeatWriter,
    drain_reason,
    drain_requested,
)

SERVER_UID = "server"
STATE_FILENAME = "server_state.json"
ENDPOINT_FILENAME = "server.json"

#: the crash-loop quarantine resolution recorded in failures.json when a
#: replayed request has crashed the server ``max_replay_attempts`` times
QUARANTINE_CRASH_LOOP = "quarantined:crash_loop"

#: failures.json resolution recorded when this member discovers it was
#: fenced — declared dead and adopted away while wedged (docs/SERVING.md
#: "Gray failures").  The member self-drains and exits
#: ``FENCED_EXIT_CODE`` without another journal byte or store write.
FENCED_RESOLUTION = "fenced:adopted_away"

#: completed/terminal request records kept in memory (oldest pruned)
_MAX_RECORDS = 512

_CLUSTER_TARGETS = ("slurm", "lsf")

#: request-record states the journal's terminal record types map to
_JOURNAL_TERMINAL = {"done": journal_mod.COMPLETED,
                     "failed": journal_mod.FAILED,
                     "drained": journal_mod.DRAINED}


def _payload_fingerprint(payload: Dict[str, Any]) -> str:
    """Canonical digest of a submission payload: a resubmit with the SAME
    fingerprint under a live/terminal id is the client's retry of an
    acknowledged request (answered idempotently), a different one is a
    real id collision (``rejected:duplicate``)."""
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True, default=str).encode()
    ).hexdigest()[:16]


def _resolve_workflow(name: str):
    """A workflow class from the CLI registry (``cluster_tools_tpu.cli.
    WORKFLOWS``) or an explicit ``module:Class`` spec."""
    from ..cli import WORKFLOWS

    spec = WORKFLOWS.get(name, name)
    if ":" not in spec:
        raise ValueError(
            f"unknown workflow {name!r}; known: {sorted(WORKFLOWS)} "
            "(or pass an explicit 'module:Class' spec)"
        )
    mod_name, cls_name = spec.split(":", 1)
    return getattr(importlib.import_module(mod_name), cls_name)


class PipelineServer:
    """The resident server: admission controller + worker pool + HTTP
    endpoint + state/heartbeat files.  See the module docstring for the
    architecture and docs/SERVING.md for the operator guide."""

    def __init__(
        self,
        base_dir: str,
        tenants: Optional[Dict[str, Dict[str, Any]]] = None,
        default_quota: Optional[Dict[str, Any]] = None,
        max_workers: int = 2,
        default_est_bytes: int = 0,
        default_max_jobs: int = 2,
        host: str = "127.0.0.1",
        port: int = 0,
        journal: bool = True,
        max_replay_attempts: int = 3,
        program_cache_size: Optional[int] = None,
        scrub: Optional[Dict[str, Any]] = None,
        journal_rotate_bytes: Optional[int] = None,
    ):
        self.base_dir = os.path.abspath(base_dir)
        os.makedirs(self.base_dir, exist_ok=True)
        self.failures_path = fu.failures_path(self.base_dir)
        self.default_est_bytes = int(default_est_bytes)
        self.default_max_jobs = int(default_max_jobs)
        self.max_workers = max(1, int(max_workers))
        self.max_replay_attempts = max(1, int(max_replay_attempts))
        #: boot-time journal size guard (docs/SERVING.md "Durability"):
        #: past this many bytes a clean boot snapshots live state into a
        #: fresh segment and archives the old one as journal.log.old.
        #: Coerced here so a malformed config value fails loudly at
        #: construction, not inside the boot's best-effort rotation.
        self.journal_rotate_bytes = (
            None if journal_rotate_bytes is None
            else int(journal_rotate_bytes)
        )
        # the resident scrubber (docs/SERVING.md "Self-healing"): walks
        # digest sidecars of the products this server owns, verifies a
        # budgeted number of bytes per interval, repairs from lineage.
        # Config: {"enabled", "interval_s", "bytes_per_interval",
        # "roots"}; default on with the module's modest budget.
        scrub_cfg = dict(scrub or {})
        scrub_roots = [self.base_dir] + list(scrub_cfg.pop("roots", []) or [])
        self.scrubber: Optional[scrub_mod.Scrubber] = scrub_mod.Scrubber(
            base_dir=self.base_dir,
            roots=scrub_roots,
            **scrub_cfg,
        )
        # the durable submission journal (docs/SERVING.md "Durability");
        # off only for embedders that explicitly opt out of the ack
        # contract (tests of the pre-journal paths)
        self._journal: Optional[journal_mod.Journal] = (
            journal_mod.Journal(journal_mod.journal_path(self.base_dir))
            if journal else None
        )
        #: replay outcome of the LAST start(): rendered by /healthz,
        #: server_state.json, and scripts/progress.py
        self._replay_stats = {"replayed": 0, "reenqueued": 0,
                              "quarantined": 0}
        quotas = {
            name: admission_mod.TenantQuota.from_config(doc)
            for name, doc in (tenants or {}).items()
        }
        self.controller = admission_mod.AdmissionController(
            quotas=quotas,
            default_quota=admission_mod.TenantQuota.from_config(default_quota),
            on_reject=self._on_reject,
        )
        self._requests: "Dict[str, Dict[str, Any]]" = {}
        self._requests_lock = threading.Lock()
        #: fencing (docs/SERVING.md "Gray failures"): armed in start(),
        #: re-validated before every journal append + handoff flush; set
        #: once a higher epoch is discovered — the self-drain trigger
        self._fence_guard: Optional[journal_mod.FenceGuard] = None
        self._fenced_exc: Optional[journal_mod.Fenced] = None
        self._reject_seq = 0
        self._order: List[str] = []  # insertion order, for pruning
        #: journal adoptions this incarnation performed (fleet failover;
        #: docs/SERVING.md "Fleet") — surfaced in server_state.json
        self._adoptions: List[Dict[str, Any]] = []
        self._workers: List[threading.Thread] = []
        self._stop = threading.Event()
        self._heartbeat: Optional[HeartbeatWriter] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self.host = host
        self.port = int(port)
        self.started_at = trace_mod.walltime()
        # server-scoped compiled-program cache (ROADMAP item-1 residual):
        # the PR-7 executor cache is instance-scoped, so a repeat request
        # re-traced its kernels even when jax's compile cache was warm.
        # The server owns one identity-keyed cache shared by every
        # executor its request tasks build (kernel code + frozen captured
        # config = identity, see executor.kernel_identity), sharpening the
        # warm split for repeat requests.  Batch entry points never
        # install one — instance scope stays the one-shot default.
        # ``program_cache_size=0`` disables.
        if program_cache_size is None:
            program_cache_size = executor_mod.SHARED_PROGRAM_CACHE_SIZE
        self.program_cache: Optional[executor_mod.ProgramCache] = (
            executor_mod.ProgramCache(
                max_size=int(program_cache_size), by_identity=True
            )
            if int(program_cache_size) > 0 else None
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "PipelineServer":
        """Recover + replay the submission journal, then bind the
        endpoint and start workers + heartbeat, and write the endpoint
        file clients discover the port from.  Replay runs BEFORE the
        bind on purpose: a client reconnecting across the restart can
        never observe a window where an acknowledged request is
        missing."""
        if trace_mod.enabled():
            # one resident-process timeline: every request's spans land in
            # the server's trace dir (an operator CTT_TRACE=<dir> pin
            # targeting the same place is equivalent and also sticks)
            trace_mod.set_trace_dir(
                os.path.join(self.base_dir, trace_mod.TRACE_DIRNAME)
            )
        # fence ownership (docs/SERVING.md "Gray failures"): boot owning
        # whatever epoch is current — a respawned member adopts the epoch
        # its respawn minted.  From here every journal append and handoff
        # flush re-validates the epoch (one cached stat); a higher one
        # means a survivor adopted this journal and we must self-drain.
        self._fence_guard = journal_mod.FenceGuard(self.base_dir)
        if self._journal is not None:
            self._journal.fence_guard = self._fence_guard
        self._recover_journal()
        self._httpd = ThreadingHTTPServer(
            (self.host, self.port), _RequestHandler
        )
        # installed only once the risky startup steps (journal recovery,
        # endpoint bind) have succeeded, and uninstalled again on ANY
        # later start failure: a process whose server never came up must
        # keep the batch instance scope — every executor a request task
        # builds shares this cache only for the server's lifetime
        if self.program_cache is not None:
            executor_mod.install_shared_program_cache(self.program_cache)
        try:
            self._httpd.pipeline = self  # type: ignore[attr-defined]
            self._httpd.daemon_threads = True
            self.port = self._httpd.server_address[1]
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever, name="serve-http",
                daemon=True,
            )
            self._http_thread.start()
            self._heartbeat = HeartbeatWriter(
                self.base_dir, SERVER_UID, interval_s=2.0
            ).start()
            if self.scrubber is not None:
                self.scrubber.start()
            for i in range(self.max_workers):
                t = threading.Thread(
                    target=self._worker_loop, name=f"serve-worker-{i}",
                    daemon=True,
                )
                t.start()
                self._workers.append(t)
            fu.atomic_write_json(
                os.path.join(self.base_dir, ENDPOINT_FILENAME),
                {
                    "host": self.host,
                    "port": self.port,
                    "pid": os.getpid(),
                    "hostname": socket.gethostname(),
                    "time": trace_mod.walltime(),
                },
            )
            self._write_state()
        except BaseException:
            # a server that failed to come up must not leave the
            # identity-keyed cache installed process-wide
            if (self.program_cache is not None
                    and executor_mod.shared_program_cache()
                    is self.program_cache):
                executor_mod.install_shared_program_cache(None)
            raise
        return self

    def serve_until_drained(self, poll_s: float = 0.2) -> None:
        """Block until the drain latch flips (SIGTERM/SIGUSR1), then drain:
        stop admission AND dispatch, let in-flight requests finish at
        their safe boundaries, flush state, and raise
        :class:`DrainInterrupt` for the entry point to map to
        ``REQUEUE_EXIT_CODE`` (docs/ANALYSIS.md CT006/CT009).  The caller
        must have installed the drain handler
        (:func:`~cluster_tools_tpu.runtime.supervision.
        install_drain_handler`)."""
        while not drain_requested():
            if self._fenced_exc is not None:
                # adopted away while wedged (docs/SERVING.md "Gray
                # failures"): stop answering, bounded-join the workers
                # (an in-flight request hits the fence at its next
                # journal append or flush and unwinds), and exit
                # FENCED_EXIT_CODE — never rc 114: a supervisor must not
                # respawn us onto a journal a survivor now owns
                self._stop.set()
                self.controller.begin_drain()
                for t in self._workers:
                    t.join(timeout=10.0)
                self._write_state()
                self._teardown()
                raise self._fenced_exc
            time.sleep(poll_s)
        self.controller.begin_drain()
        for t in self._workers:
            t.join()
        self._write_state()
        self._teardown()
        raise DrainInterrupt(
            drain_reason() or "drain requested",
        )

    def stop(self) -> None:
        """Cooperative shutdown for embedders/tests: stop dispatching,
        wait for workers, tear the endpoint down.  No drain semantics —
        use :meth:`serve_until_drained` for the SIGTERM protocol."""
        self.controller.begin_drain()
        self._stop.set()
        for t in self._workers:
            t.join(timeout=60.0)
        self._write_state()
        self._teardown()

    def _teardown(self) -> None:
        if self.scrubber is not None:
            self.scrubber.stop()
        if self._heartbeat is not None:
            self._heartbeat.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._journal is not None:
            self._journal.close()
        if (self.program_cache is not None
                and executor_mod.shared_program_cache() is self.program_cache):
            executor_mod.install_shared_program_cache(None)

    # -- journal + replay (docs/SERVING.md "Durability") -------------------
    def _journal_append(self, typ: str, request_id: str,
                        **fields: Any) -> None:
        """One lifecycle transition into the journal (fsync'd; a no-op
        with the journal off).  Never called under the admission/request
        locks — an fsync is a disk round trip (ctlint CT010).  Raises
        :class:`~cluster_tools_tpu.runtime.journal.Fenced` — with the
        record UNWRITTEN and the self-drain armed — when a survivor has
        adopted this journal (fence check under the journal lock)."""
        if self._journal is not None:
            try:
                self._journal.append_transition(typ, request_id, **fields)
            except journal_mod.Fenced as e:
                self._note_fenced(e)
                raise

    def _note_fenced(self, exc: journal_mod.Fenced) -> None:
        """First fence discovery wins: record ``fenced:adopted_away`` in
        failures.json, stop admission, and arm the self-drain (the serve
        loop exits ``FENCED_EXIT_CODE``).  Idempotent — every later
        fenced append re-raises without re-recording."""
        with self._requests_lock:
            if self._fenced_exc is not None:
                return
            self._fenced_exc = exc
        fu.log(
            f"server {self.base_dir}: FENCED — epoch {exc.own_epoch} "
            f"superseded by {exc.current_epoch} "
            f"({exc.minted_by or 'unknown'}); self-draining without "
            "another journal byte or store write"
        )
        try:
            fu.record_failures(
                self.failures_path,
                "server.fleet",
                [{
                    "block_id": f"fenced:{os.getpid()}",
                    "sites": {"fence": 1},
                    "error": str(exc),
                    "quarantined": False,
                    # resolved on the quarantine precedent: the fence DID
                    # its job — the record is the operator's pointer to
                    # the zombie incarnation, not an open problem
                    "resolved": True,
                    "resolution": FENCED_RESOLUTION,
                    "own_epoch": exc.own_epoch,
                    "fence_epoch": exc.current_epoch,
                    "minted_by": exc.minted_by,
                }],
            )
        except Exception:
            pass  # attribution is best-effort; the fence stands
        trace_mod.instant(
            "server.fenced", own_epoch=exc.own_epoch,
            fence_epoch=exc.current_epoch, by=exc.minted_by or "",
        )
        self.controller.begin_drain()
        self._write_state()

    @property
    def fenced(self) -> bool:
        return self._fenced_exc is not None

    def journal_health(self) -> Optional[Dict[str, Any]]:
        """The journal block of ``/healthz`` / ``server_state.json``:
        append/fsync stats, the replay outcome of this incarnation, and
        the live replay backlog (re-enqueued requests not yet
        terminal)."""
        if self._journal is None:
            return None
        doc = self._journal.health()
        doc.update(self._replay_stats)
        with self._requests_lock:
            doc["replay_backlog"] = sum(
                1 for rec in self._requests.values()
                if rec.get("replayed")
                and rec.get("state") in ("queued", "running")
            )
        return doc

    def _recover_journal(self) -> None:
        """Replay the journal into the restarted server: terminal
        requests become idempotently-answerable records, acknowledged-
        but-incomplete ones re-enter the queue with their original
        payload (resuming at block grain through the ordinary marker /
        handoff-invalidation machinery), tenant counters are
        reconstructed, and crash-looping requests are quarantined."""
        if self._journal is None:
            return
        records = self._journal.recover()
        folded = journal_mod.fold(records)
        inj = faults_mod.get_injector()
        counts: Dict[str, Dict[str, int]] = {}
        for rid, ent in folded.items():
            tenant = ent["tenant"]
            c = counts.setdefault(tenant, {
                "submitted": 0, "dispatched": 0, "completed": 0,
                "rejected": 0,
            })
            state = ent["state"]
            if state == journal_mod.REJECTED:
                # typed rejections are terminal AND replaceable — no
                # record is rebuilt, the id stays free for a resubmit.
                # A rejected entry WITH a payload was accepted first
                # (deadline expiry after admission), so its submitted
                # count is restored too.
                c["rejected"] += 1
                if ent.get("payload") is not None:
                    c["submitted"] += 1
                continue
            if state in (journal_mod.COMPLETED, journal_mod.FAILED,
                         journal_mod.QUARANTINED):
                c["submitted"] += 1
                c["dispatched"] += ent["attempts"]
                if state == journal_mod.COMPLETED:
                    c["completed"] += 1
                rec = dict(ent.get("record") or {})
                rec.setdefault("request_id", rid)
                rec.setdefault("tenant", tenant)
                rec.setdefault("state", {
                    journal_mod.COMPLETED: "done",
                    journal_mod.FAILED: "failed",
                    journal_mod.QUARANTINED: "quarantined",
                }[state])
                rec.setdefault("fingerprint", ent.get("fingerprint"))
                rec["replayed"] = True
                with self._requests_lock:
                    self._requests[rid] = rec
                    self._order.append(rid)
                    self._prune_locked()
                self._replay_stats["replayed"] += 1
                continue
            # acknowledged but incomplete (accepted/dispatched/drained):
            # the 200 was a durable promise — finish it, unless finishing
            # it is what keeps killing the server
            if ent["attempts"] >= self.max_replay_attempts:
                c["submitted"] += 1
                c["dispatched"] += ent["attempts"]
                self._quarantine_crash_loop(ent)
                continue
            # prior crashed attempts stay on the tenant's dispatched
            # count; submit() below restores the submitted count
            c["dispatched"] += ent["attempts"]
            self._reenqueue_replayed(ent)
            # chaos coverage: dying mid-replay must be recoverable — the
            # journal is unchanged by re-enqueueing, so the next boot
            # folds to the same decision
            inj.kill_point("journal_replay")
        for tenant, c in counts.items():
            if any(c.values()):
                self.controller.restore_counts(tenant, **c)
        # boot-time size guard (docs/SERVING.md "Durability"): a clean
        # boot past the threshold snapshots the folded live state into a
        # fresh segment and archives the old one — unbounded journal
        # growth stops here (full compaction stays future work)
        try:
            # terminal snapshots beyond the in-memory record cap cannot
            # be answered idempotently anyway — prune them with rotation
            self._journal.maybe_rotate(folded, self.journal_rotate_bytes,
                                       keep_terminal=_MAX_RECORDS)
        except Exception:
            pass  # rotation is an optimization; the boot must not fail
        self._write_state()

    def _reenqueue_replayed(self, ent: Dict[str, Any]) -> None:
        rid = ent["request_id"]
        payload = dict(ent.get("payload") or {})
        request = admission_mod.Request(
            tenant=ent["tenant"],
            request_id=rid,
            est_bytes=int(payload.get("est_bytes")
                          or self.default_est_bytes),
            # the original deadline_s bounded queue time in the dead
            # incarnation; the replayed promise is completion, so it is
            # not re-armed (docs/SERVING.md "Durability")
            deadline_s=None,
            payload=payload,
        )
        rec = {
            "request_id": rid,
            "tenant": ent["tenant"],
            "workflow": str(payload.get("workflow")),
            "state": "queued",
            "replayed": True,
            "attempts": int(ent["attempts"]),
            "fingerprint": ent.get("fingerprint"),
            "submitted": trace_mod.walltime(),
            "queue_span": trace_mod.begin(
                "server.queue", request=rid, tenant=ent["tenant"],
                replayed=True,
            ),
            "tmp_folder": self._tmp_folder(payload, rid),
        }
        with self._requests_lock:
            self._requests[rid] = rec
            self._order = [r for r in self._order if r != rid]
            self._order.append(rid)
        # admitted=True: the dead incarnation already charged this
        # request against the tenant's quota when it acknowledged it;
        # replay never re-litigates (or rejects) its own promise — the
        # admitted path enqueues unconditionally
        self.controller.submit(request, admitted=True)
        self._replay_stats["reenqueued"] += 1
        trace_mod.instant(
            "server.replay", request=rid, tenant=ent["tenant"],
            attempts=int(ent["attempts"]),
        )

    def _quarantine_crash_loop(self, ent: Dict[str, Any]) -> None:
        """Crash-loop defense: a replayed request whose dispatch has
        crashed the server ``max_replay_attempts`` times is quarantined —
        journaled, attributed in ``failures.json`` as
        ``quarantined:crash_loop``, and answered idempotently as
        ``quarantined`` from then on — instead of wedging the server in a
        replay loop."""
        rid = ent["request_id"]
        tenant = ent["tenant"]
        payload = ent.get("payload") or {}
        error = (
            f"request crashed the server {ent['attempts']} time(s); "
            f"quarantined after max_replay_attempts="
            f"{self.max_replay_attempts}"
        )
        rec = {
            "request_id": rid,
            "tenant": tenant,
            "workflow": str(payload.get("workflow")),
            "state": "quarantined",
            "code": QUARANTINE_CRASH_LOOP,
            "attempts": int(ent["attempts"]),
            "fingerprint": ent.get("fingerprint"),
            "replayed": True,
            "error": error,
            "finished": trace_mod.walltime(),
        }
        self._journal_append(
            journal_mod.QUARANTINED, rid, tenant=tenant, record=rec,
        )
        with self._requests_lock:
            self._requests[rid] = rec
            self._order = [r for r in self._order if r != rid]
            self._order.append(rid)
        try:
            fu.record_failures(
                self.failures_path,
                f"server.{tenant}",
                [{
                    "block_id": f"request:{rid}",
                    "sites": {"journal_replay": int(ent["attempts"])},
                    "error": error,
                    "quarantined": True,
                    # resolved on the rejection precedent: the quarantine
                    # IS the resolution — the server defended itself; the
                    # record is the operator's pointer to the poison
                    "resolved": True,
                    "resolution": QUARANTINE_CRASH_LOOP,
                    "tenant": tenant,
                    "request": rid,
                }],
            )
        except Exception:
            pass  # attribution is best-effort; the quarantine stands
        trace_mod.instant(
            "server.quarantine", request=rid, tenant=tenant,
            code=QUARANTINE_CRASH_LOOP,
        )
        self._replay_stats["quarantined"] += 1

    # -- fleet failover ----------------------------------------------------
    def adopt_journal(self, peer_base_dir: str) -> Dict[str, Any]:
        """Journal handoff (docs/SERVING.md "Fleet"): fold a dead peer's
        journal into this server through the ordinary replay machinery —
        terminal requests become idempotently-answerable records,
        acknowledged-but-incomplete ones re-enter this server's queue and
        finish bit-identically, crash-loopers are quarantined.  Gated on
        the exclusive adoption claim (``runtime/fleet.py``): the claim
        file in the peer's base dir must name THIS pid, so exactly one of
        N would-be adopters can ever get here (ctlint CT012).  Each
        adopted lifecycle is re-journaled HERE before it is enqueued, so
        the adopter crashing mid-adoption loses nothing — its own boot
        replay finishes the inherited promises."""
        from . import fleet as fleet_mod  # lazy: fleet imports server

        peer = os.path.abspath(peer_base_dir)
        if peer == self.base_dir:
            raise fleet_mod.AdoptionRefused(
                f"refusing self-adoption of {peer!r}"
            )
        records = fleet_mod.read_peer_journal(peer, pid=os.getpid())
        folded = journal_mod.fold(records)
        counts: Dict[str, Dict[str, int]] = {}
        stats = {"peer": peer, "completed": 0, "reenqueued": 0,
                 "quarantined": 0, "skipped": 0}
        for rid, ent in folded.items():
            tenant = ent["tenant"]
            state = ent["state"]
            with self._requests_lock:
                known = rid in self._requests
            if known or state == journal_mod.REJECTED:
                # already ours (a client retry raced the failover onto
                # this member) or terminal-and-replaceable: nothing to
                # inherit — idempotency answers the former, the id stays
                # free for the latter
                stats["skipped"] += 1
                continue
            c = counts.setdefault(tenant, {
                "submitted": 0, "dispatched": 0, "completed": 0,
                "rejected": 0,
            })
            # durability first: the inherited lifecycle goes into OUR
            # journal (never under a lock) before any in-memory state, so
            # a crash mid-adoption replays to the same decision
            if self._journal is not None:
                for rec_doc in journal_mod.snapshot_records(ent):
                    self._journal.append(rec_doc)
            if state in (journal_mod.COMPLETED, journal_mod.FAILED,
                         journal_mod.QUARANTINED):
                c["submitted"] += 1
                c["dispatched"] += ent["attempts"]
                if state == journal_mod.COMPLETED:
                    c["completed"] += 1
                rec = dict(ent.get("record") or {})
                rec.setdefault("request_id", rid)
                rec.setdefault("tenant", tenant)
                rec.setdefault("state", {
                    journal_mod.COMPLETED: "done",
                    journal_mod.FAILED: "failed",
                    journal_mod.QUARANTINED: "quarantined",
                }[state])
                rec.setdefault("fingerprint", ent.get("fingerprint"))
                rec["replayed"] = True
                rec["adopted_from"] = peer
                with self._requests_lock:
                    self._requests[rid] = rec
                    self._order.append(rid)
                    self._prune_locked()
                stats["completed"] += 1
                continue
            if ent["attempts"] >= self.max_replay_attempts:
                c["submitted"] += 1
                c["dispatched"] += ent["attempts"]
                self._quarantine_crash_loop(ent)
                stats["quarantined"] += 1
                continue
            c["dispatched"] += ent["attempts"]
            self._reenqueue_replayed(ent)
            with self._requests_lock:
                rec = self._requests.get(rid)
                if rec is not None:
                    rec["adopted_from"] = peer
            stats["reenqueued"] += 1
        for tenant, c in counts.items():
            if any(c.values()):
                self.controller.restore_counts(tenant, **c)
        event = {
            "time": trace_mod.walltime(),
            "peer": peer,
            "completed": stats["completed"],
            "reenqueued": stats["reenqueued"],
            "quarantined": stats["quarantined"],
            "skipped": stats["skipped"],
        }
        with self._requests_lock:
            self._adoptions.append(event)
            del self._adoptions[:-16]
        try:
            fu.record_failures(
                self.failures_path,
                "server.fleet",
                [{
                    "block_id": (
                        f"adopt:{os.path.basename(peer.rstrip(os.sep))}"
                        f":{os.getpid()}"
                    ),
                    "sites": {"adopt": 1},
                    "error": f"adopted journal of dead peer {peer}",
                    "quarantined": False,
                    "resolved": True,
                    "resolution": fleet_mod.ADOPTION_RESOLUTION,
                    "peer": peer,
                }],
            )
        except Exception:
            pass  # attribution is best-effort; the adoption stands
        trace_mod.instant(
            "server.adopt", peer=peer, completed=stats["completed"],
            reenqueued=stats["reenqueued"],
            quarantined=stats["quarantined"],
        )
        self._write_state()
        return stats

    # -- submission --------------------------------------------------------
    def _idempotent_doc(self, request_id: str,
                        rec: Dict[str, Any]) -> Dict[str, Any]:
        """The answer to a resubmit of an acknowledged id with the same
        payload: the recorded state (for completed requests, straight
        from the journal-recovered result) — the 200 was a durable
        promise, a retry never re-runs or bounces."""
        doc = {
            "request_id": request_id,
            "state": rec.get("state"),
            "idempotent": True,
        }
        for k in ("run_s", "total_s", "code"):
            if rec.get(k) is not None:
                doc[k] = rec.get(k)
        return doc

    def _reject_duplicate(self, tenant: str, request_id: str):
        detail = (
            f"request_id {request_id!r} already submitted with a "
            "different payload"
        )
        # attributed like every other rejection; request=None because the
        # live record under this id belongs to the ORIGINAL submission
        # and must not be flipped to rejected
        self.controller._reject(
            None, tenant, admission_mod.REJECT_DUPLICATE, detail
        )
        raise admission_mod.AdmissionError(
            admission_mod.REJECT_DUPLICATE, tenant, detail
        )

    def submit(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Admit one workflow request; returns ``{"request_id", "state"}``
        or raises :class:`~cluster_tools_tpu.runtime.admission.
        AdmissionError` with a typed backpressure code.

        Submission is idempotent per ``(request_id, payload)``: a
        resubmit of a live, completed, or quarantined id with the same
        payload fingerprint is the client's retry of an acknowledged
        request and answers from the record; the same id with a
        DIFFERENT payload is a collision (``rejected:duplicate``).  A
        rejected/failed/drained record stays replaceable — the typed
        backpressure protocol is back-off-and-resubmit the same id.
        """
        tenant = str(payload.get("tenant") or "default")
        request_id = str(payload.get("request_id") or f"{tenant}-{uuid.uuid4().hex[:12]}")
        workflow = payload.get("workflow")
        if not workflow:
            raise ValueError("request payload needs a 'workflow' name")
        _resolve_workflow(str(workflow))  # fail fast on unknown workflows
        fingerprint = _payload_fingerprint(payload)
        with self._requests_lock:
            existing = self._requests.get(request_id)
            held = existing is not None and existing.get("state") in (
                "queued", "running", "done", "quarantined",
            )
            same = held and existing.get("fingerprint") == fingerprint
            snapshot = dict(existing) if held else None
        if held:
            if same:
                return self._idempotent_doc(request_id, snapshot)
            self._reject_duplicate(tenant, request_id)
        # seeded per-tenant admission faults (kind='reject' at site
        # 'admit', runtime/faults.py): chaos proves a rejected request
        # leaves no partial state behind — checked BEFORE any directory,
        # record, or journal entry for the request exists (the rejection
        # itself is journaled through _on_reject)
        if faults_mod.get_injector().maybe_reject(tenant):
            code = admission_mod.REJECT_FAULT
            self.controller._reject(
                admission_mod.Request(tenant=tenant, request_id=request_id),
                tenant, code, "injected admit fault",
            )
            raise admission_mod.AdmissionError(
                code, tenant, "injected admit fault"
            )
        request = admission_mod.Request(
            tenant=tenant,
            request_id=request_id,
            est_bytes=int(payload.get("est_bytes")
                          or self.default_est_bytes),
            deadline_s=payload.get("deadline_s"),
            payload=payload,
        )
        rec = {
            "request_id": request_id,
            "tenant": tenant,
            "workflow": str(workflow),
            "state": "queued",
            "fingerprint": fingerprint,
            "submitted": trace_mod.walltime(),
            "queue_span": trace_mod.begin(
                "server.queue", request=request_id, tenant=tenant
            ),
            "tmp_folder": self._tmp_folder(payload, request_id),
        }
        with self._requests_lock:
            # duplicate re-check + insert under ONE acquisition: two
            # racing submits with the same id must not both insert; the
            # loser of the race answers from the winner's record (same
            # fingerprint) or bounces (different payload)
            existing = self._requests.get(request_id)
            duplicate = existing is not None and existing.get("state") in (
                "queued", "running", "done", "quarantined",
            )
            if not duplicate:
                if existing is not None:
                    self._order = [r for r in self._order if r != request_id]
                self._requests[request_id] = rec
                self._order.append(request_id)
                self._prune_locked()
            else:
                snapshot = dict(existing)
        if duplicate:
            if snapshot.get("fingerprint") == fingerprint:
                return self._idempotent_doc(request_id, snapshot)
            self._reject_duplicate(tenant, request_id)
        # durable acknowledgement: the accepted record is fsync'd AFTER
        # winning the id under the lock (a racing same-id submit with a
        # different payload must not smuggle its payload into the journal
        # for replay to resurrect) and strictly BEFORE the HTTP 200 — an
        # acknowledgement always has a record behind it.  A crash in the
        # insert-to-append window loses a request no client was ever
        # acked for.
        self._journal_append(
            journal_mod.ACCEPTED, request_id, tenant=tenant,
            payload=payload, fingerprint=fingerprint,
        )
        try:
            self.controller.submit(request)
        except admission_mod.AdmissionError as e:
            with self._requests_lock:
                rec["state"] = "rejected"
                rec["code"] = e.code
                rec["error"] = e.detail
            self._write_state()
            raise
        self._write_state()
        return {"request_id": request_id, "state": "queued"}

    def _tmp_folder(self, payload: Dict[str, Any], request_id: str) -> str:
        cfg = payload.get("config") or {}
        return os.path.abspath(
            cfg.get("tmp_folder")
            or os.path.join(self.base_dir, "requests", request_id)
        )

    def _prune_locked(self) -> None:
        while len(self._order) > _MAX_RECORDS:
            victim = None
            for rid in self._order:
                if self._requests.get(rid, {}).get("state") not in (
                    "queued", "running",
                ):
                    victim = rid
                    break
            if victim is None:
                break
            self._order.remove(victim)
            self._requests.pop(victim, None)

    # -- rejection attribution --------------------------------------------
    def _on_reject(self, request, tenant, code, detail) -> None:
        """Called by the admission controller for every rejection (never
        under its lock): journal the lifecycle transition, attribute it in
        the server's failures.json, and update the request record when one
        exists (deadline expiries)."""
        request_id = getattr(request, "request_id", None)
        if request_id is not None:
            # the rejection is a lifecycle end: journaled before the state
            # flip is observable, so a restart answers this id's fate from
            # the journal instead of replaying a request nobody admitted.
            # request=None rejections (duplicates) carry no id and do not
            # touch the original submission's journal lifecycle.
            self._journal_append(
                journal_mod.REJECTED, request_id, tenant=tenant, code=code,
            )
        if request_id is not None:
            with self._requests_lock:
                rec = self._requests.get(request_id)
                if rec is not None and rec["state"] in ("queued", "running"):
                    rec["state"] = "rejected"
                    rec["code"] = code
                    rec["error"] = detail
        # every rejection is its own attributable unit: record_failures
        # keys records by (task, block_id), so a shared key would make a
        # tenant's later rejections silently replace earlier ones (incl.
        # across a restart — the rejection history is the point)
        with self._requests_lock:
            self._reject_seq += 1
            seq = self._reject_seq
        try:
            fu.record_failures(
                self.failures_path,
                f"server.{tenant}",
                [{
                    "block_id": (
                        f"admit:{request_id or tenant}:{os.getpid()}:{seq}"
                    ),
                    "sites": {"admit": 1},
                    "error": detail or None,
                    "quarantined": False,
                    "resolved": True,
                    "resolution": code,
                    "tenant": tenant,
                    "request": request_id,
                }],
            )
        except Exception:
            pass  # attribution is best-effort; the rejection already stands
        trace_mod.instant(
            "server.reject", tenant=tenant, code=code,
            request=request_id or "",
        )
        self._write_state()

    # -- execution ---------------------------------------------------------
    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            if drain_requested():
                # SIGTERM: make sure admission/dispatch latch and stop
                # claiming queued requests; the in-flight ones (other
                # workers) drain at their own safe boundaries
                self.controller.begin_drain()
            request = self.controller.next_request(timeout=0.2)
            if request is None:
                if self.controller.draining():
                    return
                continue
            state = None
            try:
                state = self._execute(request)
            finally:
                # failed/drained requests release their claims but must not
                # count as completed in the tenant stats
                self.controller.release(request, completed=state == "done")
                self._write_state()

    def _execute(self, request: admission_mod.Request) -> None:
        from .task import build

        payload = request.payload or {}
        rid = request.request_id
        with self._requests_lock:
            rec = self._requests.get(rid) or {"request_id": rid}
            attempt = int(rec.get("attempts") or 0) + 1
            rec["attempts"] = attempt
        # the dispatch transition is journaled BEFORE the workflow runs: a
        # crash mid-run leaves a dispatched record behind, and the count
        # of those records is the crash-loop budget replay enforces
        # (max_replay_attempts -> quarantined:crash_loop)
        self._journal_append(
            journal_mod.DISPATCHED, rid, tenant=request.tenant,
            attempt=attempt,
        )
        with self._requests_lock:
            rec["state"] = "running"
            qspan = rec.pop("queue_span", None)
            rec["queued_s"] = round(qspan.end(), 6) if qspan is not None else None
        self._write_state()
        run_span = trace_mod.begin(
            "server.request", request=rid, tenant=request.tenant,
            workflow=str(payload.get("workflow")),
        )
        state, error = "failed", None
        try:
            # the ambient request context: handoff namespacing + the
            # executor's tenant byte cap; the task_context puts the whole
            # request on the resident timeline (docs/ANALYSIS.md CT009)
            with admission_mod.request_context(
                request.tenant, rid, byte_cap=request.byte_cap
            ):
                with trace_mod.task_context(
                    f"request.{rid}", tenant=request.tenant
                ):
                    wf = self._instantiate(payload, rid)
                    ok = build([wf], rerun=bool(payload.get("rerun")))
                    if ok:
                        # fence gate on the OTHER write plane: a fenced
                        # member must not store another byte either —
                        # the adopter re-runs this request and flushes
                        # its own bit-identical copy (ctlint CT013)
                        if self._fence_guard is not None:
                            self._fence_guard.check()
                        # client-visible durability: live dataset handoffs
                        # are written back before the request reports done
                        handoff_mod.flush_namespace(rid)
                    state = "done" if ok else "failed"
        except DrainInterrupt as e:
            # graceful preemption mid-request: markers/manifests are
            # flushed — the resubmitted request resumes at block grain
            state, error = "drained", str(e)
        except journal_mod.Fenced as e:
            # adopted away mid-run: the survivor re-runs this request
            # from its adopted journal copy — record NOTHING here
            self._note_fenced(e)
            state, error = "fenced", str(e)
        except Exception:
            error = fu.cap_traceback(traceback.format_exc())
        finally:
            # drop the request's namespace: a resident process must not
            # accrete dead request state (memory-only intermediates died
            # with the request; datasets were flushed above on success)
            handoff_mod.release_request(rid)
        run_s = run_span.end(error=state != "done")
        terminal = {
            "request_id": rid,
            "tenant": request.tenant,
            "workflow": str(payload.get("workflow")),
            "state": state,
            "queued_s": rec.get("queued_s"),
            "run_s": round(run_s, 6),
            "total_s": round((rec.get("queued_s") or 0.0) + run_s, 6),
            "finished": trace_mod.walltime(),
            "fingerprint": rec.get("fingerprint"),
            "tmp_folder": rec.get("tmp_folder"),
        }
        if error:
            terminal["error"] = error
        # terminal transition journaled BEFORE the state flip becomes
        # observable: done -> the idempotent-answer record a restart
        # serves; drained -> re-enqueued on replay (the drain protocol's
        # queued-work-survives contract now holds server-side).  A fenced
        # request journals NOTHING — the adopter owns its lifecycle now —
        # and a fence discovered AT this append likewise unwinds with the
        # record unwritten (Journal.append checks under its lock).
        if state != "fenced":
            try:
                self._journal_append(
                    _JOURNAL_TERMINAL.get(state, journal_mod.FAILED), rid,
                    tenant=request.tenant, record=terminal,
                )
            except journal_mod.Fenced as e:
                state, error = "fenced", str(e)
                terminal["state"] = state
                terminal["error"] = error
        with self._requests_lock:
            rec.update(
                {k: v for k, v in terminal.items() if k != "request_id"}
            )
        return state

    def _instantiate(self, payload: Dict[str, Any], request_id: str):
        cls = _resolve_workflow(str(payload.get("workflow")))
        cfg = payload.get("config") or {}
        tmp_folder = self._tmp_folder(payload, request_id)
        target = str(cfg.get("target", "local"))
        config_dir = self._materialize_config(cfg, tmp_folder, target)
        return cls(
            tmp_folder=tmp_folder,
            config_dir=config_dir,
            max_jobs=int(cfg.get("max_jobs", self.default_max_jobs)),
            target=target,
            **(cfg.get("params") or {}),
        )

    def _materialize_config(self, cfg: Dict[str, Any], tmp_folder: str,
                            target: str) -> str:
        """The request's effective config_dir: the caller's base configs
        (if any) overlaid with the request's ``global_config`` and the
        resident-owner defaults — ``memory_handoffs`` ON for in-process
        targets (the PR-8 default-off note was waiting for exactly this
        owner; cluster targets stay off because their memory dies with the
        remote job).  An explicit caller value always wins."""
        out_dir = os.path.join(tmp_folder, "server_config")
        os.makedirs(out_dir, exist_ok=True)
        base = cfg.get("config_dir")
        doc: Dict[str, Any] = {}
        if base and os.path.isdir(base):
            for fname in sorted(os.listdir(base)):
                if not fname.endswith(".config"):
                    continue
                if fname == "global.config":
                    doc.update(tu.load_config(os.path.join(base, fname)))
                else:
                    shutil.copy(
                        os.path.join(base, fname),
                        os.path.join(out_dir, fname),
                    )
        doc.update(cfg.get("global_config") or {})
        if target not in _CLUSTER_TARGETS:
            doc.setdefault("memory_handoffs", True)
        tu.dump_config(os.path.join(out_dir, "global.config"), doc)
        return out_dir

    # -- introspection -----------------------------------------------------
    def request_record(self, request_id: str) -> Optional[Dict[str, Any]]:
        with self._requests_lock:
            rec = self._requests.get(request_id)
            if rec is None:
                return None
            return {k: v for k, v in rec.items() if k != "queue_span"}

    def _state_doc(self) -> Dict[str, Any]:
        journal = self.journal_health()
        # fence pulse (docs/SERVING.md "Gray failures") — outside the
        # request lock: current() may stat/re-read the fence file
        fence = None
        if self._fence_guard is not None:
            fence = {
                "own_epoch": self._fence_guard.own_epoch,
                "current_epoch": self._fence_guard.current(),
                "fenced": self.fenced,
            }
        with self._requests_lock:
            requests = {
                rid: {
                    k: rec.get(k)
                    for k in ("tenant", "workflow", "state", "queued_s",
                              "run_s", "total_s", "code", "replayed",
                              "attempts")
                    if rec.get(k) is not None
                }
                for rid, rec in self._requests.items()
            }
            adoptions = list(self._adoptions)
        return {
            "version": 1,
            "uid": SERVER_UID,
            "pid": os.getpid(),
            "hostname": socket.gethostname(),
            "host": self.host,
            "port": self.port,
            "time": trace_mod.walltime(),
            "started": self.started_at,
            "draining": self.controller.draining() or drain_requested(),
            "tenants": self.controller.snapshot(),
            "requests": requests,
            # the resident caches' pulse: chaos asserts live_entries goes
            # back to 0 once every request is terminal (no orphaned
            # handoff state), and operators see what "warm" is worth
            "handoffs": {
                "live_entries": handoff_mod.live_entries(),
                "live_bytes": int(handoff_mod.live_bytes()),
            },
            # the durable-journal pulse (docs/SERVING.md "Durability"):
            # fsync freshness, journal growth, and what this incarnation's
            # replay recovered / re-enqueued / quarantined
            "journal": journal,
            # fleet failover (docs/SERVING.md "Fleet"): dead peers whose
            # journals this incarnation adopted
            "adoptions": adoptions,
            # fencing (docs/SERVING.md "Gray failures"): the epoch this
            # incarnation owns vs. the minted one; fenced=true means a
            # survivor adopted this journal and we are self-draining
            "fence": fence,
            # the server-scoped compiled-program cache (hits = repeat
            # requests that skipped a trace/compile; unkeyed = kernels
            # whose captured state could not be identity-frozen)
            "programs": (
                self.program_cache.stats()
                if self.program_cache is not None else None
            ),
            # the self-healing plane's pulse (docs/SERVING.md
            # "Self-healing"): scrub position/coverage/findings plus the
            # verifying-reader and lineage-repair counters
            "scrub": (
                self.scrubber.stats()
                if self.scrubber is not None else None
            ),
        }

    def _write_state(self) -> None:
        """Atomically refresh ``server_state.json`` — the file
        ``scripts/progress.py`` renders the per-tenant view from.  Never
        called under the admission lock (docs/ANALYSIS.md CT009);
        best-effort, the server must outlive a full disk."""
        try:
            fu.atomic_write_json(
                os.path.join(self.base_dir, STATE_FILENAME), self._state_doc()
            )
        except OSError:
            pass

    def status(self) -> Dict[str, Any]:
        """The ``/status`` document: the machine-readable run report
        (``failures_report.py --json`` over the server's base dir, lint
        pass skipped — it is a static repo property, not run state) plus
        the live server/tenant stats.  ``rc`` preserves the report's
        exit-code semantics: 1 on unresolved failures or a torn
        manifest."""
        report = self._json_report()
        failures = (report or {}).get("failures") or {}
        rc = 1 if (failures.get("error") or failures.get("n_unresolved")) else 0
        return {"server": self._state_doc(), "report": report, "rc": rc}

    def _json_report(self) -> Optional[Dict[str, Any]]:
        try:
            report_mod = _load_failures_report()
            if report_mod is not None:
                return report_mod.build_json_report(
                    self.base_dir, with_lint=False
                )
        except Exception:
            pass
        # minimal fallback (scripts/ not shipped next to the package):
        # same rc-relevant fields, straight from failures.json
        doc = fu.read_json_if_valid(self.failures_path)
        records = (doc or {}).get("records", [])
        error = None
        if doc is None and os.path.exists(self.failures_path):
            error = "torn failures manifest"
        return {
            "version": 1,
            "tmp_folder": self.base_dir,
            "failures": {
                "error": error,
                "n_records": len(records),
                "n_unresolved": sum(
                    1 for r in records if not r.get("resolved")
                ),
            },
        }


def _load_failures_report():
    """``scripts/failures_report.py`` as a module, located relative to the
    package checkout (None when not present — installed without scripts)."""
    import importlib.util

    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(os.path.dirname(pkg_dir), "scripts",
                        "failures_report.py")
    if not os.path.isfile(path):
        return None
    spec = importlib.util.spec_from_file_location("_ctt_failures_report", path)
    if spec is None or spec.loader is None:
        return None
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- HTTP plumbing ------------------------------------------------------------


class _RequestHandler(BaseHTTPRequestHandler):
    """Minimal JSON-over-HTTP surface: POST /submit, GET /status,
    GET /request/<id>, GET /healthz.  Local-endpoint only by default
    (the server binds 127.0.0.1)."""

    server_version = "ctt-serve/1"

    @property
    def pipeline(self) -> PipelineServer:
        return self.server.pipeline  # type: ignore[attr-defined]

    def log_message(self, fmt, *args):  # quiet: the state file is the log
        pass

    def _reply(self, code: int, doc: Dict[str, Any]) -> None:
        body = json.dumps(doc).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.rstrip("/")
        if path not in ("/submit", "/adopt"):
            self._reply(404, {"error": "not_found"})
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, OSError) as e:
            self._reply(400, {"error": "bad_request", "detail": str(e)})
            return
        if path == "/adopt":
            # fleet failover (docs/SERVING.md "Fleet"): adopt a dead
            # peer's journal — only with the exclusive claim in hand
            from . import fleet as fleet_mod  # lazy: fleet imports server

            try:
                self._reply(200, self.pipeline.adopt_journal(
                    str(payload.get("base_dir") or "")
                ))
            except fleet_mod.AdoptionRefused as e:
                self._reply(409, {
                    "error": "adoption_refused", "detail": str(e),
                })
            except (ValueError, KeyError, OSError) as e:
                self._reply(400, {"error": "bad_request", "detail": str(e)})
            return
        try:
            self._reply(200, self.pipeline.submit(payload))
        except journal_mod.Fenced as e:
            # the acceptance was NOT journaled (and so never promised):
            # typed 503 — the client retries and the gateway, which has
            # already routed traffic off this member, places it elsewhere
            self._reply(503, {
                "error": FENCED_RESOLUTION, "detail": str(e),
            })
        except admission_mod.AdmissionError as e:
            http = 503 if e.code == admission_mod.REJECT_DRAINING else 429
            self._reply(http, {
                "error": e.code, "tenant": e.tenant, "detail": e.detail,
            })
        except (ValueError, KeyError) as e:
            self._reply(400, {"error": "bad_request", "detail": str(e)})

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.rstrip("/")
        if path == "/healthz":
            self._reply(200, {
                "ok": True,
                "draining": self.pipeline.controller.draining()
                or drain_requested(),
                # fenced = a survivor owns this journal; the member is
                # exiting and must not be routed to
                "fenced": self.pipeline.fenced,
                # journal health (docs/SERVING.md "Durability"): last
                # fsync age, journal bytes, and the replay backlog — a
                # liveness probe that can also see the ack contract rot
                "journal": self.pipeline.journal_health(),
                # the server-scoped program cache's pulse (docs/SERVING.md
                # "The server-scoped compiled-program cache")
                "programs": (
                    self.pipeline.program_cache.stats()
                    if self.pipeline.program_cache is not None else None
                ),
                # the self-healing plane (docs/SERVING.md "Self-healing"):
                # scrub coverage + corruption found/repaired at rest and
                # at read — rot surfacing here is an SLO breach in waiting
                "scrub": (
                    self.pipeline.scrubber.stats()
                    if self.pipeline.scrubber is not None else None
                ),
            })
        elif path == "/status":
            self._reply(200, self.pipeline.status())
        elif path.startswith("/request/"):
            rec = self.pipeline.request_record(path[len("/request/"):])
            if rec is None:
                self._reply(404, {"error": "unknown_request"})
            else:
                self._reply(200, rec)
        else:
            self._reply(404, {"error": "not_found"})


# -- client -------------------------------------------------------------------


class ServeRejected(RuntimeError):
    """Client-side view of a typed admission rejection."""

    def __init__(self, code: str, detail: str = "", http_status: int = 429):
        self.code = code
        self.detail = detail
        self.http_status = http_status
        super().__init__(f"{code} (http {http_status}): {detail}")


#: rejection codes a durable client may retry with backoff: the restart
#: window (503), transient quota pressure, and the gateway's fleet-level
#: backpressure (no placeable member — the failover window — or every
#: member over its queue cap; both clear on their own).  byte_quota /
#: duplicate / fault are NOT retryable-by-default — resubmitting them
#: verbatim can never succeed (oversize, collision) or is the chaos
#: seed's to count.
RETRYABLE_REJECTS = (
    admission_mod.REJECT_DRAINING,
    admission_mod.REJECT_QUEUE,
    admission_mod.REJECT_FLEET_NO_MEMBER,
    admission_mod.REJECT_FLEET_BACKLOG,
    # every placeable member behind an open circuit breaker — clears on
    # the half-open probe (docs/SERVING.md "Gray failures")
    admission_mod.REJECT_FLEET_BREAKER,
    # fenced member answered directly (never through the gateway, which
    # routes off it): the acceptance was not journaled, resubmit lands
    # on the survivor
    FENCED_RESOLUTION,
)


class ServeClient:
    """Stdlib HTTP client for the serve endpoint (tests, the load
    generator, operator scripts).

    Constructed via :meth:`from_endpoint_file`, the client can survive a
    server restart: connection-level failures (the server is dead or
    binding) are retried with capped backoff while the endpoint file is
    re-read — a restarted server binds a fresh ephemeral port, and the
    durable submission journal (docs/SERVING.md "Durability") guarantees
    the requests it acknowledged are still there to poll.  Typed
    ``rejected:*`` codes are honored: only :data:`RETRYABLE_REJECTS`
    (draining / queue pressure) are retried by :meth:`submit` when given
    a retry budget; everything else raises immediately."""

    def __init__(self, host: str, port: int, timeout_s: float = 30.0,
                 base_dir: Optional[str] = None):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.base_dir = base_dir

    @classmethod
    def from_endpoint_file(cls, base_dir: str,
                           timeout_s: float = 30.0) -> "ServeClient":
        doc = fu.read_json_if_valid(
            os.path.join(base_dir, ENDPOINT_FILENAME)
        )
        if not doc:
            raise FileNotFoundError(
                f"no server endpoint file under {base_dir!r}"
            )
        return cls(doc["host"], doc["port"], timeout_s=timeout_s,
                   base_dir=base_dir)

    def _refresh_endpoint(self) -> None:
        """Re-read the endpoint file (when known): a restarted server
        writes a fresh host/port there before serving."""
        if not self.base_dir:
            return
        doc = fu.read_json_if_valid(
            os.path.join(self.base_dir, ENDPOINT_FILENAME)
        )
        if doc and doc.get("host") and doc.get("port"):
            self.host = doc["host"]
            self.port = int(doc["port"])

    def _call_once(self, method: str, path: str,
                   body: Optional[Dict[str, Any]] = None,
                   member: Optional[str] = None) -> tuple:
        # one deadline-bounded exchange through the shared serve-plane
        # doorway (fault site net_client; ``member`` carries the tenant
        # for targeted client-side faults)
        return netio.http_json_call(
            self.host, self.port, method, path, body,
            timeout_s=self.timeout_s, site="net_client", member=member,
        )

    def _call(self, method: str, path: str,
              body: Optional[Dict[str, Any]] = None,
              retry_s: Optional[float] = None,
              member: Optional[str] = None) -> tuple:
        """One HTTP call; with a ``retry_s`` budget, connection-level
        failures (refused / reset / timed out — the restart window) are
        retried with capped backoff, re-reading the endpoint file between
        attempts (:func:`netio.retry_connection` — the loop the gateway
        shares).  HTTP-level answers are never retried here — the typed
        rejection codes are the caller's protocol."""
        return netio.retry_connection(
            lambda: self._call_once(method, path, body, member=member),
            retry_s,
            on_retry=self._refresh_endpoint,
        )

    def submit(self, retry_s: Optional[float] = None,
               **payload) -> Dict[str, Any]:
        """POST /submit.  With a ``retry_s`` budget the submit also rides
        typed backpressure: :data:`RETRYABLE_REJECTS` (draining — the
        rolling-restart window — and queue pressure) back off and
        resubmit the SAME payload; submission is idempotent per
        ``(request_id, payload)`` server-side, so an ambiguous
        connection drop is safely resubmitted too."""
        deadline = (
            None if not retry_s else time.monotonic() + float(retry_s)
        )
        attempt = 0
        while True:
            # the connection-retry budget is what REMAINS of the caller's
            # budget, not a fresh retry_s per loop — otherwise a late
            # rejection re-arms the full window and blocks ~2x as long
            remaining = (
                None if deadline is None
                else max(0.1, deadline - time.monotonic())
            )
            status, doc = self._call(
                "POST", "/submit", payload, retry_s=remaining,
                member=str(payload.get("tenant") or "") or None,
            )
            if status == 200:
                return doc
            code = str(doc.get("error"))
            if (
                deadline is None
                or code not in RETRYABLE_REJECTS
                or time.monotonic() >= deadline
            ):
                raise ServeRejected(
                    code, str(doc.get("detail") or ""), http_status=status,
                )
            time.sleep(fu.backoff_delay(attempt, 0.05, 2.0))
            attempt += 1

    def status(self) -> Dict[str, Any]:
        return self._call("GET", "/status")[1]

    def healthz(self) -> Dict[str, Any]:
        return self._call("GET", "/healthz")[1]

    def request(self, request_id: str,
                retry_s: Optional[float] = None) -> Optional[Dict[str, Any]]:
        status, doc = self._call(
            "GET", f"/request/{request_id}", retry_s=retry_s
        )
        return None if status == 404 else doc

    def wait(self, request_id: str, timeout_s: float = 120.0,
             poll_s: float = 0.05,
             across_restarts: bool = False) -> Dict[str, Any]:
        """Poll until the request reaches a terminal state; returns its
        record.  Raises TimeoutError when it stays live past
        ``timeout_s``.  With ``across_restarts`` (needs a ``base_dir``
        endpoint file), polls ride out server restarts AND fleet
        failovers: connection failures retry against the re-read endpoint
        until the deadline, and a state-less answer — the gateway's typed
        failover-window document (``rejected:fleet_no_member``: the
        routed member is dead and its journal not yet adopted) — is
        treated as transient with capped backoff, because the adoption
        protocol (docs/SERVING.md "Fleet") means the record WILL come
        back, served by a different member, with zero resubmission."""
        deadline = time.monotonic() + timeout_s
        attempt = 0
        while True:
            remaining = deadline - time.monotonic()
            rec = self.request(
                request_id,
                retry_s=max(0.1, remaining) if across_restarts else None,
            )
            if (
                across_restarts
                and rec is not None
                and rec.get("state") is None
            ):
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"request {request_id} unresolved after "
                        f"{timeout_s:g}s: {rec.get('error')!r}"
                    )
                time.sleep(fu.backoff_delay(attempt, poll_s, 1.0))
                attempt += 1
                self._refresh_endpoint()
                continue
            if rec is not None and rec.get("state") not in (
                "queued", "running",
            ):
                return rec
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"request {request_id} still "
                    f"{(rec or {}).get('state')!r} after {timeout_s:g}s"
                )
            time.sleep(poll_s)
