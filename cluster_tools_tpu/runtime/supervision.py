"""Supervision primitives: heartbeats, deadlines, and duplicate commits.

PR 2's fault layer handles *loud* failures (exceptions, kills); this module
supplies the building blocks for the *silent* ones (docs/ROBUSTNESS.md
"Silent failures"):

- **Heartbeats** — cluster jobs write a small JSON liveness file under
  ``tmp_folder/heartbeats/`` every few seconds (:class:`HeartbeatWriter`);
  the submitting supervisor (``runtime/cluster.py``) reads it to declare a
  job lost when the scheduler still claims it runs but nothing is alive
  (stale heartbeat, dead pid) — the slurm/LSF "lost array task" failure
  mode that otherwise burns the whole ``submit_timeout_s``.
- :class:`Watchdog` — a daemon thread that scans registered in-flight work
  items against a wall-clock deadline and fires a callback once per overdue
  item.  The executor registers every per-block load/compute/store with it
  to detect hung blocks within ``block_deadline_s`` + one period.
- :class:`FirstWins` — the commit registry for speculative re-execution:
  when a hung block's duplicate and its original both finish, the first
  result wins and the second is checked for bit-identical agreement (a
  disagreement means a nondeterministic kernel or corrupted data, and is
  surfaced instead of silently picking one).
- **Drain latch** (docs/ROBUSTNESS.md "Graceful degradation") — the
  process-wide preemption protocol: :func:`install_drain_handler` arms
  SIGTERM/SIGUSR1 to flip a latch instead of dying; the executor, the task
  runner, and ``host_block_map`` poll :func:`drain_requested` at their block
  / task boundaries, finish in-flight work, flush markers + manifests, and
  raise :class:`DrainInterrupt` so the entry point can exit with
  :data:`REQUEUE_EXIT_CODE` — the scheduler-visible "requeue me" signal a
  preempted job sends instead of a crash.
- **Headroom probes** — :func:`host_mem_available_fraction` /
  :func:`disk_free_fraction`, the cheap measurements behind the executor's
  byte-budget admission control and resource-exhaustion backpressure.

A cluster job's first heartbeat is written by its *batch script* (a shell
one-liner, before the Python interpreter even starts), so the supervisor's
staleness clock is not confused by slow jax imports on the worker node.
"""

from __future__ import annotations

import os
import shutil
import signal
import socket
import threading
import time
import zlib
from typing import Any, Callable, Dict, Optional

from ..utils import function_utils as fu

HEARTBEAT_DIRNAME = "heartbeats"

#: Exit code a gracefully-drained (preempted) process exits with, telling
#: the submitting supervisor "requeue me, nothing is broken" — distinct from
#: both a crash (1) and an injected hard kill (``faults.KILL_EXIT_CODE``).
REQUEUE_EXIT_CODE = 114

#: Exit code a serve member exits with after discovering it was FENCED —
#: declared dead and adopted away while wedged (SIGSTOP, GC pause), then
#: woken.  Distinct from a drain (114): the supervisor must NOT requeue
#: it onto the same member dir — a survivor owns that journal now
#: (docs/SERVING.md "Gray failures").
FENCED_EXIT_CODE = 115


# -- preemption-aware draining ------------------------------------------------


class DrainInterrupt(BaseException):
    """Raised at a safe block/task boundary once a drain was requested
    (SIGTERM/SIGUSR1): in-flight work has been finished or checkpointed,
    markers and manifests are flushed, and the process should exit with
    :data:`REQUEUE_EXIT_CODE` so the supervisor requeues it.

    A ``BaseException`` on purpose: the task runtime's broad ``except
    Exception`` retry/continue paths must never swallow a preemption and
    burn failure retries on it.
    """

    def __init__(self, reason: str, remaining_ids=None):
        self.reason = reason
        self.remaining_ids = sorted(int(b) for b in (remaining_ids or []))
        msg = f"drain requested ({reason})"
        if self.remaining_ids:
            msg += f"; {len(self.remaining_ids)} block(s) left for the resume"
        super().__init__(msg)


_drain_event = threading.Event()
_drain_reason: Optional[str] = None
_drain_installed = False
_drain_lock = threading.Lock()


def request_drain(reason: str = "drain requested") -> None:
    """Flip the process-wide drain latch (idempotent; signal-safe)."""
    global _drain_reason
    if _drain_reason is None:
        _drain_reason = reason
    _drain_event.set()


def drain_requested() -> bool:
    return _drain_event.is_set()


def drain_reason() -> Optional[str]:
    return _drain_reason


def reset_drain() -> None:
    """Clear the latch (tests; a resumed run starts un-drained anyway
    because it is a fresh process)."""
    global _drain_reason
    _drain_event.clear()
    _drain_reason = None


def install_drain_handler(signals=(signal.SIGTERM, signal.SIGUSR1)) -> bool:
    """Arm SIGTERM/SIGUSR1 to flip the drain latch instead of killing the
    process.  Idempotent; only replaces *default* dispositions (an embedder
    who installed their own handler keeps it); a no-op off the main thread
    (Python restricts ``signal.signal`` to it).  Returns True when the
    latch is armed for at least one signal."""
    global _drain_installed
    with _drain_lock:
        if _drain_installed:
            return True
        armed = False
        for sig in signals:
            try:
                if signal.getsignal(sig) != signal.SIG_DFL:
                    continue

                def _handler(signum, frame, _name=signal.Signals(sig).name):
                    request_drain(f"received {_name}")

                signal.signal(sig, _handler)
                armed = True
            except (ValueError, OSError):  # non-main thread / exotic platform
                return False
        if armed:
            _drain_installed = True
        return armed


# -- resource headroom probes -------------------------------------------------


def host_mem_available_bytes() -> Optional[int]:
    """``MemAvailable`` from /proc/meminfo, or None where unavailable —
    callers treat None as "no admission control possible", never as 0."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return None


def host_mem_available_fraction() -> Optional[float]:
    """MemAvailable / MemTotal, or None where /proc/meminfo is absent."""
    try:
        avail = total = None
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1])
        if avail is not None and total:
            return avail / total
    except (OSError, ValueError, IndexError):
        pass
    return None


def disk_free_fraction(path: str) -> Optional[float]:
    """Free/total of the filesystem holding ``path``, or None."""
    try:
        usage = shutil.disk_usage(path)
        if usage.total:
            return usage.free / usage.total
    except (OSError, ValueError):
        pass
    return None


# -- heartbeats ---------------------------------------------------------------


def heartbeat_dir(tmp_folder: str) -> str:
    d = os.path.join(tmp_folder, HEARTBEAT_DIRNAME)
    os.makedirs(d, exist_ok=True)
    return d


def heartbeat_path(tmp_folder: str, uid: str) -> str:
    return os.path.join(heartbeat_dir(tmp_folder), f"{uid}.json")


def write_heartbeat(tmp_folder: str, uid: str) -> None:
    """Atomically record ``{time, pid, host}`` — the shared-filesystem pulse
    the supervisor checks for staleness and pid-liveness.  Stamped through
    the tracer's wall-clock source (docs/ANALYSIS.md CT008), so heartbeat
    timestamps and the merged trace timeline share one anchor."""
    from . import trace as trace_mod

    fu.atomic_write_json(
        heartbeat_path(tmp_folder, uid),
        {"time": trace_mod.walltime(), "pid": os.getpid(),
         "host": socket.gethostname()},
    )


def read_heartbeat(tmp_folder: str, uid: str) -> Optional[Dict[str, Any]]:
    """The last heartbeat, or None (never written, or torn mid-kill)."""
    return fu.read_json_if_valid(heartbeat_path(tmp_folder, uid))


def pid_alive(pid) -> bool:
    """Best-effort liveness probe for a pid on THIS host.  Errs on the side
    of alive: only a definite ESRCH says dead (a false 'dead' would trigger
    a spurious resubmission racing a live job)."""
    try:
        os.kill(int(pid), 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError, ValueError, OverflowError):
        return True
    return True


class HeartbeatWriter:
    """Background thread writing a heartbeat every ``interval_s`` until
    stopped.  Writes once synchronously on :meth:`start`, so liveness is
    visible the moment the job begins work."""

    def __init__(self, tmp_folder: str, uid: str, interval_s: float = 5.0):
        self.tmp_folder = tmp_folder
        self.uid = uid
        self.interval_s = max(0.05, float(interval_s))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HeartbeatWriter":
        write_heartbeat(self.tmp_folder, self.uid)
        self._thread = threading.Thread(
            target=self._run, name=f"heartbeat-{self.uid}", daemon=True
        )
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                write_heartbeat(self.tmp_folder, self.uid)
            except OSError:
                # a full/unreachable filesystem must not crash the worker —
                # the supervisor sees staleness and handles it
                pass

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s + 1.0)


# -- per-block deadline watchdog ----------------------------------------------


class Watchdog:
    """Scan registered in-flight items against a wall-clock deadline.

    ``register(token, **info)`` marks work as started, ``clear(token)`` as
    finished; a daemon thread wakes every ``period_s`` and calls
    ``on_overdue(token, info, elapsed)`` exactly once per token whose age
    exceeds ``deadline_s``.  The overdue item stays registered (its thread
    is still stuck) but never fires twice.  Detection latency is bounded by
    ``deadline_s + period_s``.
    """

    def __init__(
        self,
        deadline_s: float,
        period_s: float,
        on_overdue: Callable[[Any, Dict[str, Any], float], None],
    ):
        self.deadline_s = float(deadline_s)
        self.period_s = max(0.01, float(period_s))
        self._on_overdue = on_overdue
        self._inflight: Dict[Any, tuple] = {}
        self._fired: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self, token, **info) -> None:
        with self._lock:
            self._inflight[token] = (time.monotonic(), info)

    def clear(self, token) -> None:
        with self._lock:
            self._inflight.pop(token, None)
            self._fired.discard(token)

    def start(self) -> "Watchdog":
        self._thread = threading.Thread(
            target=self._run, name="block-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.period_s):
            self._scan()

    def _scan(self):
        now = time.monotonic()
        with self._lock:
            overdue = [
                (tok, info, now - t0)
                for tok, (t0, info) in self._inflight.items()
                if now - t0 > self.deadline_s and tok not in self._fired
            ]
            for tok, _, _ in overdue:
                self._fired.add(tok)
        for tok, info, elapsed in overdue:
            try:
                self._on_overdue(tok, info, elapsed)
            except Exception:
                # the watchdog must outlive a buggy callback — the hung
                # block is already recorded as fired
                pass

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.period_s + 1.0)


# -- speculative-duplicate commits --------------------------------------------


class FirstWins:
    """First-result-wins registry with a determinism check.

    ``commit(key, digest)`` returns ``"win"`` for the first committer of a
    key (it proceeds to store), ``"agree"`` when a later duplicate matches
    the winner bit-for-bit (it skips the store), and ``"mismatch"`` when it
    does not — the caller must surface that instead of trusting either copy.
    """

    WIN, AGREE, MISMATCH = "win", "agree", "mismatch"

    def __init__(self):
        self._digests: Dict[Any, Any] = {}
        self._lock = threading.Lock()

    def commit(self, key, digest) -> str:
        with self._lock:
            if key not in self._digests:
                self._digests[key] = digest
                return self.WIN
            return self.AGREE if self._digests[key] == digest else self.MISMATCH

    def withdraw(self, key, digest) -> None:
        """Release a WIN claim whose store ultimately failed, so a later
        re-attempt (the quarantine recompute) can claim the key instead of
        being misread as a duplicate of a result that never landed."""
        with self._lock:
            if self._digests.get(key) == digest:
                del self._digests[key]


def array_digest(arrays) -> int:
    """Order-sensitive CRC32 over (dtype, shape, bytes) of array leaves —
    the bit-identity fingerprint used by the speculative agreement check."""
    import numpy as np

    h = 0
    for a in arrays:
        a = np.ascontiguousarray(a)
        h = zlib.crc32(a.tobytes(), zlib.crc32(
            f"{a.dtype.str}:{a.shape}".encode(), h))
    return h
