"""Task runtime: a small DAG engine with idempotent, resumable tasks.

TPU-native replacement for the reference's ``cluster_tools/cluster_tasks.py``
(SURVEY.md §2a "Task runtime"): there, ``BaseClusterTask(luigi.Task)`` mapped
blocks to slurm/LSF/local *jobs* communicating over the shared filesystem,
with success-log targets for resume.  Here there is no external scheduler —
the "cluster" is the device mesh — so the runtime keeps only the parts that
still earn their place:

- the **DAG** of tasks with ``requires()`` and idempotent skip-if-done
  (``luigi.build`` -> :func:`build`),
- the **success-manifest target** per task (resume grain: task), plus
  block-level markers inside a task (resume grain: block, matching the
  reference's ``log_block_success`` / ``clean_up_for_retry`` semantics),
- the **config system**: ``global.config`` + ``<task_name>.config`` JSON files
  in a ``config_dir``, with ``default_task_config()`` per task and
  ``get_config()`` aggregation on workflows (SURVEY.md §5.6),
- the **target trio** pattern: every op module exposes ``<Op>Local`` /
  ``<Op>TPU`` classes (reference: Local/Slurm/LSF) selected by name in
  :class:`WorkflowBase`; the difference is only which devices back the mesh.

Execution of the per-block compute happens inside ``run_impl`` via the
:class:`~cluster_tools_tpu.runtime.executor.BlockwiseExecutor`, which batches
blocks across the mesh — the TPU analogue of ``prepare_jobs``/``submit_jobs``.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import traceback
from typing import Any, Dict, Iterable, List, Optional, Sequence

from ..utils import function_utils as fu
from ..utils import task_utils as tu


class SuccessTarget:
    """A success manifest file: the task's luigi-style output target."""

    def __init__(self, tmp_folder: str, task_name: str):
        self.path = os.path.join(tmp_folder, f"{task_name}.success.json")

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def write(self, payload: Optional[Dict[str, Any]] = None):
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        doc = {"time": time.time()}
        if payload:
            doc.update(payload)
        with open(self.path, "w") as f:
            json.dump(doc, f, indent=2, default=tu._default)

    def read(self) -> Dict[str, Any]:
        with open(self.path) as f:
            return json.load(f)


class BaseTask:
    """Base of all tasks.  Subclasses set ``task_name`` and define
    ``run_impl()``; backend subclasses (``<Op>Local`` / ``<Op>TPU``) only pin
    the execution ``target``.

    Common parameters mirror the reference: ``tmp_folder`` (scratch +
    markers), ``config_dir`` (JSON configs), ``max_jobs`` (here: max
    concurrent device batches / host IO workers).
    """

    task_name: str = "base"
    target: str = "local"  # backend: 'local' (CPU devices) or 'tpu'

    def __init__(
        self,
        tmp_folder: str,
        config_dir: str,
        max_jobs: int = 1,
        dependencies: Optional[Sequence["BaseTask"]] = None,
        **params: Any,
    ):
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = int(max_jobs)
        self.dependencies = list(dependencies or [])
        self.params = params
        os.makedirs(tmp_folder, exist_ok=True)
        # task identity includes a parameter hash (as luigi's did), so two
        # differently-parameterized instances of one task class in the same
        # tmp_folder get distinct targets, markers, and DAG-dedup keys
        h = hashlib.sha256(
            json.dumps(
                {"params": params, "target": self.target}, sort_keys=True, default=str
            ).encode()
        ).hexdigest()[:8]
        self.uid = f"{self.task_name}.{h}"
        self.logger = fu.get_logger(
            self.uid, os.path.join(tmp_folder, f"{self.uid}.log")
        )

    # -- config ------------------------------------------------------------
    @staticmethod
    def default_task_config() -> Dict[str, Any]:
        return {"threads_per_job": 1, "device_batch": 1}

    @staticmethod
    def default_global_config() -> Dict[str, Any]:
        return {
            "block_shape": [64, 64, 64],
            "roi_begin": None,
            "roi_end": None,
            "halo": None,
        }

    def get_config(self) -> Dict[str, Any]:
        defaults = dict(self.default_global_config())
        defaults.update(self.default_task_config())
        config = tu.load_task_config(self.config_dir, self.task_name, defaults)
        config.update(self.params)
        return config

    # -- DAG protocol ------------------------------------------------------
    def requires(self) -> List["BaseTask"]:
        return self.dependencies

    def output(self) -> SuccessTarget:
        return SuccessTarget(self.tmp_folder, self.uid)

    def run_impl(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def run(self):
        t0 = time.time()
        self.logger.info(f"start {self.task_name} (target={self.target})")
        result = self.run_impl() or {}
        result["runtime_s"] = time.time() - t0
        result["target"] = self.target
        self.output().write(result)
        self.logger.info(
            f"done {self.task_name} in {result['runtime_s']:.2f}s"
        )

    # -- block-level resume helpers ---------------------------------------
    def blocks_done(self) -> List[int]:
        return fu.blocks_done(self.tmp_folder, self.uid)

    def log_block_success(self, block_id: int):
        fu.log_block_success(self.tmp_folder, self.uid, block_id)

    def host_block_map(self, block_ids: Sequence[int], process) -> int:
        """Run ``process(block_id)`` for every block without a success
        marker, on the host IO thread pool, marking each success.

        The common scaffold of host-side blockwise tasks (thin-slab scans,
        relabel writes, artifact dumps): resume-filtering, pooling, and
        error propagation live here so every task behaves identically.
        All failures are surfaced (not just the first): raises RuntimeError
        listing every failed block.  Returns the number of blocks run.
        """
        done = set(self.blocks_done())
        todo = [b for b in block_ids if b not in done]
        errors: List[tuple] = []

        def wrapped(block_id):
            try:
                process(block_id)
                self.log_block_success(block_id)
            except Exception:
                errors.append((block_id, traceback.format_exc()))

        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=max(1, self.max_jobs)) as pool:
            list(pool.map(wrapped, todo))
        if errors:
            failed_ids = sorted(b for b, _ in errors)
            detail = "\n".join(
                f"-- block {b} --\n{tb}" for b, tb in errors[:5]
            )
            raise RuntimeError(
                f"{self.task_name}: {len(errors)}/{len(todo)} blocks failed "
                f"(ids: {failed_ids}); first tracebacks:\n{detail}"
            )
        return len(todo)


class DummyTask(BaseTask):
    """No-op dependency placeholder (reference: ``DummyTask``)."""

    task_name = "dummy"

    def __init__(self, tmp_folder: str = "/tmp/ctt_dummy", config_dir: str = "", **kw):
        super().__init__(tmp_folder, config_dir, **kw)

    def run_impl(self):
        return {}


_TARGET_SUFFIX = {"local": "Local", "tpu": "TPU"}
_CLUSTER_TARGETS = ("slurm", "lsf")


def _check_target(target: str) -> None:
    if target not in _TARGET_SUFFIX and target not in _CLUSTER_TARGETS:
        raise ValueError(
            f"unknown target {target!r}, expected one of "
            f"{sorted(_TARGET_SUFFIX) + list(_CLUSTER_TARGETS)}"
        )


def get_task_cls(module, base_name: str, target: str):
    """Resolve ``<Op><Target>`` in an op module (reference: ``WorkflowBase``'s
    ``getattr(module, name + 'Local'/'Slurm'/'LSF')``).

    ``slurm``/``lsf`` targets are synthesized on demand: the task's Local
    variant wrapped into a batch-submitting class (``runtime/cluster.py``)
    — every task gains the cluster backends without per-module
    boilerplate.  Compute-side workloads should still run on the mesh;
    the cluster targets exist for ingest (SURVEY.md §7 L2' note).
    """
    _check_target(target)
    if target in _CLUSTER_TARGETS:
        from .cluster import make_cluster_task

        local_cls = getattr(module, base_name + "Local")
        return make_cluster_task(local_cls, target)
    return getattr(module, base_name + _TARGET_SUFFIX[target])


class WorkflowBase(BaseTask):
    """Base for workflow tasks: selects backend classes by ``target`` and
    chains sub-tasks (reference: ``WorkflowBase`` in workflows.py)."""

    task_name = "workflow"

    def __init__(self, *args, target: str = "local", **kwargs):
        _check_target(target)
        # set before super().__init__ so the uid hash sees the real target
        self.target = target
        super().__init__(*args, **kwargs)

    def run_impl(self):
        return {}


def build(tasks: Sequence[BaseTask], rerun: bool = False) -> bool:
    """Run a task DAG to completion (reference: ``luigi.build``).

    Topologically executes ``requires()`` dependencies first, skipping tasks
    whose success target already exists (idempotent resume).  Returns True on
    success; on failure logs the traceback and returns False (matching
    luigi's boolean contract).
    """
    order: List[BaseTask] = []
    seen = set()

    def visit(task: BaseTask, stack: tuple):
        key = (type(task).__name__, task.uid, task.tmp_folder)
        if key in stack:
            raise RuntimeError(f"dependency cycle at {key}")
        if key in seen:
            return
        for dep in task.requires():
            visit(dep, stack + (key,))
        seen.add(key)
        order.append(task)

    for t in tasks:
        visit(t, ())

    for task in order:
        if task.output().exists() and not rerun:
            task.logger.info(f"skip {task.task_name}: target exists")
            continue
        try:
            task.run()
        except Exception:
            task.logger.error(
                f"task {task.task_name} failed:\n{traceback.format_exc()}"
            )
            return False
        if not task.output().exists():
            task.logger.error(f"task {task.task_name} produced no target")
            return False
    return True
