"""Task runtime: a small DAG engine with idempotent, resumable tasks.

TPU-native replacement for the reference's ``cluster_tools/cluster_tasks.py``
(SURVEY.md §2a "Task runtime"): there, ``BaseClusterTask(luigi.Task)`` mapped
blocks to slurm/LSF/local *jobs* communicating over the shared filesystem,
with success-log targets for resume.  Here there is no external scheduler —
the "cluster" is the device mesh — so the runtime keeps only the parts that
still earn their place:

- the **DAG** of tasks with ``requires()`` and idempotent skip-if-done
  (``luigi.build`` -> :func:`build`),
- the **success-manifest target** per task (resume grain: task), plus
  block-level markers inside a task (resume grain: block, matching the
  reference's ``log_block_success`` / ``clean_up_for_retry`` semantics),
- the **config system**: ``global.config`` + ``<task_name>.config`` JSON files
  in a ``config_dir``, with ``default_task_config()`` per task and
  ``get_config()`` aggregation on workflows (SURVEY.md §5.6),
- the **target trio** pattern: every op module exposes ``<Op>Local`` /
  ``<Op>TPU`` classes (reference: Local/Slurm/LSF) selected by name in
  :class:`WorkflowBase`; the difference is only which devices back the mesh.

Execution of the per-block compute happens inside ``run_impl`` via the
:class:`~cluster_tools_tpu.runtime.executor.BlockwiseExecutor`, which batches
blocks across the mesh — the TPU analogue of ``prepare_jobs``/``submit_jobs``.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import traceback
from typing import Any, Dict, Iterable, List, Optional, Sequence

from ..utils import function_utils as fu
from ..utils import task_utils as tu
from . import trace as trace_mod


class SuccessTarget:
    """A success manifest file: the task's luigi-style output target.

    Written atomically (temp file + ``os.replace``) and validated on read:
    a kill mid-write must leave either no manifest or the previous one —
    a torn manifest counts as NOT done, so resume re-runs the task instead
    of crashing on (or worse, trusting) half a JSON document.
    """

    def __init__(self, tmp_folder: str, task_name: str):
        self.path = os.path.join(tmp_folder, f"{task_name}.success.json")

    def exists(self) -> bool:
        return fu.read_json_if_valid(self.path) is not None

    def write(self, payload: Optional[Dict[str, Any]] = None):
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        doc = {"time": trace_mod.walltime()}
        if payload:
            doc.update(payload)
        fu.atomic_write_json(self.path, doc, default=tu._default)

    def read(self) -> Dict[str, Any]:
        doc = fu.read_json_if_valid(self.path)
        if doc is None:
            raise FileNotFoundError(
                f"no valid success manifest at {self.path} (missing or torn)"
            )
        return doc


class MemoryTarget:
    """A typed in-memory output target (docs/PERFORMANCE.md "Task-graph
    fusion"): the declaration that a task's output lives in host RAM,
    keyed by the dataset/artifact identity a storage consumer would have
    opened, with spill-to-storage as the universal fallback.

    Declared through :meth:`BaseTask.handoff_dataset` (chunked volumes) or
    :meth:`BaseTask.save_handoff_arrays` (npz/npy artifacts); backed by the
    process-wide registry in :mod:`cluster_tools_tpu.runtime.handoff`.  The
    task's success manifest records one entry per target (``stored`` True
    when it spilled), and :meth:`BaseTask.complete` treats a memory-only
    manifest whose handle is gone — a process restart — as NOT done, so the
    DAG re-runs the producer instead of handing consumers a hole.
    """

    def __init__(self, entry):
        self.entry = entry

    @property
    def identity(self) -> str:
        return self.entry.identity

    def live(self) -> bool:
        """True while the payload is resident (and not spilled)."""
        return not self.entry.spilled and self.entry.obj is not None

    def stored(self) -> bool:
        """True once the payload has a storage copy (spilled)."""
        return bool(self.entry.spilled)


class BaseTask:
    """Base of all tasks.  Subclasses set ``task_name`` and define
    ``run_impl()``; backend subclasses (``<Op>Local`` / ``<Op>TPU``) only pin
    the execution ``target``.

    Common parameters mirror the reference: ``tmp_folder`` (scratch +
    markers), ``config_dir`` (JSON configs), ``max_jobs`` (here: max
    concurrent device batches / host IO workers).
    """

    task_name: str = "base"
    target: str = "local"  # backend: 'local' (CPU devices) or 'tpu'

    def __init__(
        self,
        tmp_folder: str,
        config_dir: str,
        max_jobs: int = 1,
        dependencies: Optional[Sequence["BaseTask"]] = None,
        **params: Any,
    ):
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = int(max_jobs)
        self.dependencies = list(dependencies or [])
        self.params = params
        os.makedirs(tmp_folder, exist_ok=True)
        # task identity includes a parameter hash (as luigi's did), so two
        # differently-parameterized instances of one task class in the same
        # tmp_folder get distinct targets, markers, and DAG-dedup keys
        h = hashlib.sha256(
            json.dumps(
                {"params": params, "target": self.target}, sort_keys=True, default=str
            ).encode()
        ).hexdigest()[:8]
        self.uid = f"{self.task_name}.{h}"
        self.logger = fu.get_logger(
            self.uid, os.path.join(tmp_folder, f"{self.uid}.log")
        )
        # in-memory output targets declared during run_impl (docs/
        # PERFORMANCE.md "Task-graph fusion"); finalized into the success
        # manifest by run()
        self._memory_targets: List[MemoryTarget] = []

    # -- config ------------------------------------------------------------
    @staticmethod
    def default_task_config() -> Dict[str, Any]:
        return {"threads_per_job": 1, "device_batch": 1}

    @staticmethod
    def default_retry_config() -> Dict[str, Any]:
        """Fault-tolerance knobs honored for every task (docs/ROBUSTNESS.md):
        ``max_retries`` task-level re-runs in :func:`build` (0 = fail fast),
        ``retry_backoff_s`` base of the capped exponential task backoff,
        ``io_retries`` / ``io_backoff_s`` per-block load/store retries inside
        :class:`~cluster_tools_tpu.runtime.executor.BlockwiseExecutor`,
        ``io_threads`` the executor's host IO pool width (None = derive
        from ``max_jobs``, the historical default), ``block_schedule`` the
        sweep order (``"morton"`` Z-order locality scheduling for the
        decompressed-chunk cache, ``"given"`` to keep grid order),
        ``sweep_mode`` the executor dispatch shape (``"auto"`` — sharded
        when the mesh has >= 2 devices or the sweep fills a sharded batch —
        ``"sharded"``: one compiled program per Morton batch over the
        device mesh, or ``"per_block"``: the historical
        one-dispatch-per-block path; docs/PERFORMANCE.md "Sharded
        sweeps") with ``sharded_batch`` the blocks per sharded program
        (None = auto),
        ``block_deadline_s`` / ``watchdog_period_s`` the hung-block deadline
        + speculative re-execution (None disables), the cluster-target
        supervision knobs ``heartbeat_interval_s`` / ``heartbeat_timeout_s``
        / ``max_resubmits`` / ``max_preempt_resubmits``
        (``runtime/cluster.py``), and the graceful-degradation knobs
        ``allow_block_split`` (OOM'd blocks re-execute as halo-correct
        sub-blocks — only for shape-local kernels, see the executor's
        ``splittable`` contract), ``min_block_shape`` (split floor),
        ``degrade_wait_s`` (bounded headroom wait before a degrade
        re-attempt) and ``inflight_byte_budget`` (admission cap; None =
        auto from MemAvailable, 0 = off).  ``memory_handoffs`` (default
        off) enables task-graph fusion (docs/PERFORMANCE.md): intermediate
        outputs declared through :meth:`handoff_dataset` /
        :meth:`save_handoff_arrays` stay in host RAM and downstream tasks
        consume them without a storage round-trip, with spill-to-storage
        (byte-budget admission, headroom probes, forced ``spill`` faults)
        as the universal fallback.  ``device_pool`` (``"auto"``/``"on"``/
        ``"off"``) and ``device_pool_bytes`` drive the HBM-resident page
        pool on ragged sweeps, and ``device_handoffs`` (default off) keeps
        :meth:`save_handoff_device_arrays` outputs resident in device
        memory for fused consumers — the device-resident data plane
        (docs/PERFORMANCE.md), with host staging / the memory rung as the
        ladder below and ``CTT_DEVICE_POOL=0`` as the kill switch.
        ``solver_shards`` / ``reduce_fanout`` /
        ``solver_workers`` shard the global agglomeration/multicut solve
        over an octant reduce tree (docs/PERFORMANCE.md "Distributed
        agglomeration"; ``parallel/reduce_tree.py``): ``solver_shards=1``
        keeps today's single-host solve, ``>1`` partitions the graph by
        Morton block octants, runs frontier-aware contraction per shard,
        and merges boundary edges up a ``reduce_fanout``-ary tree —
        in-process, or over a ``solver_workers``-process multihost worker
        group; any sharded failure degrades back to the single-host solve
        (``degraded:unsharded_solve`` in failures.json)."""
        return {
            "max_retries": 0,
            "retry_backoff_s": 1.0,
            "io_retries": 2,
            "io_backoff_s": 0.05,
            "io_threads": None,
            "block_schedule": "morton",
            "sweep_mode": "auto",
            "sharded_batch": None,
            "block_deadline_s": None,
            "watchdog_period_s": None,
            "heartbeat_interval_s": 5.0,
            "heartbeat_timeout_s": 0.0,
            "max_resubmits": 2,
            "max_preempt_resubmits": 3,
            "allow_block_split": False,
            "min_block_shape": None,
            "degrade_wait_s": 5.0,
            "inflight_byte_budget": None,
            "memory_handoffs": False,
            "device_pool": "auto",
            "device_pool_bytes": None,
            "device_handoffs": False,
            "solver_shards": 1,
            "reduce_fanout": 2,
            "solver_workers": 1,
        }

    @staticmethod
    def default_global_config() -> Dict[str, Any]:
        return {
            "block_shape": [64, 64, 64],
            "roi_begin": None,
            "roi_end": None,
            "halo": None,
        }

    def get_config(self) -> Dict[str, Any]:
        defaults = dict(self.default_global_config())
        defaults.update(self.default_retry_config())
        defaults.update(self.default_task_config())
        config = tu.load_task_config(self.config_dir, self.task_name, defaults)
        config.update(self.params)
        return config

    # -- DAG protocol ------------------------------------------------------
    def requires(self) -> List["BaseTask"]:
        return self.dependencies

    def output(self) -> SuccessTarget:
        return SuccessTarget(self.tmp_folder, self.uid)

    def run_impl(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def run(self):
        from . import faults as faults_mod
        from . import handoff as handoff_mod
        from ..io import chunk_cache

        from . import executor as executor_mod

        from ..ops import contraction as contraction_mod
        from ..parallel import device_pool as device_pool_mod
        from ..parallel import reduce_tree as reduce_tree_mod

        self.logger.info(f"start {self.task_name} (target={self.target})")
        # unified tracing plane (docs/OBSERVABILITY.md): every task of a run
        # shards its spans into <tmp_folder>/trace/; first writer pins the
        # directory, an operator CTT_TRACE=<dir> pin always wins
        if trace_mod.enabled():
            trace_mod.set_trace_dir(
                os.path.join(self.tmp_folder, trace_mod.TRACE_DIRNAME)
            )
        # the task.run span doubles as the runtime_s clock (CT008: trace
        # spans are the one timing source in runtime/) and carries the
        # dependency uids the trace aggregator's critical path walks
        run_span = trace_mod.begin(
            "task.run", task=self.uid, task_name=self.task_name,
            deps=[d.uid for d in self.dependencies],
        )
        # fault specs with a "tasks" filter target the running task's uid
        faults_mod.set_current_task(self.uid)
        io_snap = chunk_cache.snapshot()
        disp_snap = executor_mod.dispatch_snapshot()
        handoff_snap = handoff_mod.snapshot()
        device_snap = device_pool_mod.snapshot()
        solver_snap = contraction_mod.solver_snapshot()
        tree_snap = reduce_tree_mod.solve_snapshot()
        ok = False
        try:
            result = self.run_impl() or {}
            # finalize in-memory targets INSIDE the task context: forced
            # `spill` faults filter on the producing task's uid
            handoff_records = self._finalize_handoffs()
            ok = True
        finally:
            faults_mod.set_current_task(None)
            if not ok:
                # a failing task still leaves its spans behind: the error'd
                # task.run span and everything below it flush now, so the
                # timeline of a crashed run shows exactly where it died
                run_span.end(error=True)
                self._flush_trace()
        result["runtime_s"] = run_span.end()
        result["target"] = self.target
        if handoff_records:
            # the DAG engine's resume contract (complete()): a memory-only
            # record whose handle died with this process re-runs the task
            result["handoffs"] = handoff_records
        # chunk-IO + dispatch + handoff attribution: the counters' movement
        # during this task, surfaced in the success manifest AND merged
        # (additively, across resumed runs and cluster job processes) into
        # the run-wide io_metrics.json next to failures.json — so the
        # sharded sweep's dispatch amortization and the fusion layer's
        # avoided storage round-trips are observable per task
        # (docs/PERFORMANCE.md "Sharded sweeps" / "Task-graph fusion")
        io_metrics = chunk_cache.delta(io_snap)
        dispatch_metrics = executor_mod.dispatch_delta(disp_snap)
        if any(dispatch_metrics.values()):
            io_metrics.update(dispatch_metrics)
        handoff_metrics = handoff_mod.delta(handoff_snap)
        if any(handoff_metrics.values()):
            io_metrics.update(handoff_metrics)
        # device-plane attribution (docs/PERFORMANCE.md "Device-resident
        # data plane"): h2d/d2h traffic, resident-pool hit rates, and the
        # bytes fused consumers never re-staged, per task
        device_metrics = device_pool_mod.delta(device_snap)
        if any(device_metrics.values()):
            io_metrics.update(device_metrics)
        # solver attribution: contraction-engine calls/rounds/edge counts
        # plus the reduce tree's per-level solve/merge movement, so the
        # global solve is as observable as the I/O and dispatch paths
        # (docs/PERFORMANCE.md "Distributed agglomeration")
        solver_metrics = contraction_mod.solver_delta(solver_snap)
        if any(solver_metrics.values()):
            io_metrics.update(solver_metrics)
        tree_metrics = reduce_tree_mod.solve_delta(tree_snap)
        if any(tree_metrics.values()):
            io_metrics.update(tree_metrics)
        if any(io_metrics.values()):
            result["io_metrics"] = io_metrics
            try:
                fu.record_io_metrics(
                    fu.io_metrics_path(self.tmp_folder), self.uid, io_metrics
                )
            except Exception:
                self.logger.warning(
                    f"io_metrics recording failed:\n{traceback.format_exc()}"
                )
        self.output().write(result)
        # flush this process's trace shard and (re)stitch the run timeline
        # so trace.json + trace_summary.json track the run as it executes;
        # the restitch re-reads every shard, so it is throttled to once per
        # MERGE_MIN_INTERVAL_S per process — build() always merges at the
        # end, so the finished timeline is current regardless
        self._flush_trace(merge=True)
        self.logger.info(
            f"done {self.task_name} in {result['runtime_s']:.2f}s"
        )

    def _flush_trace(self, merge: bool = False) -> None:
        """Best-effort trace shard flush (+ optional timeline re-merge):
        observability must never fail a run."""
        if not trace_mod.enabled():
            return
        try:
            trace_mod.flush()
            if merge:
                trace_mod.write_timeline(
                    self.tmp_folder,
                    min_interval_s=trace_mod.MERGE_MIN_INTERVAL_S,
                )
        except Exception:
            self.logger.warning(
                f"trace flush failed:\n{traceback.format_exc()}"
            )

    # -- block-level resume helpers ---------------------------------------
    def blocks_done(self) -> List[int]:
        # markers stamped by ANOTHER process's in-memory run describe data
        # that died with it (docs/PERFORMANCE.md "Task-graph fusion") —
        # cleared here regardless of how THIS run stores its output
        from . import handoff

        if handoff.invalidate_stale_markers(self.tmp_folder, self.uid):
            self.logger.info(
                f"{self.task_name}: cleared block markers from a previous "
                "process's in-memory run (outputs no longer exist)"
            )
        return fu.blocks_done(self.tmp_folder, self.uid)

    def log_block_success(self, block_id: int):
        fu.log_block_success(self.tmp_folder, self.uid, block_id)

    @property
    def failures_path(self) -> str:
        """The run's shared ``failures.json`` manifest (docs/ROBUSTNESS.md)."""
        return fu.failures_path(self.tmp_folder)

    def clean_up_for_retry(self):
        """Clear stale partial state before a re-run (the reference's
        ``clean_up_for_retry``): job-level markers go; valid block-level
        markers are kept — the re-run resumes at block grain.  Torn block
        markers are pruned as a side effect of :meth:`blocks_done`."""
        fu.clean_up_for_retry(self.tmp_folder, self.uid)
        self.blocks_done()

    # -- in-memory handoff targets (docs/PERFORMANCE.md "Task-graph fusion") --
    def _handoffs_on(self) -> bool:
        """Task-graph fusion applies when the ``memory_handoffs`` config
        knob is set, the process-level kill switch (``CTT_HANDOFF``) is on,
        and the task does not cross a host boundary (cluster targets run
        their payload in a separate process whose memory dies before the
        submitter-side consumer runs)."""
        if self.target in _CLUSTER_TARGETS:
            return False
        from . import handoff

        if not handoff.handoff_enabled():
            return False
        try:
            cfg = self.get_config()
        except Exception:
            return False
        return bool(cfg.get("memory_handoffs", False))

    def declare_handoff_producer(self) -> bool:
        """Call at the top of ``run_impl`` in tasks that publish
        *artifact* handoffs from block-grain work (per-block npz/npy
        writers under :meth:`host_block_map`): returns whether handoffs
        are on, and stamps this task's marker directory with the process
        token — any later run in a different process (whatever its knob
        or spill path) clears the markers before trusting them, because
        the data they describe dies with this process
        (:func:`~cluster_tools_tpu.runtime.handoff.invalidate_stale_markers`,
        checked inside :meth:`blocks_done`).  Dataset producers get the
        same guard from :meth:`handoff_dataset`.
        """
        if not self._handoffs_on():
            return False
        from . import handoff

        handoff.invalidate_stale_markers(self.tmp_folder, self.uid)
        handoff.mark_memory_producer(self.tmp_folder, self.uid)
        return True

    def handoff_dataset(self, path, key, shape, chunks, dtype,
                        fill_value: int = 0):
        """Declare a chunked-volume output as a :class:`MemoryTarget` and
        return the dataset to write through.

        With handoffs off (the default) this is exactly
        ``file_reader(path).require_dataset(...)`` — the storage path,
        bit-for-bit.  With handoffs on, the returned dataset is the
        in-memory ``memory://`` twin
        (:class:`~cluster_tools_tpu.io.containers.HandoffDataset`) unless
        the target spills at birth (byte-budget admission, a forced
        ``spill`` fault, or a spilled predecessor at the same identity) —
        then it is the real storage dataset and every write lands
        checksummed as usual.

        Contract (docs/ANALYSIS.md CT007): a declaring call site must pass
        the full spill wiring — ``path``/``key`` plus the ``shape`` /
        ``chunks`` / ``dtype`` needed to create the storage twin — and the
        module must wire the returned handle into a post-store
        ``region_verifier`` so integrity verification covers the in-memory
        data plane too.
        """
        from ..utils.volume_utils import file_reader
        from . import handoff

        if not self._handoffs_on():
            # a previous run's live payload at this identity must not
            # shadow the fresh STORAGE bytes this run is about to write
            handoff.discard(handoff.dataset_identity(path, key))
            return file_reader(path).require_dataset(
                key, shape=shape, chunks=chunks, dtype=dtype
            )

        # markers stamped by a previous process's in-memory run are stale
        # on EVERY acquire path — including spill-at-birth, whose storage
        # twin starts empty where those markers claim blocks are done
        handoff.invalidate_stale_markers(self.tmp_folder, self.uid)
        ds, entry = handoff.acquire_dataset(
            path, key, shape=shape, chunks=chunks, dtype=dtype,
            producer=self.uid, failures_path=self.failures_path,
            fill_value=fill_value,
        )
        self._memory_targets.append(MemoryTarget(entry))
        if not entry.spilled:
            # output lives in THIS process's memory: stamp the markers so
            # any later process invalidates them before trusting them
            handoff.mark_memory_producer(self.tmp_folder, self.uid)
        return ds

    def save_handoff_arrays(self, path, **arrays):
        """Publish named arrays as the artifact a storage consumer would
        have loaded from ``path`` (npz).  With handoffs off this is a plain
        ``np.savez`` — today's behavior.  With handoffs on the arrays stay
        in host RAM (read-only) unless admission or a forced ``spill``
        fault writes the file (+ CRC sidecar) through."""
        from . import handoff

        if not self._handoffs_on():
            import numpy as np

            # drop any previous run's live payload AND spill sidecar for
            # this identity: the plain file this run writes is the truth,
            # and a stale CRC would flag the fresh bytes as corruption
            handoff.forget_artifact(path)
            np.savez(path, **arrays)
            return
        entry = handoff.publish_arrays(
            path, arrays, producer=self.uid,
            failures_path=self.failures_path,
        )
        self._memory_targets.append(MemoryTarget(entry))

    def save_handoff_array(self, path, array):
        """Single-array (`.npy`) twin of :meth:`save_handoff_arrays`."""
        from . import handoff

        if not self._handoffs_on():
            import numpy as np

            handoff.forget_artifact(path)
            np.save(path, array)
            return
        entry = handoff.publish_arrays(
            path, {"data": array}, producer=self.uid,
            failures_path=self.failures_path,
        )
        self._memory_targets.append(MemoryTarget(entry))

    def _device_handoffs_on(self) -> bool:
        """Device-rung handoffs: the ``device_handoffs`` config knob on
        top of everything :meth:`_handoffs_on` already requires, plus the
        ``CTT_DEVICE_POOL`` process kill switch."""
        if not self._handoffs_on():
            return False
        from ..parallel import device_pool

        if not device_pool.device_pool_enabled():
            return False
        try:
            cfg = self.get_config()
        except Exception:
            return False
        return bool(cfg.get("device_handoffs", False))

    def save_handoff_device_arrays(self, path, **arrays):
        """Device-rung twin of :meth:`save_handoff_arrays`
        (docs/PERFORMANCE.md "Device-resident data plane"): with
        ``device_handoffs`` on, the named arrays (jax arrays stay
        resident; host arrays are uploaded) live in DEVICE memory under
        the artifact identity, and a fused consumer's
        :func:`~cluster_tools_tpu.runtime.handoff.resolve_device_arrays`
        serves them without a single host byte.  The ladder below is
        automatic: the knob (or kill switch) off lands on the memory rung
        / plain npz exactly like :meth:`save_handoff_arrays`, and a
        resource failure at publish falls back to the memory rung
        attributed ``degraded:host_staged``.

        Contract (docs/ANALYSIS.md CT007): a device-handoff declaration
        must carry its spill wiring — the registry needs ``producer`` and
        ``failures_path`` to demote, spill, and attribute without the
        task on the stack; this method passes both."""
        from . import handoff

        if not self._device_handoffs_on():
            import numpy as np

            # jax payloads land on host here — the one d2h the ladder costs
            return self.save_handoff_arrays(path, **{
                k: np.asarray(v) for k, v in arrays.items()
            })
        entry = handoff.publish_device_arrays(
            path, arrays, producer=self.uid,
            failures_path=self.failures_path,
        )
        self._memory_targets.append(MemoryTarget(entry))

    def save_handoff_device_array(self, path, array):
        """Single-array (`.npy`) twin of
        :meth:`save_handoff_device_arrays`."""
        from . import handoff

        if not self._device_handoffs_on():
            import numpy as np

            return self.save_handoff_array(path, np.asarray(array))
        entry = handoff.publish_device_arrays(
            path, {"data": array}, producer=self.uid,
            failures_path=self.failures_path,
        )
        self._memory_targets.append(MemoryTarget(entry))

    def _finalize_handoffs(self) -> List[Dict[str, Any]]:
        """Mark this run's declared targets complete; returns the manifest
        records :meth:`complete` validates on resume.  Runs while the fault
        injector's current-task context is still set, so ``spill`` faults
        can target tasks."""
        if not self._memory_targets:
            return []
        from . import handoff

        return handoff.finalize_task(self._memory_targets, self.uid)

    def complete(self) -> bool:
        """Luigi-style completeness with handoff resolution: the success
        manifest must exist AND every memory-only output it records must
        still be live in this process's registry.  A memory-only manifest
        whose handle is gone (process restart) is invalidated — manifest
        and block markers removed — so the DAG re-runs the producer
        instead of handing consumers a hole; spilled outputs stay complete
        because storage holds the (checksummed) truth."""
        doc = fu.read_json_if_valid(self.output().path)
        if doc is None:
            return False
        stale = [h for h in doc.get("handoffs", []) if not h.get("stored")]
        if stale:
            from . import handoff

            # resolvable = live in memory OR spilled since the manifest
            # was written (a post-completion headroom spill leaves a valid
            # checksummed storage copy — not a reason to recompute).  Under
            # service mode the identity must also belong to THIS request's
            # namespace: a resubmitted request must never trust a manifest
            # whose memory-only outputs live under a previous request's id
            # (its consumers resolve through the new namespace and would
            # find a hole) — docs/SERVING.md.
            stale = [
                h for h in stale
                if not (
                    handoff.in_current_namespace(h.get("identity"))
                    and handoff.is_resolvable(h.get("identity"))
                )
            ]
        if not stale:
            return True
        self.logger.info(
            f"{self.task_name}: {len(stale)} memory-only handoff output(s) "
            "no longer live (process restart?) — re-running the task"
        )
        try:
            os.remove(self.output().path)
        except OSError:
            pass
        fu.clear_block_markers(self.tmp_folder, self.uid)
        return False

    def host_block_map(
        self,
        block_ids: Sequence[int],
        process,
        store_verify_fn=None,
        blocking=None,
    ) -> int:
        """Run ``process(block_id)`` for every block without a success
        marker, on the host IO thread pool, marking each success.

        The common scaffold of host-side blockwise tasks (thin-slab scans,
        relabel writes, artifact dumps): resume-filtering, pooling, and
        error propagation live here so every task behaves identically.
        All failures are surfaced (not just the first): every failed block
        is recorded in ``failures.json`` (same schema as the executor's,
        tracebacks capped) and a RuntimeError lists every failed block id.
        Returns the number of blocks run.

        Hardened-executor knobs (docs/ROBUSTNESS.md, docs/ANALYSIS.md
        CT001): the per-block retry budget (``io_retries`` /
        ``io_backoff_s``), the hung-block deadline (``block_deadline_s`` /
        ``watchdog_period_s``) and the sweep order (``block_schedule``) are
        *derived from the task config* — call sites never re-plumb them
        (the declarative-wiring direction of ROADMAP item 5).  The two
        wirings that cannot be derived come from the call site: a
        ``store_verify_fn(block)`` post-store integrity check (build it
        with :func:`~cluster_tools_tpu.runtime.executor.region_verifier`;
        verification failures retry, so a corrupt chunk is repaired by the
        re-run while the writer still owns the block) and the ``blocking``
        (which resolves block ids to geometry for the verifier and enables
        the Morton locality schedule).  Resource-classified failures
        (OOM/ENOSPC) skip the same-size retries — re-running the exact
        allocation that just failed only burns the budget.
        """
        from . import admission as admission_mod
        from .supervision import (
            DrainInterrupt,
            Watchdog,
            drain_reason,
            drain_requested,
        )
        from .executor import classify_resource_error, morton_order

        try:
            cfg = self.get_config()
        except Exception:
            cfg = {}
        io_retries = max(0, int(cfg.get("io_retries", 2) or 0))
        io_backoff = float(cfg.get("io_backoff_s", 0.05) or 0.0)
        deadline = float(cfg.get("block_deadline_s") or 0.0)
        period = cfg.get("watchdog_period_s")
        schedule = str(cfg.get("block_schedule") or "morton")

        done = set(self.blocks_done())
        todo = [b for b in block_ids if b not in done]
        if blocking is not None and schedule == "morton":
            # same Z-order locality scheduling as the device executor:
            # consecutive blocks share boundary chunks while they are
            # still resident in the decompressed-chunk cache
            todo = [
                int(b.block_id)
                for b in morton_order([blocking.get_block(i) for i in todo])
            ]
        errors: List[tuple] = []
        skipped_for_drain: List[int] = []
        hung: Dict[int, str] = {}
        completed: set = set()
        watchdog: Optional[Watchdog] = None
        if deadline > 0:
            def _on_hung(token, info, elapsed):
                hung[int(info["block_id"])] = (
                    f"block exceeded block_deadline_s={deadline:g}s on the "
                    f"host path ({elapsed:.2f}s elapsed)"
                )

            watchdog = Watchdog(
                deadline,
                float(period) if period else max(0.02, deadline / 4.0),
                _on_hung,
            ).start()

        # service mode (docs/SERVING.md): the ambient request context is
        # thread-local, but process() may publish block-grain artifact
        # handoffs from THIS pool's worker threads — capture the context
        # here and re-enter it per block, or those identities would lose
        # their request namespace and concurrent requests over the same
        # dataset paths could resolve each other's intermediates
        req_ctx = admission_mod.current_request()

        def wrapped(block_id):
            if drain_requested():
                # drain latch flipped (SIGTERM): stop claiming blocks; the
                # ones already processed keep their markers for the resume
                skipped_for_drain.append(block_id)
                return
            last_tb, attempts = None, 0
            # the span covers the whole retry ladder — the latency an
            # operator chases is time-to-markered, not per-attempt time
            with admission_mod.request_scope(req_ctx), trace_mod.span(
                "host.block", block=int(block_id), task=self.uid
            ):
                for k in range(io_retries + 1):
                    attempts = k + 1
                    if watchdog is not None:
                        watchdog.register(
                            (block_id, k), block_id=int(block_id), stage="host"
                        )
                    try:
                        process(block_id)
                        if store_verify_fn is not None and blocking is not None:
                            # post-store integrity check: a corruption
                            # raises, and the retry re-runs process ->
                            # re-writes the block -> repairs the corrupt
                            # chunk
                            store_verify_fn(blocking.get_block(block_id))
                    except Exception as e:
                        last_tb = fu.cap_traceback(traceback.format_exc())
                        if classify_resource_error(e) is not None:
                            break  # same-size retries re-run the failed alloc
                        if k < io_retries:
                            time.sleep(fu.backoff_delay(k, io_backoff, 5.0))
                    else:
                        completed.add(block_id)
                        self.log_block_success(block_id)
                        if store_verify_fn is not None and blocking is not None:
                            # self-healing lineage (runtime/repair.py): a
                            # verified host-path store registers its
                            # recompute — re-run process() and re-verify —
                            # so read-time/scrub corruption of this block
                            # heals without an operator.  Best effort.
                            try:
                                from . import repair as repair_mod

                                ds = getattr(
                                    store_verify_fn, "dataset", None
                                )
                                blk = blocking.get_block(block_id)
                                bb_of = getattr(
                                    store_verify_fn, "bb_of", None
                                ) or (lambda b: b.bb)
                                if ds is not None:
                                    def recompute(b=block_id):
                                        process(b)
                                        store_verify_fn(
                                            blocking.get_block(b)
                                        )

                                    repair_mod.register_producer(
                                        ds, bb_of(blk), recompute,
                                        task=self.uid,
                                        block_id=int(block_id),
                                        failures_path=self.failures_path,
                                    )
                            except Exception:
                                pass
                        return
                    finally:
                        if watchdog is not None:
                            watchdog.clear((block_id, k))
            trace_mod.instant(
                "fault:host", block=int(block_id), task=self.uid
            )
            errors.append((block_id, last_tb, attempts))

        from concurrent.futures import ThreadPoolExecutor

        try:
            with ThreadPoolExecutor(max_workers=max(1, self.max_jobs)) as pool:
                list(pool.map(wrapped, todo))
        finally:
            if watchdog is not None:
                watchdog.stop()
        records = [
            {
                "block_id": int(b),
                "sites": {"host": int(attempts)},
                "error": tb,
                "quarantined": False,
                "resolved": False,
            }
            for b, tb, attempts in sorted(errors)
        ]
        records += [
            {
                "block_id": int(b),
                "sites": {"hung": 1},
                "error": msg,
                "quarantined": False,
                # a hung block that eventually finished (and markered) is
                # resolved; one that never did is the operator's to chase
                "resolved": b in completed,
            }
            for b, msg in sorted(hung.items())
            if not any(b == eb for eb, _, _ in errors)
        ]
        if records:
            fu.record_failures(self.failures_path, self.uid, records)
        if skipped_for_drain:
            # a drain outranks block errors: the requeued run retries them
            # anyway, and burning task-level retries on a preemption would
            # turn a graceful eviction into a spurious failure
            raise DrainInterrupt(
                drain_reason() or "drain requested",
                skipped_for_drain + [b for b, _, _ in errors],
            )
        if errors:
            failed_ids = sorted(b for b, _, _ in errors)
            detail = "\n".join(
                f"-- block {b} --\n{tb}" for b, tb, _ in errors[:5]
            )
            raise RuntimeError(
                f"{self.task_name}: {len(errors)}/{len(todo)} blocks failed "
                f"(ids: {failed_ids}); see {self.failures_path}; "
                f"first tracebacks:\n{detail}"
            )
        return len(todo)


class DummyTask(BaseTask):
    """No-op dependency placeholder (reference: ``DummyTask``)."""

    task_name = "dummy"

    def __init__(self, tmp_folder: str = "/tmp/ctt_dummy", config_dir: str = "", **kw):
        super().__init__(tmp_folder, config_dir, **kw)

    def run_impl(self):
        return {}


_TARGET_SUFFIX = {"local": "Local", "tpu": "TPU"}
_CLUSTER_TARGETS = ("slurm", "lsf")


def _check_target(target: str) -> None:
    if target not in _TARGET_SUFFIX and target not in _CLUSTER_TARGETS:
        raise ValueError(
            f"unknown target {target!r}, expected one of "
            f"{sorted(_TARGET_SUFFIX) + list(_CLUSTER_TARGETS)}"
        )


def get_task_cls(module, base_name: str, target: str):
    """Resolve ``<Op><Target>`` in an op module (reference: ``WorkflowBase``'s
    ``getattr(module, name + 'Local'/'Slurm'/'LSF')``).

    ``slurm``/``lsf`` targets are synthesized on demand: the task's Local
    variant wrapped into a batch-submitting class (``runtime/cluster.py``)
    — every task gains the cluster backends without per-module
    boilerplate.  Compute-side workloads should still run on the mesh;
    the cluster targets exist for ingest (SURVEY.md §7 L2' note).
    """
    _check_target(target)
    if target in _CLUSTER_TARGETS:
        from .cluster import make_cluster_task

        local_cls = getattr(module, base_name + "Local")
        return make_cluster_task(local_cls, target)
    return getattr(module, base_name + _TARGET_SUFFIX[target])


class WorkflowBase(BaseTask):
    """Base for workflow tasks: selects backend classes by ``target`` and
    chains sub-tasks (reference: ``WorkflowBase`` in workflows.py)."""

    task_name = "workflow"

    def __init__(self, *args, target: str = "local", **kwargs):
        _check_target(target)
        # set before super().__init__ so the uid hash sees the real target
        self.target = target
        super().__init__(*args, **kwargs)

    def run_impl(self):
        return {}


def _task_retry_knobs(task: BaseTask) -> tuple:
    """(max_retries, backoff_base_s) from the task's config; tolerant of
    tasks whose config cannot be loaded (defaults: fail fast)."""
    try:
        cfg = task.get_config()
        return (
            int(cfg.get("max_retries", 0) or 0),
            float(cfg.get("retry_backoff_s", 1.0) or 0.0),
        )
    except Exception:
        return 0, 1.0


def _run_with_retries(task: BaseTask) -> bool:
    """One task to completion: ``max_retries`` re-runs with capped
    exponential backoff, clearing stale partial state between attempts
    (``clean_up_for_retry`` — valid block markers survive, so each retry
    resumes at block grain rather than recomputing the task from scratch)."""
    max_retries, backoff = _task_retry_knobs(task)
    for attempt in range(max_retries + 1):
        if attempt:
            delay = fu.backoff_delay(attempt - 1, backoff, 60.0)
            task.logger.warning(
                f"retry {attempt}/{max_retries} for {task.task_name} "
                f"after {delay:.1f}s backoff"
            )
            try:
                task.clean_up_for_retry()
            except Exception:
                task.logger.warning(
                    f"clean_up_for_retry failed:\n{traceback.format_exc()}"
                )
            time.sleep(delay)
        try:
            task.run()
        except Exception:
            task.logger.error(
                f"task {task.task_name} failed (attempt {attempt + 1}/"
                f"{max_retries + 1}):\n{traceback.format_exc()}"
            )
            continue
        if task.output().exists():
            return True
        task.logger.error(f"task {task.task_name} produced no target")
    return False


def build(tasks: Sequence[BaseTask], rerun: bool = False) -> bool:
    """Run a task DAG to completion (reference: ``luigi.build``).

    Topologically executes ``requires()`` dependencies first, skipping tasks
    whose success target already exists (idempotent resume).  A failed task
    (after its ``max_retries`` re-runs) does NOT abort the DAG: only its
    downstream dependents are skipped, independent branches keep running —
    one bad branch no longer throws away hours of progress elsewhere, and
    the manifests it did produce still shrink the eventual re-run.  Returns
    True only if every task succeeded (matching luigi's boolean contract).

    Preemption (docs/ROBUSTNESS.md "Graceful degradation"): once the drain
    latch is flipped (SIGTERM/SIGUSR1), no further task starts and
    :class:`~cluster_tools_tpu.runtime.supervision.DrainInterrupt`
    propagates — it is a ``BaseException``, so the per-task retry loop
    cannot mistake a preemption for a flaky task.  Finished tasks keep
    their manifests; the requeued run resumes behind them.
    """
    from .supervision import DrainInterrupt, drain_reason, drain_requested
    order: List[BaseTask] = []
    seen = set()
    deps_of: Dict[tuple, List[tuple]] = {}

    def _key(task: BaseTask) -> tuple:
        return (type(task).__name__, task.uid, task.tmp_folder)

    def visit(task: BaseTask, stack: tuple):
        key = _key(task)
        if key in stack:
            raise RuntimeError(f"dependency cycle at {key}")
        if key in seen:
            return
        deps_of[key] = [_key(dep) for dep in task.requires()]
        for dep in task.requires():
            visit(dep, stack + (key,))
        seen.add(key)
        order.append(task)

    for t in tasks:
        visit(t, ())

    # the DAG-engine span: brackets every task.run of this build, so the
    # timeline shows scheduling gaps (skip checks, retry backoffs) between
    # tasks, not just the tasks themselves (docs/OBSERVABILITY.md)
    build_span = trace_mod.begin("task.build", n_tasks=len(order))
    failed: set = set()
    for task in order:
        key = _key(task)
        # completeness first: a task whose target already exists is done,
        # even when an upstream failed (luigi semantics) — its own
        # dependents still get their real input.  complete() additionally
        # validates in-memory handoff outputs: a memory-only manifest
        # whose handle died with its process re-runs (docs/PERFORMANCE.md
        # "Task-graph fusion")
        if task.complete() and not rerun:
            task.logger.info(f"skip {task.task_name}: target exists")
            continue
        blocked = [d for d in deps_of[key] if d in failed]
        if blocked:
            task.logger.error(
                f"skip {task.task_name}: upstream failed "
                f"({[d[0] for d in blocked]})"
            )
            failed.add(key)
            continue
        if drain_requested():
            raise DrainInterrupt(drain_reason() or "drain requested")
        if _run_with_retries(task):
            from . import faults as faults_mod

            faults_mod.get_injector().kill_point("task_done")
        else:
            failed.add(key)
    build_span.end(n_failed=len(failed))
    if trace_mod.enabled() and order:
        # the build span itself must reach the timeline: flush through the
        # last task's tmp_folder (where the run's shard directory lives)
        try:
            trace_mod.flush()
            trace_mod.write_timeline(order[-1].tmp_folder)
        except Exception:
            pass
    return not failed
