"""Unified tracing plane: per-block spans from every process, one timeline.

The runtime attributes every *failure* (``failures.json``, schema v2) and
counts every *byte and dispatch* (``io_metrics.json``), but neither answers
the question that gates the service mode's p50/p99 work (ROADMAP item 4):
**where does the wall-clock go — per block, per site, per process?**  This
module is that layer (docs/OBSERVABILITY.md):

- a **process-wide, low-overhead span tracer** — ring-buffered, monotonic-
  clock, thread-aware, and block/task-context aware the same way
  :mod:`.faults` is (events inherit the executor's thread-local block id
  and the process-level current task, so a span recorded three layers
  below ``map_blocks`` still lands attributed).  ``CTT_TRACE`` is the
  knob: unset/``0`` is a TRUE no-op (the hooks return a shared null
  context — no clock reads on the pure-timeline paths, no counters, no
  files), ``1`` enables tracing with the shard directory supplied by the
  runtime (``BaseTask.run`` points it at ``<tmp_folder>/trace/``), and a
  path value enables tracing *and* fixes the directory — which is how
  worker processes inherit the submitter's timeline through the
  environment.
- **per-process shard files** — every participating process (the
  submitter, cluster-runner workers, reduce-tree solver workers,
  multihost pod workers) flushes its buffered events into
  ``<trace_dir>/shard_<host>_<pid>.json`` (atomic rewrite, crash-safe);
  each shard carries a ``(wall0, mono0)`` clock anchor so the merger can
  place every process's monotonic timestamps on ONE wall-clock-corrected
  timeline even when the monotonic clocks are arbitrarily offset.
- a **merger + aggregator** — :func:`merge` stitches the shards into a
  Chrome-trace-event JSON (Perfetto-loadable ``trace.json``: ``ph="X"``
  complete spans per process/thread track, ``ph="i"`` instants for the
  degrade/fault/quarantine events of the attribution plane — a failure is
  visually adjacent to the latency it caused); :func:`summarize` computes
  per-site latency aggregates (count, total, p50/p95/p99/max), the
  critical path through the task DAG (``task.run`` spans carry their
  dependency uids), and per-process overlap/utilization figures, written
  next to ``io_metrics.json`` as ``trace_summary.json`` and rendered by
  ``scripts/failures_report.py --trace``.

Timing discipline (docs/ANALYSIS.md CT008): this module is the ONE place
``runtime/`` reads ``time.time`` / ``time.perf_counter`` — every other
runtime module measures durations through :func:`span` / :func:`begin`
(whose :meth:`Span.end` returns the elapsed seconds, so existing counters
like the executor's ``dispatch_wait_s`` keep working with the tracer off)
and stamps wall-clock timestamps through :func:`walltime`.  One clock
source means the timeline, the manifests, and the heartbeats agree.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

ENV_VAR = "CTT_TRACE"
ENV_BUFFER = "CTT_TRACE_BUFFER"

#: ring-buffer bound on buffered events per process; oldest events drop
#: (counted) so a runaway sweep cannot let the tracer eat the host
DEFAULT_BUFFER = 200_000

#: shard directory name under a run's tmp_folder
TRACE_DIRNAME = "trace"
_SHARD_PREFIX = "shard_"

#: merged-output filenames (written next to failures.json / io_metrics.json)
TIMELINE_NAME = "trace.json"
SUMMARY_NAME = "trace_summary.json"

_OFF_VALUES = ("", "0", "false", "off")


def walltime() -> float:
    """The runtime's sanctioned wall-clock source (== ``time.time()``).

    Manifest/heartbeat timestamps read it so they share the tracer's
    wall anchor; docs/ANALYSIS.md CT008 bans direct ``time.time()`` in
    ``runtime/`` outside this module."""
    return time.time()


class _Tracer:
    """Process-wide event buffer + clock anchor (module singleton).

    Hot-path discipline: events are buffered as bare tuples
    ``(ph, name, ts, dur, tid, args)`` — dict/JSON shaping happens once,
    at flush, never per event — because the <5% bench-sweep overhead bar
    prices every per-event allocation (``bench.py --sweep`` measures it).
    """

    def __init__(self, enabled: Optional[bool] = None,
                 trace_dir: Optional[str] = None,
                 buffer: Optional[int] = None):
        env = os.environ.get(ENV_VAR, "").strip()
        if enabled is None:
            enabled = env.lower() not in _OFF_VALUES
        if trace_dir is None and env.lower() not in _OFF_VALUES \
                and env.lower() not in ("1", "on", "true"):
            trace_dir = env
        if buffer is None:
            try:
                buffer = int(os.environ.get(ENV_BUFFER, DEFAULT_BUFFER))
            except ValueError:
                buffer = DEFAULT_BUFFER
        self.enabled = bool(enabled)
        self.dir: Optional[str] = trace_dir
        # an explicitly-supplied dir (operator CTT_TRACE=<dir> pin or a
        # test/bench configure()) is never re-pointed; only task-derived
        # dirs set via set_trace_dir may roll over to a new run's dir
        self.pinned = trace_dir is not None
        self.max_events = max(1, int(buffer))
        self._events: deque = deque(maxlen=self.max_events)
        # the per-process clock anchor: monotonic timestamps in the shard
        # map to wall time as wall0 + (ts - mono0), which is what lets the
        # merger put offset clocks on one timeline
        self.wall0 = time.time()
        self.mono0 = time.monotonic()
        self.dropped = 0
        self.flushes = 0

    def record(self, ph: str, name: str, ts: float, dur: float,
               args: Dict[str, Any]) -> None:
        # LOCK-FREE on purpose: deque.append is GIL-atomic in CPython, and
        # the drop check is advisory — per-event locking was the single
        # largest cost in the <5% bench-sweep overhead budget
        events = self._events
        if len(events) == self.max_events:
            self.dropped += 1
        events.append((ph, name, ts, dur, threading.get_ident(), args))

    def counts(self) -> Dict[str, int]:
        """Buffered span/instant counts + all-time dropped/flushes —
        computed lazily (never per event; see :meth:`record`)."""
        raw = list(self._events)
        spans = sum(1 for ev in raw if ev[0] == "X")
        return {
            "spans": spans,
            "instants": len(raw) - spans,
            "dropped": int(self.dropped),
            "flushes": int(self.flushes),
        }

    def snapshot_events(self) -> List[Dict[str, Any]]:
        return [
            {"ph": ph, "name": name, "ts": ts, "dur": dur, "tid": tid,
             "args": args}
            for ph, name, ts, dur, tid, args in list(self._events)
        ]


_tracer: Optional[_Tracer] = None
_singleton_lock = threading.Lock()


def _get() -> _Tracer:
    global _tracer
    if _tracer is None:
        with _singleton_lock:
            if _tracer is None:
                _tracer = _Tracer()
    return _tracer


def configure(enabled: Optional[bool] = None,
              trace_dir: Optional[str] = None,
              buffer: Optional[int] = None) -> _Tracer:
    """Install a fresh tracer (tests / bench A-B runs): empties the buffer
    and zeroes the counters.  Arguments default to the environment knobs."""
    global _tracer
    with _singleton_lock:
        _tracer = _Tracer(enabled=enabled, trace_dir=trace_dir, buffer=buffer)
        _last_merge.clear()
    return _tracer


def reset() -> None:
    """Drop the installed tracer; the next hook re-reads the environment."""
    global _tracer
    with _singleton_lock:
        _tracer = None
        _last_merge.clear()


def enabled() -> bool:
    return _get().enabled


def stats() -> Dict[str, int]:
    """The tracer's counters: buffered spans/instants plus all-time
    dropped/flushes — the tracer-off no-op test asserts these stay zero.
    Computed lazily from the ring (never maintained per event: the record
    hot path is priced by the <5% bench-sweep overhead bar)."""
    return _get().counts()


def trace_dir() -> Optional[str]:
    return _get().dir


def set_trace_dir(path: str) -> None:
    """Point the tracer at a run's shard directory.  Within a run the first
    writer wins, and an operator-pinned ``CTT_TRACE=<dir>`` (or an explicit
    :func:`configure` dir) is never re-pointed.  A task-derived call with a
    DIFFERENT directory means a NEW run in the same long-lived process: the
    previous run's shard is sealed in its own directory and the ring starts
    fresh, so two runs' timelines never cross-contaminate."""
    t = _get()
    if t.dir is None:
        t.dir = path
    elif path != t.dir and not t.pinned:
        flush()
        t._events.clear()
        t.dropped = 0
        t.dir = path
        _last_merge.clear()


_faults_mod = None


def _faults():
    # lazily bound once (not per event): the import indirection breaks the
    # runtime's only would-be cycle (faults never imports trace)
    global _faults_mod
    if _faults_mod is None:
        from . import faults

        _faults_mod = faults
    return _faults_mod


def _context_args(args: Dict[str, Any]) -> Dict[str, Any]:
    """Enrich event args with the fault-targeting context (thread-local
    block id, process-level task uid) unless the caller pinned them."""
    if "block" not in args or "task" not in args:
        fm = _faults()
        if "block" not in args:
            bid = fm.current_block_id()
            if bid is not None:
                args["block"] = int(bid)
        if "task" not in args:
            task = fm.current_task()
            if task is not None:
                args["task"] = task
    return args


class Span:
    """One timed span: a context manager (``with span(...)``) or a manual
    ``begin()``/``end()`` pair.  ``end`` returns the elapsed seconds —
    always measured, so callers can feed duration counters whether or not
    the event was recorded — and records the event unless ``discard``.

    Hot-path discipline (the <5% bench-sweep overhead bar): the tracer
    reference is captured at construction (one singleton lookup per span,
    not two) and the timestamp reads are bound locally."""

    __slots__ = ("name", "args", "t0", "elapsed_s", "_recorded", "_tracer")

    def __init__(self, name: str, args: Dict[str, Any],
                 tracer: Optional["_Tracer"] = None):
        self.name = name
        self.args = args
        self._tracer = tracer
        self.t0 = time.monotonic()
        self.elapsed_s: Optional[float] = None
        self._recorded = False

    def end(self, discard: bool = False, **extra) -> float:
        t1 = time.monotonic()
        if self.elapsed_s is None:
            self.elapsed_s = t1 - self.t0
        if self._recorded or discard:
            return self.elapsed_s
        self._recorded = True
        t = self._tracer or _get()
        if t.enabled:
            if extra:
                self.args.update(extra)
            t.record(
                "X", self.name, self.t0, self.elapsed_s,
                _context_args(self.args),
            )
        return self.elapsed_s

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.end(error=True) if exc_type is not None else self.end()
        return False


class _NullSpan:
    """Shared no-op span for the tracer-off fast path: no clock reads, no
    allocation beyond the singleton."""

    __slots__ = ()
    elapsed_s = 0.0

    def end(self, discard: bool = False, **extra) -> float:
        return 0.0

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NULL = _NullSpan()


def span(name: str, **args):
    """A pure-timeline span: records ``name`` with its duration when
    tracing is on; the shared null context (zero cost) when off.  Use
    :func:`begin` instead when the caller needs the elapsed seconds for a
    metrics counter regardless of the knob."""
    t = _tracer
    if t is None:
        t = _get()
    if not t.enabled:
        return _NULL
    return Span(name, args, t)


def begin(name: str, **args) -> Span:
    """A *timed* span: always measures (two monotonic reads), records only
    when tracing is on.  ``sp.end()`` returns the elapsed seconds;
    ``sp.end(discard=True)`` measures without recording (e.g. an admission
    gate that never actually waited)."""
    return Span(name, args)


def task_context(name: str, **args):
    """The task trace context for call sites OUTSIDE a task class (bench
    drivers, scripts): a ``task.run`` span carrying ``task=name``, the
    same shape ``BaseTask.run`` opens — docs/ANALYSIS.md CT008 requires
    every ``map_blocks`` / ``host_block_map`` / ``solve_with_reduce_tree``
    call site to run under one."""
    args.setdefault("task", name)
    if not _get().enabled:
        return _NULL
    return Span("task.run", args)


def instant(name: str, **args) -> None:
    """A zero-duration timeline marker (Chrome ``ph="i"``): the degrade /
    fault / quarantine events of the attribution plane land through this,
    so a failure sits on the same timeline as the latency it caused."""
    t = _get()
    if not t.enabled:
        return
    t.record("i", name, time.monotonic(), 0.0, _context_args(args))


def shard_path(trace_dir: str) -> str:
    host = socket.gethostname().replace(os.sep, "_")
    return os.path.join(
        trace_dir, f"{_SHARD_PREFIX}{host}_{os.getpid()}.json"
    )


def flush(trace_dir: Optional[str] = None) -> Optional[str]:
    """Write this process's buffered events as its shard (atomic rewrite —
    a kill mid-flush leaves the previous shard, never a torn one).  Safe
    to call repeatedly: each flush rewrites the full buffer, so the last
    flush before a crash is what survives.  No-op (returns None) when
    tracing is off or no directory is known."""
    t = _get()
    if not t.enabled:
        return None
    d = trace_dir or t.dir
    if not d:
        return None
    os.makedirs(d, exist_ok=True)
    path = shard_path(d)
    doc = {
        "version": 1,
        "pid": os.getpid(),
        "hostname": socket.gethostname(),
        "wall0": t.wall0,
        "mono0": t.mono0,
        "dropped": int(t.dropped),
        "events": t.snapshot_events(),
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    t.flushes += 1
    return path


# -- merger: shards -> one Perfetto-loadable timeline -------------------------


def _load_shards(trace_dir: str) -> List[Dict[str, Any]]:
    shards = []
    try:
        names = sorted(os.listdir(trace_dir))
    except OSError:
        return shards
    for fname in names:
        if not (fname.startswith(_SHARD_PREFIX) and fname.endswith(".json")):
            continue
        try:
            with open(os.path.join(trace_dir, fname)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue  # torn/unreadable shard: skip, never fail the merge
        if isinstance(doc, dict) and isinstance(doc.get("events"), list):
            shards.append(doc)
    return shards


def merge(trace_dir: str) -> Dict[str, Any]:
    """Stitch every process shard into one Chrome-trace-event document.

    Clock-offset correction: each shard's monotonic timestamps map to wall
    time through its own ``(wall0, mono0)`` anchor, so two processes whose
    monotonic clocks are offset by hours still interleave correctly; the
    merged timeline is then re-based at the earliest event (``ts`` starts
    at 0, microseconds — what Perfetto expects)."""
    shards = _load_shards(trace_dir)
    placed: List[Dict[str, Any]] = []
    meta: List[Dict[str, Any]] = []
    seen_pids: Dict[int, int] = {}
    for shard in shards:
        wall0 = float(shard.get("wall0", 0.0))
        mono0 = float(shard.get("mono0", 0.0))
        pid = int(shard.get("pid", 0))
        # two hosts can reuse a pid: give the collision a synthetic id so
        # the tracks stay separate (the real identity is in process_name)
        while pid in seen_pids:
            pid += 1_000_000
        seen_pids[pid] = 1
        host = str(shard.get("hostname", "?"))
        meta.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"{host}:{shard.get('pid', pid)}"},
        })
        tid_map: Dict[int, int] = {}
        for ev in shard["events"]:
            try:
                wall = wall0 + (float(ev["ts"]) - mono0)
                tid = int(ev.get("tid", 0))
                name = str(ev.get("name", "?"))
                placed.append({
                    "name": name,
                    # category derived HERE, not at record time: the hot
                    # path buffers bare tuples (see _Tracer)
                    "cat": name.split(":", 1)[0].split(".", 1)[0],
                    "ph": str(ev.get("ph", "X")),
                    "pid": pid,
                    "tid": tid_map.setdefault(tid, len(tid_map)),
                    "_wall": wall,
                    "dur": float(ev.get("dur", 0.0)),
                    "args": ev.get("args") or {},
                })
            except (TypeError, ValueError, KeyError):
                continue
    base = min((e["_wall"] for e in placed), default=0.0)
    placed.sort(key=lambda e: e["_wall"])
    events: List[Dict[str, Any]] = list(meta)
    for e in placed:
        out = {
            "name": e["name"], "cat": e["cat"], "ph": e["ph"],
            "pid": e["pid"], "tid": e["tid"],
            "ts": round((e["_wall"] - base) * 1e6, 3),
            "args": e["args"],
        }
        if e["ph"] == "X":
            out["dur"] = round(e["dur"] * 1e6, 3)
        else:
            out["s"] = "t"  # thread-scoped instant
        events.append(out)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "processes": len(shards),
            "dropped": sum(int(s.get("dropped", 0)) for s in shards),
        },
    }


# -- aggregator: latency percentiles, critical path, utilization --------------


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (no numpy: the
    report path must work in bare tooling environments)."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def _critical_path(task_spans: List[Dict[str, Any]]) -> Optional[Dict]:
    """Longest-duration chain through the task DAG: ``task.run`` spans
    carry their task uid and dependency uids, so the chain that bounds the
    run's wall time falls out of the recorded spans alone."""
    dur: Dict[str, float] = {}
    deps: Dict[str, List[str]] = {}
    for ev in task_spans:
        uid = ev["args"].get("task")
        if not uid:
            continue
        # merged-timeline durations are microseconds (Chrome trace format)
        dur[uid] = dur.get(uid, 0.0) + float(ev.get("dur", 0.0)) / 1e6
        for d in ev["args"].get("deps") or []:
            if d not in deps.setdefault(uid, []):
                deps[uid].append(d)
    if not dur:
        return None
    memo: Dict[str, float] = {}

    def cp(uid: str, stack=()) -> float:
        if uid in memo:
            return memo[uid]
        if uid in stack:  # defensive: the DAG engine rejects cycles
            return 0.0
        best = 0.0
        for d in deps.get(uid, []):
            if d in dur:
                best = max(best, cp(d, stack + (uid,)))
        memo[uid] = dur[uid] + best
        return memo[uid]

    end = max(dur, key=lambda u: cp(u))
    chain, cur = [], end
    while cur is not None:
        chain.append(cur)
        nxt, best = None, 0.0
        for d in deps.get(cur, []):
            if d in dur and cp(d) >= best:
                nxt, best = d, cp(d)
        cur = nxt
    chain.reverse()
    return {
        "tasks": chain,
        "total_s": round(cp(end), 6),
        "task_s": {u: round(dur[u], 6) for u in chain},
    }


def summarize(chrome: Dict[str, Any]) -> Dict[str, Any]:
    """Run-level aggregates over a merged timeline: per-site latency
    percentiles, instant counts, the task-DAG critical path, and per-
    process utilization (busy seconds by category vs wall extent — >1.0
    concurrency means the category genuinely overlapped)."""
    spans = [e for e in chrome.get("traceEvents", [])
             if e.get("ph") == "X"]
    instants = [e for e in chrome.get("traceEvents", [])
                if e.get("ph") == "i"]
    sites: Dict[str, List[float]] = {}
    for e in spans:
        sites.setdefault(e["name"], []).append(float(e.get("dur", 0.0)) / 1e6)
    site_stats = {}
    for name, vals in sorted(sites.items()):
        vals.sort()
        site_stats[name] = {
            "count": len(vals),
            "total_s": round(sum(vals), 6),
            "p50_ms": round(_percentile(vals, 50) * 1e3, 3),
            "p95_ms": round(_percentile(vals, 95) * 1e3, 3),
            "p99_ms": round(_percentile(vals, 99) * 1e3, 3),
            "max_ms": round(vals[-1] * 1e3, 3),
        }
    instant_counts: Dict[str, int] = {}
    for e in instants:
        instant_counts[e["name"]] = instant_counts.get(e["name"], 0) + 1

    procs: Dict[int, Dict[str, Any]] = {}
    for e in spans:
        p = procs.setdefault(int(e.get("pid", 0)), {
            "start": float(e["ts"]), "end": 0.0, "busy": {}, "events": 0,
        })
        ts, dur = float(e["ts"]), float(e.get("dur", 0.0))
        p["start"] = min(p["start"], ts)
        p["end"] = max(p["end"], ts + dur)
        p["events"] += 1
        cat = str(e.get("cat", "runtime"))
        p["busy"][cat] = p["busy"].get(cat, 0.0) + dur / 1e6
    names = {
        int(e.get("pid", 0)): e.get("args", {}).get("name")
        for e in chrome.get("traceEvents", [])
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    processes = []
    for pid in sorted(procs):
        p = procs[pid]
        wall = max(0.0, (p["end"] - p["start"]) / 1e6)
        processes.append({
            "pid": pid,
            "process": names.get(pid) or str(pid),
            "events": p["events"],
            "wall_s": round(wall, 6),
            "busy_s_by_cat": {
                c: round(v, 6) for c, v in sorted(p["busy"].items())
            },
        })

    # executor overlap: the share of sweep wall NOT stalled on
    # un-overlapped loads (the same figure io_metrics derives, computed
    # here from the spans so the two planes cross-check each other)
    sweep = sum(sites.get("executor.sweep", []))
    wait = sum(sites.get("executor.batch_wait", []))
    overlap = None
    if sweep > 0:
        overlap = {
            "sweep_s": round(sweep, 6),
            "batch_wait_s": round(wait, 6),
            "overlap_efficiency": round(max(0.0, 1.0 - wait / sweep), 4),
        }

    return {
        "version": 1,
        "n_events": len(spans) + len(instants),
        "n_processes": len(processes),
        "dropped": int(chrome.get("otherData", {}).get("dropped", 0)),
        "sites": site_stats,
        "instants": instant_counts,
        "critical_path": _critical_path(
            [e for e in spans if e["name"] == "task.run"]
        ),
        "processes": processes,
        "overlap": overlap,
    }


# per-tmp_folder monotonic stamp of the last in-process re-merge: the
# per-task merge in BaseTask.run is throttled through this (a run with
# many short tasks would otherwise re-read every shard after every task,
# O(tasks x shards)); the build()-end merge passes min_interval_s=0 so
# the finished timeline is always current
MERGE_MIN_INTERVAL_S = 30.0
_last_merge: Dict[str, float] = {}


def write_timeline(tmp_folder: str,
                   trace_dir: Optional[str] = None,
                   min_interval_s: float = 0.0) -> Optional[Dict]:
    """Merge the run's shards into ``<tmp_folder>/trace.json`` (Perfetto-
    loadable) + ``<tmp_folder>/trace_summary.json`` (the latency
    aggregates, next to ``io_metrics.json``).  Returns the summary, or
    None when there is nothing to merge.  Atomic writes; best-effort by
    contract — callers must not fail a run over its observability.
    ``min_interval_s`` > 0 skips the merge (returning None) when this
    process already merged ``tmp_folder`` within that window — the
    shards themselves are always current, only the restitch is deferred."""
    if min_interval_s > 0.0:
        last = _last_merge.get(tmp_folder)
        if last is not None and (time.monotonic() - last) < min_interval_s:
            return None
    _last_merge[tmp_folder] = time.monotonic()
    d = trace_dir or _get().dir or os.path.join(tmp_folder, TRACE_DIRNAME)
    chrome = merge(d)
    if not any(e.get("ph") in ("X", "i") for e in chrome["traceEvents"]):
        return None
    summary = summarize(chrome)
    for fname, doc in ((TIMELINE_NAME, chrome), (SUMMARY_NAME, summary)):
        path = os.path.join(tmp_folder, fname)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    return summary


def summary_path(tmp_folder: str) -> str:
    return os.path.join(tmp_folder, SUMMARY_NAME)


def timeline_path(tmp_folder: str) -> str:
    return os.path.join(tmp_folder, TIMELINE_NAME)
