"""Service-mode CLI entry: run the resident pipeline server.

Usage (docs/SERVING.md)::

    python -m cluster_tools_tpu.serve --base-dir /srv/ctt \\
        [--port 0] [--max-workers 2] [--config server.json] [--tpu]
    python -m cluster_tools_tpu.serve --status /srv/ctt

The server binds 127.0.0.1 on ``--port`` (0 = ephemeral; the bound port is
written to ``<base_dir>/server.json`` for clients), admits workflow
requests per-tenant (``--config`` names a JSON document with ``tenants`` /
``default_quota`` / ``max_workers`` / ``default_est_bytes`` /
``max_replay_attempts`` keys), and serves until a SIGTERM drains it —
in-flight requests finish at their safe boundaries, queued ones stay
journaled for the restart's replay, and the process exits
``REQUEUE_EXIT_CODE`` (114) so rolling restarts ride the standard
requeue protocol.  Every acknowledged request is recorded in the durable
submission journal (``<base_dir>/journal.log``, docs/SERVING.md
"Durability"): after ANY exit — drain or ``kill -9`` — the restarted
server replays acknowledged-but-incomplete requests to completion and
quarantines one that keeps crashing it (``max_replay_attempts``, default
3).  ``--status`` prints a running server's ``/status`` document and
exits with its ``rc`` field (the ``failures_report.py --json``
contract).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load_server_config(path):
    if not path:
        return {}
    with open(path) as f:
        return json.load(f)


def cmd_status(base_dir: str) -> int:
    from .runtime.server import ServeClient

    client = ServeClient.from_endpoint_file(base_dir)
    doc = client.status()
    print(json.dumps(doc, indent=2))
    return int(doc.get("rc") or 0)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="cluster_tools_tpu.serve",
        description="resident multi-tenant pipeline server (docs/SERVING.md)",
    )
    p.add_argument("--base-dir", required=False,
                   help="server scratch dir (state, failures.json, request "
                        "tmp folders)")
    p.add_argument("--port", type=int, default=0,
                   help="bind port (default 0 = ephemeral, see server.json)")
    p.add_argument("--max-workers", type=int, default=None,
                   help="concurrent request executors (default 2)")
    p.add_argument("--config", default=None,
                   help="server config json: tenants/default_quota/"
                        "max_workers/default_est_bytes")
    p.add_argument("--tpu", action="store_true",
                   help="skip the cpu platform pin (requests may target "
                        "the accelerator)")
    p.add_argument("--status", metavar="BASE_DIR", default=None,
                   help="print a running server's /status and exit with "
                        "its rc")
    args = p.parse_args(argv)

    if args.status:
        return cmd_status(args.status)
    if not args.base_dir:
        p.error("--base-dir is required (unless --status)")

    if not args.tpu:
        # same contract as cli.py: host-side serving must never block on an
        # unreachable accelerator via platform-pinning sitecustomize hooks
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    from .runtime.journal import Fenced
    from .runtime.server import PipelineServer
    from .runtime.supervision import (
        FENCED_EXIT_CODE,
        REQUEUE_EXIT_CODE,
        DrainInterrupt,
        install_drain_handler,
    )

    cfg = _load_server_config(args.config)
    server = PipelineServer(
        base_dir=args.base_dir,
        tenants=cfg.get("tenants"),
        default_quota=cfg.get("default_quota"),
        max_workers=(
            args.max_workers
            if args.max_workers is not None
            else int(cfg.get("max_workers", 2))
        ),
        default_est_bytes=int(cfg.get("default_est_bytes", 0)),
        default_max_jobs=int(cfg.get("default_max_jobs", 2)),
        port=args.port,
        max_replay_attempts=int(cfg.get("max_replay_attempts", 3)),
        # self-healing plane (docs/SERVING.md "Self-healing"): scrubber
        # knobs ({"enabled", "interval_s", "bytes_per_interval", "roots"})
        # and the boot-time journal rotation threshold
        scrub=cfg.get("scrub"),
        journal_rotate_bytes=cfg.get("journal_rotate_bytes"),
    )
    install_drain_handler()
    server.start()
    replay = server.journal_health() or {}
    print(
        f"serving on {server.host}:{server.port} "
        f"(base_dir={os.path.abspath(args.base_dir)}, "
        f"workers={server.max_workers}; journal replay: "
        f"{replay.get('replayed', 0)} replayed, "
        f"{replay.get('reenqueued', 0)} re-enqueued, "
        f"{replay.get('quarantined', 0)} quarantined)",
        flush=True,
    )
    try:
        server.serve_until_drained()
    except Fenced as e:
        # gray-failure defense (docs/SERVING.md "Gray failures"): this
        # member was declared dead and its journal adopted while it was
        # wedged.  NOT a requeue — a survivor owns the journal; the
        # supervisor must not respawn onto this base dir.
        print(
            f"FENCED ({e}); exiting {FENCED_EXIT_CODE} — journal "
            "adopted away, do not requeue",
            flush=True,
        )
        return FENCED_EXIT_CODE
    except DrainInterrupt as e:
        # CT006/CT009: a drained server is a requeue, not a crash — the
        # supervisor restarts it and clients resubmit their queued work
        print(
            f"DRAINED ({e.reason}); exiting {REQUEUE_EXIT_CODE} for requeue",
            flush=True,
        )
        return REQUEUE_EXIT_CODE
    return 0


if __name__ == "__main__":
    sys.exit(main())
