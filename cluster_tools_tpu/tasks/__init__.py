"""The op/task library (reference: one subpackage per op, SURVEY.md §2a)."""

from . import connected_components
from . import copy_volume
from . import costs
from . import downscaling
from . import evaluation
from . import features
from . import graph
from . import morphology
from . import multicut
from . import node_labels
from . import postprocess
from . import relabel
from . import statistics
from . import thresholded_components
from . import watershed
from . import write
from . import agglomerative_clustering
from . import mutex_watershed
from . import stitching
from . import debugging
from . import distances
from . import ilastik
from . import inference
from . import label_multisets
from . import paintera
from . import skeletons
