"""The op/task library (reference: one subpackage per op, SURVEY.md §2a)."""
