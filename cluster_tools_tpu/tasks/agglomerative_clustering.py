"""Agglomerative clustering of the RAG (alternative to multicut).

Re-design of the reference's ``cluster_tools/agglomerative_clustering/``
(SURVEY.md §2a): GASP-style average-linkage agglomeration over the merged
edge features, stopping at a boundary-probability threshold.  A single
driver task — its input (graph + features) is tiny next to the volume; the
voxel-scale passes are the graph/features tasks it depends on.

Emits a write-task-compatible assignment table
(``agglomerative_assignments.npz``).
"""

from __future__ import annotations

import os

import numpy as np

from ..ops.contraction import average_parallel
from ..runtime.task import BaseTask, WorkflowBase
from .features import features_path
from .graph import load_global_graph


def agglomerative_assignments_path(tmp_folder: str) -> str:
    return os.path.join(tmp_folder, "agglomerative_assignments.npz")


class AgglomerativeClusteringBase(BaseTask):
    """Params: ``threshold`` (merge edges while mean boundary prob is below
    it, default 0.5); ``impl`` selects the contraction engine
    (:mod:`..ops.contraction` ladder: ``auto`` resolves device-JAX on an
    accelerator, else native C++, else numpy; ``heap`` is the sequential
    oracle of :mod:`..ops.agglomeration`)."""

    task_name = "agglomerative_clustering"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "threshold": 0.5,
            "impl": "auto",
        }

    def run_impl(self):
        cfg = self.get_config()
        from ..runtime import handoff

        nodes, _, edges, sizes = load_global_graph(self.tmp_folder)
        feats = handoff.load_array(features_path(self.tmp_folder))
        labels = average_parallel(
            len(nodes),
            edges.astype(np.int64),
            feats[:, 0],
            sizes,
            float(cfg.get("threshold", 0.5)),
            impl=str(cfg.get("impl", "auto")),
        )
        np.savez(
            agglomerative_assignments_path(self.tmp_folder),
            keys=nodes,
            values=(labels + 1).astype(np.uint64),
        )
        return {
            "n_nodes": int(len(nodes)),
            "n_clusters": int(labels.max()) + 1 if len(labels) else 0,
        }


class AgglomerativeClusteringLocal(AgglomerativeClusteringBase):
    target = "local"


class AgglomerativeClusteringTPU(AgglomerativeClusteringBase):
    target = "tpu"
