"""Agglomerative clustering of the RAG (alternative to multicut).

Re-design of the reference's ``cluster_tools/agglomerative_clustering/``
(SURVEY.md §2a): GASP-style average-linkage agglomeration over the merged
edge features, stopping at a boundary-probability threshold.  A single
driver task — its input (graph + features) is tiny next to the volume; the
voxel-scale passes are the graph/features tasks it depends on.

Emits a write-task-compatible assignment table
(``agglomerative_assignments.npz``).
"""

from __future__ import annotations

import os

import numpy as np

from ..ops.contraction import average_parallel
from ..runtime.task import BaseTask, WorkflowBase
from .features import features_path
from .graph import load_global_graph


def agglomerative_assignments_path(tmp_folder: str) -> str:
    return os.path.join(tmp_folder, "agglomerative_assignments.npz")


class AgglomerativeClusteringBase(BaseTask):
    """Params: ``threshold`` (merge edges while mean boundary prob is below
    it, default 0.5); ``impl`` selects the contraction engine
    (:mod:`..ops.contraction` ladder: ``auto`` resolves device-JAX on an
    accelerator, else native C++, else numpy; ``heap`` is the sequential
    oracle of :mod:`..ops.agglomeration`).

    ``solver_shards > 1`` shards the agglomeration over the reduce tree
    (docs/PERFORMANCE.md "Distributed agglomeration") with the
    size-weighted mean payload carried through every merge level; the
    supervoxel id range stands in for block octants (blockwise watershed
    labels consecutive ids per block, so contiguous ranges are spatial
    neighborhoods).  Single-host average linkage stays the
    ``solver_shards=1`` case and the ``degraded:unsharded_solve``
    fallback."""

    task_name = "agglomerative_clustering"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "threshold": 0.5,
            "impl": "auto",
        }

    def run_impl(self):
        cfg = self.get_config()
        from ..ops import contraction as contraction_mod
        from ..parallel import reduce_tree as reduce_tree_mod
        from ..runtime import handoff

        nodes, _, edges, sizes = load_global_graph(self.tmp_folder)
        feats = handoff.load_array(features_path(self.tmp_folder))
        threshold = float(cfg.get("threshold", 0.5))
        impl = str(cfg.get("impl", "auto"))
        shards = int(cfg.get("solver_shards", 1) or 1)
        solver_snap = contraction_mod.solver_snapshot()
        tree_snap = reduce_tree_mod.solve_snapshot()

        def unsharded():
            return average_parallel(
                len(nodes), edges.astype(np.int64), feats[:, 0], sizes,
                threshold, impl=impl,
            )

        if shards > 1 and len(edges):
            # average-linkage payload: (prob * size, size) columns, summed
            # on merge — the same contract as ops/contraction
            s = np.maximum(np.asarray(sizes, np.float64), 1e-12)
            payload = np.stack(
                [np.asarray(feats[:, 0], np.float64) * s, s], axis=1
            )
            labels, solve_info = reduce_tree_mod.solve_with_reduce_tree(
                len(nodes), edges.astype(np.int64), payload,
                node_shard=reduce_tree_mod.contiguous_node_shards(
                    len(nodes), shards
                ),
                solver_shards=shards,
                fanout=int(cfg.get("reduce_fanout", 2) or 2),
                reduce_plane=str(cfg.get("reduce_plane", "auto") or "auto"),
                hop_deadline_s=cfg.get("hop_deadline_s"),
                failures_path=self.failures_path,
                task_name=self.uid,
                unsharded=unsharded,
                mode="min",
                threshold=threshold,
                workers=int(cfg.get("solver_workers", 1) or 1),
                scratch_dir=os.path.join(self.tmp_folder, "reduce_tree"),
                max_workers=max(1, self.max_jobs),
            )
        else:
            labels = unsharded()
            solve_info = {"sharded": False, "shards": 1}
        np.savez(
            agglomerative_assignments_path(self.tmp_folder),
            keys=nodes,
            values=(labels + 1).astype(np.uint64),
        )
        from .multicut import _solver_manifest

        return {
            "n_nodes": int(len(nodes)),
            "n_clusters": int(labels.max()) + 1 if len(labels) else 0,
            # no signed multicut objective here; the mean-probability
            # criterion has no global energy — record edge movement/rounds
            "solver": _solver_manifest(
                None, edges, labels,
                contraction_mod.solver_delta(solver_snap),
                reduce_tree_mod.solve_delta(tree_snap),
                solve_info,
            ),
        }


class AgglomerativeClusteringLocal(AgglomerativeClusteringBase):
    target = "local"


class AgglomerativeClusteringTPU(AgglomerativeClusteringBase):
    target = "tpu"
