"""Blockwise connected components with global label stitching.

Re-design of the reference's ``cluster_tools/connected_components/``
(SURVEY.md §3.2).  The reference ran five luigi tasks: per-block vigra CCL ->
prefix-sum label offsets -> per-face equivalence scan -> serial
``nifty.ufd`` union-find -> blockwise write.  Two structural changes here:

1. **No offset pass.**  Per-block labels are the *global flat index of the
   component's minimum voxel + 1* — globally unique by construction (the
   device CCL kernel already produces block-local min-voxel indices, which
   the host shifts into volume coordinates).  The reference needed the
   prefix-sum because vigra labels were 1..k per block.
2. **The union-find merge is a device kernel** (pointer jumping over the
   dense label table), not a serial C++ loop — the reference's named
   scalability cliff (SURVEY.md §3.2 "serial on one node").

Task chain (same barrier structure as the reference, so resume behaves the
same):

    BlockComponents   (mesh-batched)  per-block CCL -> global labels + uniques
    MergeLabels       (driver)        merge per-block uniques -> dense table
    BlockFaces        (host IO pool)  boundary scan (faces, plus edges and
                                      corners at connectivity>1) -> pairs
    MergeAssignments  (device)        union-find -> assignment table
    Write             (host IO pool)  apply assignment blockwise
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..ops.ccl import label_components, label_components_keyed
from ..ops.unionfind import union_find, union_find_host
from ..runtime import handoff
from ..runtime.executor import (
    BlockwiseExecutor,
    region_verifier,
    validate_labels,
)
from ..runtime.task import BaseTask, WorkflowBase, build
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader, pad_block_to

import jax.numpy as jnp


def _uniques_path(tmp_folder: str, block_id: int) -> str:
    d = os.path.join(tmp_folder, "cc_uniques")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"block_{block_id}.npy")


def _faces_path(tmp_folder: str, block_id: int) -> str:
    d = os.path.join(tmp_folder, "cc_faces")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"block_{block_id}.npy")


class BlockComponentsBase(BaseTask):
    """Pass 1: per-block CCL on the thresholded/binary input.

    Params: ``input_path/input_key`` (binary or real-valued volume),
    ``output_path/output_key`` (uint64 labels), optional ``threshold`` +
    ``threshold_mode`` ('greater'/'less'), optional ``mask_path/mask_key``.
    """

    task_name = "block_components"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "threshold": None,
            "threshold_mode": "greater",
            "connectivity": 1,
            # keyed=True: components of equal-valued regions (CC on a
            # segmentation, each segment split into its connected parts)
            "keyed": False,
        }

    def run_impl(self):
        cfg = self.get_config()
        # fusable input edge: a producer's live in-memory handoff (e.g. an
        # inference probability map) is consumed without a storage read
        inp = handoff.resolve_dataset(cfg["input_path"], cfg["input_key"])
        shape = inp.shape
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        # MemoryTarget output: label volume stays in RAM for the faces /
        # write consumers, spill-to-storage under the degrade ladder
        out = self.handoff_dataset(
            cfg["output_path"], cfg["output_key"],
            shape=shape, chunks=block_shape, dtype="uint64",
        )
        # the per-block uniques below are block-grain ARTIFACT handoffs:
        # stamp the marker epoch even when the dataset itself spilled at
        # birth, or a resumed process would trust markers whose uniques
        # died in this process's RAM
        self.declare_handoff_producer()
        done = set(self.blocks_done())
        blocks_all = [blocking.get_block(b) for b in block_ids]

        threshold = cfg.get("threshold")
        mode = cfg.get("threshold_mode", "greater")
        connectivity = int(cfg.get("connectivity", 1))
        if not 1 <= connectivity <= len(shape):
            # fail in pass 1, before any blocks burn time with an empty or
            # nonsense neighborhood
            raise ValueError(f"connectivity must be in [1, {len(shape)}]")
        keyed = bool(cfg.get("keyed", False))
        mask_ds = None
        if cfg.get("mask_path"):
            mask_ds = file_reader(cfg["mask_path"])[cfg["mask_key"]]

        def load(block):
            data = inp[block.bb]
            if keyed:
                # dense per-block int32 keys (device kernels can't take
                # uint64 labels); key identity only matters within a block
                _, keys = np.unique(np.asarray(data), return_inverse=True)
                keys = keys.reshape(np.asarray(data).shape).astype(np.int32)
                keys[np.asarray(data) == 0] = 0
                if mask_ds is not None:
                    keys[~(np.asarray(mask_ds[block.bb]) > 0)] = 0
                return (pad_block_to(keys, block_shape),)
            if threshold is None:
                m = data > 0
            elif mode == "greater":
                m = data > threshold
            else:
                m = data < threshold
            if mask_ds is not None:
                m &= mask_ds[block.bb] > 0
            return (pad_block_to(m, block_shape).astype(bool),)

        n_pad = int(np.prod(block_shape))

        def kernel(m):
            if keyed:
                return label_components_keyed(m, connectivity=connectivity)
            return label_components(m, connectivity=connectivity)

        def store(block, raw):
            # raw: padded-block flat index of component min voxel, sentinel=n
            bs = block.shape
            raw = raw[tuple(slice(0, s) for s in bs)]
            fg = raw < n_pad
            local = np.unravel_index(raw[fg].astype(np.int64), block_shape)
            coords = tuple(
                l + b for l, b in zip(local, block.begin)
            )
            glob = np.ravel_multi_index(coords, shape).astype(np.uint64) + 1
            labels = np.zeros(bs, np.uint64)
            labels[fg] = glob
            out[block.bb] = labels
            self.save_handoff_array(
                _uniques_path(self.tmp_folder, block.block_id), np.unique(glob)
            )

        executor = BlockwiseExecutor(
            target=self.target,
            device_batch=int(cfg.get("device_batch", 1)),
            io_threads=int(cfg.get("io_threads") or max(1, self.max_jobs)),
            max_retries=int(cfg.get("io_retries", 2)),
            backoff_base=float(cfg.get("io_backoff_s", 0.05)),
        )
        executor.map_blocks(
            kernel,
            blocks_all,
            load,
            store,
            on_block_done=lambda b: self.log_block_success(b.block_id),
            done_block_ids=done,
            validate_fn=validate_labels,
            failures_path=self.failures_path,
            task_name=self.uid,
            block_deadline_s=cfg.get("block_deadline_s"),
            watchdog_period_s=cfg.get("watchdog_period_s"),
            store_verify_fn=region_verifier(out),
            schedule=str(cfg.get("block_schedule") or "morton"),
            sweep_mode=str(cfg.get("sweep_mode") or "auto"),
            sharded_batch=cfg.get("sharded_batch"),
            device_pool=str(cfg.get("device_pool") or "auto"),
            device_pool_bytes=cfg.get("device_pool_bytes"),
            # degrade on OOM/ENOSPC; never splittable: the per-block CC
            # decomposition (and the min-voxel label of a component crossing
            # a would-be split plane) changes under sub-block re-execution
            splittable=False,
            degrade_wait_s=float(cfg.get("degrade_wait_s", 5.0)),
            inflight_byte_budget=cfg.get("inflight_byte_budget"),
        )
        return {"n_blocks": len(block_ids), "shape": list(shape)}


class BlockComponentsLocal(BlockComponentsBase):
    target = "local"


class BlockComponentsTPU(BlockComponentsBase):
    target = "tpu"


class MergeLabelsBase(BaseTask):
    """Merge per-block unique labels into the dense global label table.

    Replaces the reference's ``merge_offsets`` prefix-sum (our labels are
    globally unique already); the table maps sorted uint64 labels -> dense
    int32 ids for the device union-find.
    """

    task_name = "merge_labels"

    def run_impl(self):
        cfg = self.get_config()
        shape = handoff.resolve_dataset(
            cfg["input_path"], cfg["input_key"]
        ).shape
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        uniques = [
            handoff.load_array(_uniques_path(self.tmp_folder, b))
            for b in block_ids
            if handoff.array_exists(_uniques_path(self.tmp_folder, b))
        ]
        table = (
            np.unique(np.concatenate(uniques))
            if uniques
            else np.zeros(0, np.uint64)
        )
        self.save_handoff_array(
            os.path.join(self.tmp_folder, "cc_label_table.npy"), table
        )
        return {"n_labels": len(table)}


class MergeLabelsLocal(MergeLabelsBase):
    target = "local"


class MergeLabelsTPU(MergeLabelsBase):
    target = "tpu"


def _shifted_views(a: np.ndarray, b: np.ndarray, shifts) -> tuple:
    """Views pairing ``a[p]`` with ``b[p + shifts]`` (per free axis)."""
    sl_a, sl_b = [], []
    for sh, n in zip(shifts, a.shape):
        if sh == 1:
            sl_a.append(slice(0, n - 1))
            sl_b.append(slice(1, n))
        elif sh == -1:
            sl_a.append(slice(1, n))
            sl_b.append(slice(0, n - 1))
        else:
            sl_a.append(slice(None))
            sl_b.append(slice(None))
    return a[tuple(sl_a)], b[tuple(sl_b)]


class BlockFacesBase(BaseTask):
    """Pass 2: scan adjacent block boundaries for label equivalences.

    For every block and every unordered neighbor direction (faces at
    connectivity 1; faces, edges, and corners at higher connectivity), reads
    the 1-voxel slabs on either side of the shared boundary and emits
    (label_a, label_b) pairs for every in-range voxel offset with at most
    ``connectivity`` differing coordinates — the blockwise completion of the
    per-block CCL's neighborhood (scipy semantics).  Host-side: thin-slab IO
    is bandwidth-bound, not compute.
    """

    task_name = "block_faces"

    def run_impl(self):
        from itertools import product

        from ..ops.ccl import _neighbor_offsets

        cfg = self.get_config()
        connectivity = int(cfg.get("connectivity", 1))
        keyed = bool(cfg.get("keyed", False))
        inp_ds = (
            handoff.resolve_dataset(cfg["input_path"], cfg["input_key"])
            if keyed else None
        )
        # fusable edge (block_components -> block_faces): slab reads come
        # from the live in-memory label volume when one exists
        ds = handoff.resolve_dataset(cfg["output_path"], cfg["output_key"])
        shape = ds.shape
        ndim = len(shape)
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        roi_set = set(block_ids)
        if not 1 <= connectivity <= ndim:
            raise ValueError(f"connectivity must be in [1, {ndim}]")
        # the kernel's half-neighborhood doubles as the unordered
        # block-direction list (each adjacent block pair scanned once);
        # {-1,0,1} offsets make sum(|o|) == nnz, so the budgets coincide
        directions = _neighbor_offsets(ndim, connectivity)
        self.declare_handoff_producer()

        def slab_bbs(block, d):
            """(our-side bb, neighbor-side bb) of the shared boundary."""
            bb_a, bb_b = [], []
            for a, o in enumerate(d):
                b, e = block.begin[a], block.end[a]
                if o == 1:
                    bb_a.append(slice(e - 1, e))
                    bb_b.append(slice(e, e + 1))
                elif o == -1:
                    bb_a.append(slice(b, b + 1))
                    bb_b.append(slice(b - 1, b))
                else:
                    bb_a.append(slice(b, e))
                    bb_b.append(slice(b, e))
            return tuple(bb_a), tuple(bb_b)

        def process(block_id: int):
            block = blocking.get_block(block_id)
            pairs = []
            for d in directions:
                nbr = blocking.neighbor_id_offset(block_id, d)
                if nbr is None or nbr not in roi_set:
                    continue
                bb_a, bb_b = slab_bbs(block, d)
                crossing = tuple(a for a in range(ndim) if d[a] != 0)
                A = np.asarray(ds[bb_a]).squeeze(axis=crossing)
                B = np.asarray(ds[bb_b]).squeeze(axis=crossing)
                if keyed:
                    ka = np.asarray(inp_ds[bb_a]).squeeze(axis=crossing)
                    kb = np.asarray(inp_ds[bb_b]).squeeze(axis=crossing)
                free_budget = connectivity - len(crossing)
                for s in product((-1, 0, 1), repeat=ndim - len(crossing)):
                    if sum(1 for o in s if o) > free_budget:
                        continue
                    av, bv = _shifted_views(A, B, s)
                    both = (av > 0) & (bv > 0)
                    if keyed:
                        # CC-on-segmentation: only merge across the boundary
                        # where the ORIGINAL segment label matches
                        kav, kbv = _shifted_views(ka, kb, s)
                        both &= kav == kbv
                    if both.any():
                        p = np.stack([av[both], bv[both]], axis=1)
                        pairs.append(np.unique(p, axis=0))
            result = (
                np.concatenate(pairs)
                if pairs
                else np.zeros((0, 2), np.uint64)
            )
            self.save_handoff_array(_faces_path(self.tmp_folder, block_id), result)

        n = self.host_block_map(block_ids, process)
        return {"n_blocks": n}


class BlockFacesLocal(BlockFacesBase):
    target = "local"


class BlockFacesTPU(BlockFacesBase):
    target = "tpu"


class MergeAssignmentsBase(BaseTask):
    """Union-find over all face equivalences -> global assignment table.

    The reference ran serial ``nifty.ufd`` here; we map labels to dense ids
    and run the pointer-jumping union-find on device (host scipy fallback for
    tiny problems).  The final assignment renumbers roots consecutively.
    """

    task_name = "merge_assignments"

    @staticmethod
    def default_task_config():
        return {"threads_per_job": 1, "device_batch": 1, "use_device": True}

    def run_impl(self):
        cfg = self.get_config()
        shape = handoff.resolve_dataset(
            cfg["input_path"], cfg["input_key"]
        ).shape
        table = handoff.load_array(
            os.path.join(self.tmp_folder, "cc_label_table.npy")
        )
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        pair_files = [
            _faces_path(self.tmp_folder, b)
            for b in block_ids
            if handoff.array_exists(_faces_path(self.tmp_folder, b))
        ]
        pairs = (
            np.concatenate([handoff.load_array(f) for f in pair_files])
            if pair_files
            else np.zeros((0, 2), np.uint64)
        )
        if len(pairs):
            pairs = np.unique(pairs, axis=0)
        n = len(table)
        # dense ids: position in the sorted label table
        dense_pairs = np.searchsorted(table, pairs).astype(np.int64)
        if n and cfg.get("use_device", True) and len(dense_pairs):
            roots = np.asarray(
                union_find(jnp.asarray(dense_pairs.astype(np.int32)), n)
            ).astype(np.int64)
        else:
            roots = union_find_host(dense_pairs, n)
        # renumber roots consecutively 1..K
        uniq_roots, assignment = np.unique(roots, return_inverse=True)
        assignment = (assignment + 1).astype(np.uint64)
        self.save_handoff_arrays(
            os.path.join(self.tmp_folder, "cc_assignments.npz"),
            keys=table,
            values=assignment,
        )
        return {"n_labels": n, "n_components": len(uniq_roots)}


class MergeAssignmentsLocal(MergeAssignmentsBase):
    target = "local"


class MergeAssignmentsTPU(MergeAssignmentsBase):
    target = "tpu"


class ConnectedComponentsWorkflow(WorkflowBase):
    """End-to-end blockwise CCL (reference: ``ConnectedComponentsWorkflow``)."""

    task_name = "connected_components_workflow"

    def requires(self):
        from . import connected_components as cc_mod
        from . import write as write_mod
        from ..runtime.task import get_task_cls

        cfg_common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        p = self.params
        # provisional per-block labels live in a tmp dataset, so the final
        # Write never mutates its own input (crash-safe block resume)
        tmp_path = os.path.join(self.tmp_folder, "cc_blocks.zarr")
        tmp_key = "labels"
        t1 = get_task_cls(cc_mod, "BlockComponents", self.target)(
            **cfg_common,
            dependencies=self.dependencies,
            input_path=p["input_path"],
            input_key=p["input_key"],
            output_path=tmp_path,
            output_key=tmp_key,
            **{
                k: p[k]
                for k in ("threshold", "threshold_mode", "mask_path", "mask_key", "block_shape", "connectivity", "keyed")
                if k in p
            },
        )
        t2 = get_task_cls(cc_mod, "MergeLabels", self.target)(
            **cfg_common,
            dependencies=[t1],
            input_path=p["input_path"],
            input_key=p["input_key"],
            **{k: p[k] for k in ("block_shape",) if k in p},
        )
        t3 = get_task_cls(cc_mod, "BlockFaces", self.target)(
            **cfg_common,
            dependencies=[t2],
            output_path=tmp_path,
            output_key=tmp_key,
            input_path=p["input_path"],
            input_key=p["input_key"],
            **{k: p[k] for k in ("block_shape", "connectivity", "keyed") if k in p},
        )
        t4 = get_task_cls(cc_mod, "MergeAssignments", self.target)(
            **cfg_common,
            dependencies=[t3],
            input_path=p["input_path"],
            input_key=p["input_key"],
            **{k: p[k] for k in ("block_shape",) if k in p},
        )
        t5 = get_task_cls(write_mod, "Write", self.target)(
            **cfg_common,
            dependencies=[t4],
            input_path=tmp_path,
            input_key=tmp_key,
            output_path=p["output_path"],
            output_key=p["output_key"],
            assignment_path=os.path.join(self.tmp_folder, "cc_assignments.npz"),
            **{k: p[k] for k in ("block_shape",) if k in p},
        )
        return [t5]

    def run_impl(self):
        return {}
