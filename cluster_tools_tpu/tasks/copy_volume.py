"""Blockwise dataset copy/convert (reference: ``cluster_tools/copy_volume/``,
SURVEY.md §2a): h5 <-> n5 <-> zarr, dtype casts, chunk re-shaping, channel
slicing, optional fixed-range normalization.  Pure host bandwidth —
parallelized over the IO thread pool."""

from __future__ import annotations

import numpy as np

from ..runtime.executor import region_verifier
from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


class CopyVolumeBase(BaseTask):
    """Params: ``input_path/input_key``, ``output_path/output_key``; optional
    ``dtype`` (cast), ``out_chunks``, ``channel`` (int: slice a leading
    channel axis), ``scale_factor``/``offset`` (affine y = x*scale + offset,
    applied before the cast), ``fit_to_roi``."""

    task_name = "copy_volume"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "dtype": None,
            "out_chunks": None,
            "channel": None,
            "scale_factor": None,
            "offset": None,
            "fit_to_roi": False,
        }

    def run_impl(self):
        cfg = self.get_config()
        inp = file_reader(cfg["input_path"])[cfg["input_key"]]
        channel = cfg.get("channel")
        in_shape = inp.shape[1:] if channel is not None else inp.shape
        block_shape = tuple(cfg["block_shape"])
        out_chunks = tuple(cfg.get("out_chunks") or block_shape)
        if any(b % c for b, c in zip(block_shape, out_chunks)):
            # race safety (SURVEY.md §5.2): parallel block writes must tile
            # whole output chunks — the container guard can only compare the
            # requested chunks, not the write grid, so enforce it here
            raise ValueError(
                f"block_shape {block_shape} must be a per-axis multiple of "
                f"out_chunks {out_chunks} for chunk-aligned parallel writes"
            )
        dtype = cfg.get("dtype") or str(inp.dtype)
        scale, offset = cfg.get("scale_factor"), cfg.get("offset")
        roi_begin, roi_end = cfg.get("roi_begin"), cfg.get("roi_end")
        fit_to_roi = bool(cfg.get("fit_to_roi")) and roi_begin is not None
        if fit_to_roi:
            # output covers exactly the ROI, shifted to the origin
            re = roi_end if roi_end is not None else in_shape
            out_shape = tuple(int(e) - int(b) for b, e in zip(roi_begin, re))
            shift = tuple(int(b) for b in roi_begin)
        else:
            out_shape = in_shape
            shift = tuple(0 for _ in in_shape)

        out = file_reader(cfg["output_path"]).require_dataset(
            cfg["output_key"], shape=out_shape, chunks=out_chunks, dtype=dtype
        )
        blocking = Blocking(in_shape, block_shape)
        block_ids = blocks_in_volume(in_shape, block_shape, roi_begin, roi_end)

        def _convert(data):
            if scale is not None or offset is not None:
                data = data.astype(np.float64) * (
                    1.0 if scale is None else scale
                ) + (0.0 if offset is None else offset)
            target = np.dtype(dtype)
            if np.issubdtype(target, np.integer) and target != data.dtype:
                info = np.iinfo(target)
                if np.issubdtype(data.dtype, np.integer):
                    # narrowing / sign-changing int casts must clip, not wrap
                    src = np.iinfo(data.dtype)
                    lo = max(int(info.min), int(src.min))
                    hi = min(int(info.max), int(src.max))
                    data = np.clip(data, lo, hi)
                else:
                    data = np.clip(np.round(data), info.min, info.max)
            return data.astype(dtype)

        roi_lo = tuple(int(b) for b in (roi_begin or [0] * len(in_shape)))
        roi_hi = tuple(
            int(e) for e in (roi_end if roi_end is not None else in_shape)
        )

        def process(block_id):
            bb = blocking.get_block(block_id).bb
            # clip to the ROI: blocks straddling a non-aligned ROI edge must
            # not read/write outside it (out_bb would go negative/OOB)
            bb = tuple(
                slice(max(b.start, lo), min(b.stop, hi))
                for b, lo, hi in zip(bb, roi_lo, roi_hi)
            )
            data = inp[(channel,) + bb] if channel is not None else inp[bb]
            out_bb = tuple(
                slice(b.start - s, b.stop - s) for b, s in zip(bb, shift)
            )
            out[out_bb] = _convert(data)

        def _out_bb(block):
            # the region process() actually wrote: ROI-clipped, shifted to
            # the output's origin — verifying block.bb would miss the digest
            bb = tuple(
                slice(max(b.start, lo), min(b.stop, hi))
                for b, lo, hi in zip(block.bb, roi_lo, roi_hi)
            )
            return tuple(
                slice(b.start - s, b.stop - s) for b, s in zip(bb, shift)
            )

        n = self.host_block_map(
            block_ids, process,
            store_verify_fn=region_verifier(out, bb_of=_out_bb),
            blocking=blocking,
        )
        return {"n_blocks": n, "shape": list(out_shape), "dtype": dtype}


class CopyVolumeLocal(CopyVolumeBase):
    target = "local"


class CopyVolumeTPU(CopyVolumeBase):
    target = "tpu"


class CopyVolumeWorkflow(WorkflowBase):
    task_name = "copy_volume_workflow"

    def requires(self):
        from . import copy_volume as cv_mod

        return [
            get_task_cls(cv_mod, "CopyVolume", self.target)(
                tmp_folder=self.tmp_folder,
                config_dir=self.config_dir,
                max_jobs=self.max_jobs,
                dependencies=self.dependencies,
                **self.params,
            )
        ]

    def run_impl(self):
        return {}
