"""Blockwise dataset copy/convert (reference: ``cluster_tools/copy_volume/``,
SURVEY.md §2a): h5 <-> n5 <-> zarr, dtype casts, chunk re-shaping, channel
slicing, optional fixed-range normalization.  Pure host bandwidth —
parallelized over the IO thread pool."""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


class CopyVolumeBase(BaseTask):
    """Params: ``input_path/input_key``, ``output_path/output_key``; optional
    ``dtype`` (cast), ``out_chunks``, ``channel`` (int: slice a leading
    channel axis), ``scale_factor``/``offset`` (affine y = x*scale + offset,
    applied before the cast), ``fit_to_roi``."""

    task_name = "copy_volume"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "dtype": None,
            "out_chunks": None,
            "channel": None,
            "scale_factor": None,
            "offset": None,
        }

    def run_impl(self):
        cfg = self.get_config()
        inp = file_reader(cfg["input_path"])[cfg["input_key"]]
        channel = cfg.get("channel")
        shape = inp.shape[1:] if channel is not None else inp.shape
        block_shape = tuple(cfg["block_shape"])
        out_chunks = tuple(cfg.get("out_chunks") or block_shape)
        dtype = cfg.get("dtype") or str(inp.dtype)
        scale, offset = cfg.get("scale_factor"), cfg.get("offset")

        out = file_reader(cfg["output_path"]).require_dataset(
            cfg["output_key"], shape=shape, chunks=out_chunks, dtype=dtype
        )
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        done = set(self.blocks_done())

        def process(block_id):
            bb = blocking.get_block(block_id).bb
            data = inp[(channel,) + bb] if channel is not None else inp[bb]
            if scale is not None or offset is not None:
                data = data.astype(np.float64) * (
                    1.0 if scale is None else scale
                ) + (0.0 if offset is None else offset)
            if np.issubdtype(np.dtype(dtype), np.integer) and not np.issubdtype(
                data.dtype, np.integer
            ):
                info = np.iinfo(np.dtype(dtype))
                data = np.clip(np.round(data), info.min, info.max)
            out[bb] = data.astype(dtype)
            self.log_block_success(block_id)

        todo = [b for b in block_ids if b not in done]
        with ThreadPoolExecutor(max_workers=max(1, self.max_jobs)) as pool:
            list(pool.map(process, todo))
        return {"n_blocks": len(todo), "shape": list(shape), "dtype": dtype}


class CopyVolumeLocal(CopyVolumeBase):
    target = "local"


class CopyVolumeTPU(CopyVolumeBase):
    target = "tpu"


class CopyVolumeWorkflow(WorkflowBase):
    task_name = "copy_volume_workflow"

    def requires(self):
        from . import copy_volume as cv_mod

        return [
            get_task_cls(cv_mod, "CopyVolume", self.target)(
                tmp_folder=self.tmp_folder,
                config_dir=self.config_dir,
                max_jobs=self.max_jobs,
                dependencies=self.dependencies,
                **self.params,
            )
        ]

    def run_impl(self):
        return {}
