"""Edge probabilities -> multicut costs.

Re-design of the reference's ``cluster_tools/costs/probs_to_costs.py``
(SURVEY.md §2a "costs"): the classic transform

    w(e) = log((1 - p_e) / p_e) + log((1 - beta) / beta)

with optional edge-size weighting and ignore-label handling.  A single
driver-side task (the reference also ran it as one job): m edges is tiny
next to the volume.  The vectorized transform runs through jax.numpy so the
same code path serves host and device.

Artifact: ``tmp_folder/graph/costs.npy`` (float32 [m]), aligned with
``graph.npz``'s edge list.
"""

from __future__ import annotations

import os

import numpy as np
import jax.numpy as jnp

from ..runtime import handoff
from ..runtime.task import BaseTask
from .features import features_path
from .graph import graph_dir, load_global_graph


def costs_path(tmp_folder: str) -> str:
    return os.path.join(graph_dir(tmp_folder), "costs.npy")


def compute_costs(
    probs: np.ndarray,
    beta: float = 0.5,
    edge_sizes: np.ndarray | None = None,
    weighting_exponent: float = 1.0,
    eps: float = 1e-5,
) -> np.ndarray:
    """The probability->cost transform, vectorized.

    ``beta`` < 0.5 biases toward merging, > 0.5 toward splitting.  With
    ``edge_sizes``, costs are scaled by ``(size / max_size) ** exponent``
    (the reference's 'xy'/size weighting scheme collapsed to its core).
    """
    p = jnp.clip(jnp.asarray(probs, jnp.float32), eps, 1.0 - eps)
    w = jnp.log((1.0 - p) / p) + float(np.log((1.0 - beta) / beta))
    if edge_sizes is not None:
        sizes = jnp.asarray(edge_sizes, jnp.float32)
        w = w * (sizes / jnp.maximum(sizes.max(), 1.0)) ** weighting_exponent
    return np.asarray(w, dtype=np.float32)


class ProbsToCostsBase(BaseTask):
    """Transform merged edge features into signed multicut costs.

    Params: ``beta``, ``weighting_scheme`` (None or 'size'),
    ``weighting_exponent``; optional ``ignore_label`` semantics are already
    enforced upstream (label 0 never becomes a graph node).
    """

    task_name = "probs_to_costs"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "beta": 0.5,
            "weighting_scheme": None,
            "weighting_exponent": 1.0,
        }

    def run_impl(self):
        cfg = self.get_config()
        # fusable edges (features -> costs, graph -> costs): consume the
        # merged features and edge sizes from live in-memory handoffs
        feats = handoff.load_array(features_path(self.tmp_folder))
        _, _, _, sizes = load_global_graph(self.tmp_folder)
        probs = feats[:, 0]
        use_sizes = cfg.get("weighting_scheme") == "size"
        costs = compute_costs(
            probs,
            beta=float(cfg.get("beta", 0.5)),
            edge_sizes=sizes if use_sizes else None,
            weighting_exponent=float(cfg.get("weighting_exponent", 1.0)),
        )
        self.save_handoff_array(costs_path(self.tmp_folder), costs)
        return {"n_edges": len(costs), "n_attractive": int((costs > 0).sum())}


class ProbsToCostsLocal(ProbsToCostsBase):
    target = "local"


class ProbsToCostsTPU(ProbsToCostsBase):
    target = "tpu"
