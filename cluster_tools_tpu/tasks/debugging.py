"""Sanity checks on intermediate artifacts.

Re-design of the reference's ``cluster_tools/debugging/`` (SURVEY.md §2a:
"sanity checks on intermediate artifacts, e.g. re-check sub-graphs vs
seg").  Two checkers:

- :class:`CheckSubGraphsBase`: re-extract every block's RAG from the
  segmentation and compare against the stored per-block graph artifacts
  (catches stale graph caches after a re-run with changed labels).
- :class:`CheckBlocksBase`: scan a dataset blockwise for NaN/Inf, all-zero
  blocks, and dtype-range violations — the "did inference/IO corrupt
  something" check.

Both write a JSON report and fail the task (so the DAG halts) when
violations are found, unless ``warn_only``.  Checks deliberately do NOT use
block-level resume markers: a failed check must re-inspect every block on
retry, otherwise the rerun would skip the flagged blocks and pass.
"""

from __future__ import annotations

import json
import os

import numpy as np

from concurrent.futures import ThreadPoolExecutor

from ..ops.rag import block_rag
from ..runtime import handoff
from ..runtime.task import BaseTask
from ..utils import function_utils as fu


def _scan_all(task, block_ids, process):
    """Run ``process`` over ALL blocks (no resume markers — see module
    docstring), surfacing every exception."""
    with ThreadPoolExecutor(max_workers=max(1, task.max_jobs)) as pool:
        list(pool.map(process, block_ids))
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader
from .graph import _upper_halo_bb, block_graph_path


class CheckSubGraphsBase(BaseTask):
    """Validate stored block graphs against the segmentation."""

    task_name = "check_sub_graphs"

    @staticmethod
    def default_task_config():
        return {"threads_per_job": 1, "device_batch": 1, "warn_only": False}

    def run_impl(self):
        cfg = self.get_config()
        # the volume under validation may live only in a handoff handle
        ds = handoff.resolve_dataset(cfg["input_path"], cfg["input_key"])
        shape = ds.shape
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        bad = []

        def process(block_id):
            p = block_graph_path(self.tmp_folder, block_id)
            if not handoff.array_exists(p):
                bad.append({"block": block_id, "error": "missing graph artifact"})
                return
            block = blocking.get_block(block_id)
            seg = np.asarray(ds[_upper_halo_bb(block, shape)])
            uv, sizes, _ = block_rag(seg, inner_shape=block.shape)
            f = handoff.load_arrays(p)
            ok = (
                f["uv"].shape == uv.shape
                and (f["uv"] == uv).all()
                and (f["sizes"] == sizes).all()
            )
            if not ok:
                bad.append({"block": block_id, "error": "graph mismatch"})

        _scan_all(self, block_ids, process)
        report = {"n_blocks": len(block_ids), "violations": bad}
        # atomic (CT002): the report is a shared tmp_folder manifest
        fu.atomic_write_json(
            os.path.join(self.tmp_folder, "check_sub_graphs.json"), report
        )
        if bad and not cfg.get("warn_only", False):
            raise RuntimeError(
                f"sub-graph check failed for {len(bad)} blocks "
                f"(see check_sub_graphs.json)"
            )
        return report


class CheckSubGraphsLocal(CheckSubGraphsBase):
    target = "local"


class CheckSubGraphsTPU(CheckSubGraphsBase):
    target = "tpu"


class CheckBlocksBase(BaseTask):
    """Scan a dataset for NaN/Inf / all-zero blocks."""

    task_name = "check_blocks"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "warn_only": False,
            "check_all_zero": True,
        }

    def run_impl(self):
        cfg = self.get_config()
        # the volume under validation may live only in a handoff handle
        ds = handoff.resolve_dataset(cfg["input_path"], cfg["input_key"])
        shape = ds.shape
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        check_zero = bool(cfg.get("check_all_zero", True))
        bad = []

        def process(block_id):
            data = np.asarray(ds[blocking.get_block(block_id).bb])
            if np.issubdtype(data.dtype, np.floating):
                if not np.isfinite(data).all():
                    bad.append({"block": block_id, "error": "non-finite values"})
                    return
            if check_zero and not data.any():
                bad.append({"block": block_id, "error": "all-zero block"})

        _scan_all(self, block_ids, process)
        report = {"n_blocks": len(block_ids), "violations": bad}
        # atomic (CT002): the report is a shared tmp_folder manifest
        fu.atomic_write_json(
            os.path.join(self.tmp_folder, "check_blocks.json"), report
        )
        if bad and not cfg.get("warn_only", False):
            raise RuntimeError(
                f"block check failed for {len(bad)} blocks (see check_blocks.json)"
            )
        return report


class CheckBlocksLocal(CheckBlocksBase):
    target = "local"


class CheckBlocksTPU(CheckBlocksBase):
    target = "tpu"
