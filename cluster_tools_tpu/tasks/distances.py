"""Object-pair boundary distances, blockwise.

Re-design of the reference's ``cluster_tools/distances/`` (SURVEY.md §2a:
object-pair distance computations).  For every pair of distinct objects
whose surfaces come within ``max_distance`` of each other, compute the
minimum boundary-to-boundary distance:

1. per block (read with a ``max_distance`` halo): collect boundary voxels
   per object, kd-tree query between object pairs present in the window,
   record per-pair minima;
2. merge: global minimum per pair.

Artifacts: ``distances/block_<id>.npz`` parts and the merged
``distances/distances.npz`` {pairs [m, 2], distances [m]}.
"""

from __future__ import annotations

import os
from collections import defaultdict

import numpy as np

from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


def distances_dir(tmp_folder: str) -> str:
    d = os.path.join(tmp_folder, "distances")
    os.makedirs(d, exist_ok=True)
    return d


def distances_path(tmp_folder: str) -> str:
    return os.path.join(distances_dir(tmp_folder), "distances.npz")


def boundary_voxels(seg: np.ndarray) -> np.ndarray:
    """Mask of voxels adjacent (face-connectivity) to a different label."""
    b = np.zeros(seg.shape, bool)
    for axis in range(seg.ndim):
        sl_a = [slice(None)] * seg.ndim
        sl_b = [slice(None)] * seg.ndim
        sl_a[axis] = slice(0, -1)
        sl_b[axis] = slice(1, None)
        diff = seg[tuple(sl_a)] != seg[tuple(sl_b)]
        b[tuple(sl_a)] |= diff
        b[tuple(sl_b)] |= diff
    return b


def block_pair_distances(
    seg: np.ndarray, max_distance: float, sampling=(1.0, 1.0, 1.0)
):
    """Min distances between boundary voxels of object pairs within one
    window.  Returns (pairs [m, 2] uint64, dists [m])."""
    from scipy.spatial import cKDTree

    bmask = boundary_voxels(seg) & (seg != 0)
    labels = seg[bmask]
    coords = np.argwhere(bmask).astype(np.float64) * np.asarray(sampling)
    result = {}
    ids = np.unique(labels)
    trees = {}
    for obj in ids:
        trees[obj] = cKDTree(coords[labels == obj])
    for i, a in enumerate(ids):
        for b in ids[i + 1 :]:
            m = trees[a].sparse_distance_matrix(
                trees[b], max_distance=float(max_distance), output_type="coo_matrix"
            )
            if m.nnz:
                result[(int(a), int(b))] = float(m.data.min())
    if not result:
        return np.zeros((0, 2), np.uint64), np.zeros(0)
    pairs = np.array(sorted(result), dtype=np.uint64)
    dists = np.array([result[tuple(p)] for p in pairs])
    return pairs, dists


class BlockDistancesBase(BaseTask):
    """Per-block pair distances (window = block + max_distance halo)."""

    task_name = "block_distances"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "max_distance": 10.0,
            "sampling": [1.0, 1.0, 1.0],
        }

    def run_impl(self):
        cfg = self.get_config()
        ds = file_reader(cfg["input_path"])[cfg["input_key"]]
        shape = ds.shape
        block_shape = tuple(cfg["block_shape"])
        max_dist = float(cfg.get("max_distance", 10.0))
        sampling = tuple(cfg.get("sampling") or (1.0,) * len(shape))
        halo = tuple(
            int(np.ceil(max_dist / s)) for s in sampling
        )
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = distances_dir(self.tmp_folder)

        def process(block_id):
            block = blocking.get_block(block_id, halo)
            seg = np.asarray(ds[block.outer_bb])
            pairs, dists = block_pair_distances(seg, max_dist, sampling)
            np.savez(
                os.path.join(d, f"block_{block_id}.npz"),
                pairs=pairs,
                dists=dists,
            )

        n = self.host_block_map(block_ids, process)
        return {"n_blocks": n}


class BlockDistancesLocal(BlockDistancesBase):
    target = "local"


class BlockDistancesTPU(BlockDistancesBase):
    target = "tpu"


class MergeDistancesBase(BaseTask):
    """Global minimum per object pair."""

    task_name = "merge_distances"

    def run_impl(self):
        cfg = self.get_config()
        shape = file_reader(cfg["input_path"])[cfg["input_key"]].shape
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = distances_dir(self.tmp_folder)
        best = defaultdict(lambda: np.inf)
        for b in block_ids:
            p = os.path.join(d, f"block_{b}.npz")
            if not os.path.exists(p):
                continue
            with np.load(p) as f:
                for (a, c), dist in zip(f["pairs"], f["dists"]):
                    key = (int(a), int(c))
                    if dist < best[key]:
                        best[key] = float(dist)
        pairs = np.array(sorted(best), dtype=np.uint64).reshape(-1, 2)
        dists = np.array([best[tuple(map(int, p))] for p in pairs])
        np.savez(distances_path(self.tmp_folder), pairs=pairs, dists=dists)
        return {"n_pairs": int(len(pairs))}


class MergeDistancesLocal(MergeDistancesBase):
    target = "local"


class MergeDistancesTPU(MergeDistancesBase):
    target = "tpu"


class PairwiseDistanceWorkflow(WorkflowBase):
    task_name = "pairwise_distance_workflow"

    def requires(self):
        from . import distances as dist_mod

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        kw = {
            k: p[k]
            for k in (
                "input_path",
                "input_key",
                "max_distance",
                "sampling",
                "block_shape",
                "roi_begin",
                "roi_end",
            )
            if k in p
        }
        t1 = get_task_cls(dist_mod, "BlockDistances", self.target)(
            **common, dependencies=self.dependencies, **kw
        )
        t2 = get_task_cls(dist_mod, "MergeDistances", self.target)(
            **common, dependencies=[t1], **kw
        )
        return [t2]
