"""Blockwise multiscale pyramid (reference: ``cluster_tools/downscaling/``,
SURVEY.md §2a): per-scale blockwise downsampling (mean for raw data,
nearest/mode for labels, min/max variants), chained over scale levels by the
workflow, with paintera-style multiscale metadata (``downsamplingFactors``)
written to the dataset attributes."""

from __future__ import annotations

import os
from typing import List, Sequence

import numpy as np

from ..runtime.executor import region_verifier
from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


def _reduce_block(data: np.ndarray, factor: Sequence[int], mode: str) -> np.ndarray:
    """Downsample one region by integer ``factor`` per axis."""
    factor = tuple(int(f) for f in factor)
    if mode == "nearest":
        return data[tuple(slice(None, None, f) for f in factor)]
    # pad up to a multiple with edge values so edge cells average real data
    pad = [
        (0, (-s) % f) for s, f in zip(data.shape, factor)
    ]
    if any(p[1] for p in pad):
        data = np.pad(data, pad, mode="edge")
    new_shape = []
    for s, f in zip(data.shape, factor):
        new_shape += [s // f, f]
    blocks = data.reshape(new_shape)
    axes = tuple(range(1, 2 * data.ndim, 2))
    if mode == "mean":
        m = blocks.mean(axes)
        if np.issubdtype(data.dtype, np.integer):
            # keep the pyramid dtype-consistent with s0 (multiscale
            # consumers require it): round back to the input integer type
            info = np.iinfo(data.dtype)
            m = np.clip(np.round(m), info.min, info.max)
        return m.astype(data.dtype)
    if mode == "max":
        return blocks.max(axes)
    if mode == "min":
        return blocks.min(axes)
    if mode == "mode":
        # majority vote per cell (labels): flatten cell axes, take the most
        # frequent value.  O(cell) per voxel but cells are tiny (e.g. 2^3).
        flat = np.moveaxis(blocks, axes, range(data.ndim, 2 * data.ndim))
        flat = flat.reshape(flat.shape[: data.ndim] + (-1,))
        out = np.empty(flat.shape[: data.ndim], dtype=data.dtype)
        it = np.nditer(out, flags=["multi_index"], op_flags=["writeonly"])
        for x in it:
            vals, counts = np.unique(flat[it.multi_index], return_counts=True)
            x[...] = vals[np.argmax(counts)]
        return out
    raise ValueError(f"unknown downscaling mode {mode!r}")


class DownscalingBase(BaseTask):
    """One scale step: ``input_path/input_key`` (scale s) ->
    ``output_path/output_key`` (scale s+1), by ``scale_factor`` with
    ``mode`` in mean/nearest/mode/max/min."""

    task_name = "downscaling"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "scale_factor": [2, 2, 2],
            "mode": "mean",
        }

    def run_impl(self):
        cfg = self.get_config()
        inp = file_reader(cfg["input_path"])[cfg["input_key"]]
        factor = tuple(int(f) for f in cfg["scale_factor"])
        mode = cfg.get("mode", "mean")
        in_shape = inp.shape
        out_shape = tuple((s + f - 1) // f for s, f in zip(in_shape, factor))
        block_shape = tuple(cfg["block_shape"])
        dtype = str(inp.dtype)
        out = file_reader(cfg["output_path"]).require_dataset(
            cfg["output_key"], shape=out_shape, chunks=block_shape, dtype=dtype
        )
        blocking = Blocking(out_shape, block_shape)
        block_ids = blocks_in_volume(
            out_shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        def process(block_id):
            block = blocking.get_block(block_id)
            in_bb = tuple(
                slice(b.start * f, min(b.stop * f, s))
                for b, f, s in zip(block.bb, factor, in_shape)
            )
            out[block.bb] = _reduce_block(inp[in_bb], factor, mode).astype(dtype)

        n = self.host_block_map(
            block_ids, process,
            store_verify_fn=region_verifier(out), blocking=blocking,
        )
        # per-step factor; workflows overwrite with the cumulative factor
        out.update_attrs(downsamplingFactors=list(factor), downscalingMode=mode)
        return {"n_blocks": n, "out_shape": list(out_shape)}


class DownscalingLocal(DownscalingBase):
    target = "local"


class DownscalingTPU(DownscalingBase):
    target = "tpu"


class DownscalingWorkflow(WorkflowBase):
    """Chain scale levels: writes ``<output_key_prefix>/s1..sN`` from
    ``input_key`` (= s0), with cumulative ``downsamplingFactors`` metadata
    (reference: ``DownscalingWorkflow`` + paintera scale metadata)."""

    task_name = "downscaling_workflow"

    def requires(self):
        from . import downscaling as ds_mod

        p = self.params
        factors: List[Sequence[int]] = p["scale_factors"]
        prefix = p.get("output_key_prefix", "")
        mode = p.get("mode", "mean")
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        bs = {k: p[k] for k in ("block_shape",) if k in p}
        prev_key = p["input_key"]
        prev = None
        tasks = []
        for level, factor in enumerate(factors, start=1):
            key = (prefix + "/" if prefix else "") + f"s{level}"
            t = get_task_cls(ds_mod, "Downscaling", self.target)(
                **common,
                dependencies=self.dependencies if prev is None else [prev],
                input_path=p["input_path"] if prev is None else p["output_path"],
                input_key=prev_key,
                output_path=p["output_path"],
                output_key=key,
                scale_factor=list(factor),
                mode=mode,
                **bs,
            )
            tasks.append(t)
            prev, prev_key = t, key
        return [tasks[-1]] if tasks else []

    def run_impl(self):
        p = self.params
        prefix = p.get("output_key_prefix", "")
        out = file_reader(p["output_path"])
        # paintera-style multiscale metadata: downsamplingFactors must be
        # cumulative relative to s0, so rewrite each level's attrs here
        cum = []
        acc = np.ones(len(p["scale_factors"][0]), int)
        for level, f in enumerate(p["scale_factors"], start=1):
            acc = acc * np.asarray(f, int)
            cum.append([int(x) for x in acc])
            key = (prefix + "/" if prefix else "") + f"s{level}"
            out[key].update_attrs(downsamplingFactors=[int(x) for x in acc])
        return {"cumulative_factors": cum}

