"""Segmentation evaluation at scale: sparse contingency table -> VI / RAND.

Re-design of the reference's ``cluster_tools/evaluation/`` (SURVEY.md §2a):
blockwise sparse contingency tables between a segmentation and ground truth,
merged, then variation of information (split/merge entropies) and
adapted-RAND scores computed from the merged table.

The blockwise pair-counting reuses the node_labels overlap machinery; the
metric formulas act on the tiny merged table, on the driver.

Metrics (ignoring label 0 in both volumes):

- ``vi_split``  = H(seg | gt)   (over-segmentation distance, nats)
- ``vi_merge``  = H(gt | seg)   (under-segmentation distance, nats)
- ``adapted_rand_error`` = 1 - F1 of RAND precision/recall (CREMI style)
"""

from __future__ import annotations

import json
import os
from typing import Dict, Tuple

import numpy as np

from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils import function_utils as fu
from .node_labels import BlockNodeLabelsBase, _nl_dir
from ..utils.volume_utils import blocks_in_volume, file_reader


def contingency_metrics(
    pairs: np.ndarray, counts: np.ndarray
) -> Dict[str, float]:
    """VI and adapted-RAND from a sparse contingency table.

    ``pairs[:, 0]`` = segmentation ids, ``pairs[:, 1]`` = ground-truth ids,
    ``counts`` = co-occurrence voxel counts (label 0 already excluded).
    """
    if len(pairs) == 0:
        return {
            "vi_split": 0.0,
            "vi_merge": 0.0,
            "adapted_rand_error": 0.0,
            "n_pairs": 0,
        }
    n = counts.sum()
    p_ij = counts.astype(np.float64) / n
    seg_ids, seg_inv = np.unique(pairs[:, 0], return_inverse=True)
    gt_ids, gt_inv = np.unique(pairs[:, 1], return_inverse=True)
    p_seg = np.zeros(len(seg_ids))
    np.add.at(p_seg, seg_inv.ravel(), p_ij)
    p_gt = np.zeros(len(gt_ids))
    np.add.at(p_gt, gt_inv.ravel(), p_ij)

    # conditional entropies from the joint + marginals
    h_joint = -np.sum(p_ij * np.log(p_ij))
    h_seg = -np.sum(p_seg * np.log(p_seg))
    h_gt = -np.sum(p_gt * np.log(p_gt))
    vi_split = h_joint - h_gt   # H(seg|gt)
    vi_merge = h_joint - h_seg  # H(gt|seg)

    # adapted RAND (CREMI): precision = sum p_ij^2 / sum p_seg^2,
    # recall = sum p_ij^2 / sum p_gt^2, ARE = 1 - F1
    sum_ij = np.sum(p_ij**2)
    prec = sum_ij / np.sum(p_seg**2)
    rec = sum_ij / np.sum(p_gt**2)
    f1 = 2 * prec * rec / max(prec + rec, 1e-12)
    return {
        "vi_split": float(max(vi_split, 0.0)),
        "vi_merge": float(max(vi_merge, 0.0)),
        "adapted_rand_error": float(1.0 - f1),
        "rand_precision": float(prec),
        "rand_recall": float(rec),
        "n_pairs": int(len(pairs)),
    }


class ContingencyTableBase(BlockNodeLabelsBase):
    """Blockwise (seg, gt) co-occurrence counts — the node_labels vote pass
    with both zero-ignores on (reference: ``ContingencyTableBase``)."""

    task_name = "contingency_table"


class ContingencyTableLocal(ContingencyTableBase):
    target = "local"


class ContingencyTableTPU(ContingencyTableBase):
    target = "tpu"


class MeasuresBase(BaseTask):
    """Merge contingency parts and compute the metrics (reference: the
    evaluation measures task).  Writes ``evaluation.json``."""

    task_name = "measures"

    def run_impl(self):
        cfg = self.get_config()
        shape = file_reader(cfg["input_path"])[cfg["input_key"]].shape
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = _nl_dir(self.tmp_folder, "contingency_table_parts")
        all_pairs, all_counts = [], []
        for b in block_ids:
            p = os.path.join(d, f"block_{b}.npz")
            if os.path.exists(p):
                with np.load(p) as f:
                    all_pairs.append(f["pairs"])
                    all_counts.append(f["counts"])
        pairs = (
            np.concatenate([p for p in all_pairs if len(p)])
            if any(len(p) for p in all_pairs)
            else np.zeros((0, 2), np.uint64)
        )
        counts = (
            np.concatenate([c for c in all_counts if len(c)])
            if any(len(c) for c in all_counts)
            else np.zeros(0, np.int64)
        )
        if len(pairs):
            uv, inv = np.unique(pairs, axis=0, return_inverse=True)
            merged = np.zeros(len(uv), np.int64)
            np.add.at(merged, inv.ravel(), counts)
        else:
            uv, merged = pairs, counts
        metrics = contingency_metrics(uv, merged)
        # atomic (CT002): the report is a shared tmp_folder manifest
        fu.atomic_write_json(
            os.path.join(self.tmp_folder, "evaluation.json"), metrics
        )
        return metrics


class MeasuresLocal(MeasuresBase):
    target = "local"


class MeasuresTPU(MeasuresBase):
    target = "tpu"


class EvaluationWorkflow(WorkflowBase):
    """contingency_table -> measures.  Params: ``input_path/input_key``
    (segmentation), ``labels_path/labels_key`` (ground truth)."""

    task_name = "evaluation_workflow"

    def requires(self):
        from . import evaluation as ev_mod

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        kw = {
            k: p[k]
            for k in (
                "input_path",
                "input_key",
                "labels_path",
                "labels_key",
                "block_shape",
                "roi_begin",
                "roi_end",
            )
            if k in p
        }
        t1 = get_task_cls(ev_mod, "ContingencyTable", self.target)(
            **common, dependencies=self.dependencies, **kw
        )
        t2 = get_task_cls(ev_mod, "Measures", self.target)(
            **common, dependencies=[t1], **kw
        )
        return [t2]
