"""Edge features: per-block accumulation + global weighted merge.

Re-design of the reference's ``cluster_tools/features/`` (SURVEY.md §2a
"features"): ``block_edge_features.py`` accumulated boundary-map/affinity
statistics per RAG edge through ``nifty.distributed``; ``merge_edge_features``
did the count-weighted merge.  Here the per-block scan+accumulate reuses the
jitted RAG kernel (:func:`..ops.rag.block_rag` with values), and the merge is
:func:`..ops.rag.merge_feature_lists` on the driver.

Artifacts (in ``tmp_folder/graph``, next to the graph):

    features_block_<id>.npz  {uv, feats}     per-block edge features
    features.npy             float32 [m, 5]  (mean, min, max, count, variance) per
                                             global edge, aligned with
                                             graph.npz's edge list
"""

from __future__ import annotations

import os
import numpy as np

from ..ops.rag import block_rag, merge_feature_lists
from ..runtime import handoff
from ..runtime.task import BaseTask, WorkflowBase
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader
from .graph import _upper_halo_bb, graph_dir, load_global_graph


def block_features_path(tmp_folder: str, block_id: int) -> str:
    return os.path.join(graph_dir(tmp_folder), f"features_block_{block_id}.npz")


def features_path(tmp_folder: str) -> str:
    return os.path.join(graph_dir(tmp_folder), "features.npy")


def _read_boundary_map(ds, bb, channel):
    """Read a boundary/affinity map block; reduce a channel axis if present.

    ``channel``: None (no channel axis), int, or list of ints (averaged) —
    matching the reference's affinity-channel handling.
    """
    if channel is None:
        return np.asarray(ds[bb])
    if isinstance(channel, int):
        return np.asarray(ds[(slice(channel, channel + 1),) + bb][0])
    sel = np.asarray(ds[(slice(min(channel), max(channel) + 1),) + bb])
    sel = sel[[c - min(channel) for c in channel]]
    return sel.mean(axis=0)


class BlockEdgeFeaturesBase(BaseTask):
    """Per-block edge-feature accumulation (reference:
    ``block_edge_features.py``).

    Params: ``input_path/input_key`` (boundary or affinity map, optionally
    with a leading channel axis + ``channel`` selector), ``labels_path/
    labels_key`` (the supervoxels the graph was built from).
    """

    task_name = "block_edge_features"

    @staticmethod
    def default_task_config():
        return {"threads_per_job": 1, "device_batch": 1, "channel": None}

    def run_impl(self):
        cfg = self.get_config()
        # fusable edges: the boundary map may itself be a live in-memory
        # handoff (inference/ilastik output), and the supervoxels come
        # from the watershed producer's handle when one exists
        ds_in = handoff.resolve_dataset(cfg["input_path"], cfg["input_key"])
        ds_labels = handoff.resolve_dataset(cfg["labels_path"], cfg["labels_key"])
        shape = ds_labels.shape
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        channel = cfg.get("channel")
        self.declare_handoff_producer()

        def process(block_id: int):
            block = blocking.get_block(block_id)
            bb = _upper_halo_bb(block, shape)
            seg = np.asarray(ds_labels[bb])
            val = _read_boundary_map(ds_in, bb, channel)
            uv, _, feats = block_rag(seg, values=val, inner_shape=block.shape)
            self.save_handoff_arrays(
                block_features_path(self.tmp_folder, block_id), uv=uv, feats=feats
            )

        n = self.host_block_map(block_ids, process)
        return {"n_blocks": n}


class BlockEdgeFeaturesLocal(BlockEdgeFeaturesBase):
    target = "local"


class BlockEdgeFeaturesTPU(BlockEdgeFeaturesBase):
    target = "tpu"


class MergeEdgeFeaturesBase(BaseTask):
    """Count-weighted merge of block features onto the global edge list
    (reference: ``merge_edge_features.py``)."""

    task_name = "merge_edge_features"

    def run_impl(self):
        cfg = self.get_config()
        shape = handoff.resolve_dataset(
            cfg["labels_path"], cfg["labels_key"]
        ).shape
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        _, uv_global, _, _ = load_global_graph(self.tmp_folder)

        def parts():
            for b in block_ids:
                f = handoff.load_arrays(
                    block_features_path(self.tmp_folder, b)
                )
                yield f["uv"], f["feats"]

        feats = merge_feature_lists(uv_global, parts())
        self.save_handoff_array(features_path(self.tmp_folder), feats)
        return {"n_edges": len(feats)}


class MergeEdgeFeaturesLocal(MergeEdgeFeaturesBase):
    target = "local"


class MergeEdgeFeaturesTPU(MergeEdgeFeaturesBase):
    target = "tpu"


class EdgeFeaturesWorkflow(WorkflowBase):
    """BlockEdgeFeatures -> MergeEdgeFeatures."""

    task_name = "edge_features_workflow"

    def requires(self):
        from . import features as feat_mod
        from ..runtime.task import get_task_cls

        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        p = self.params
        keys = {
            k: p[k]
            for k in (
                "input_path",
                "input_key",
                "labels_path",
                "labels_key",
                "channel",
                "block_shape",
                "roi_begin",
                "roi_end",
            )
            if k in p
        }
        t1 = get_task_cls(feat_mod, "BlockEdgeFeatures", self.target)(
            **common, dependencies=self.dependencies, **keys
        )
        t2 = get_task_cls(feat_mod, "MergeEdgeFeatures", self.target)(
            **common, dependencies=[t1], **keys
        )
        return [t2]
