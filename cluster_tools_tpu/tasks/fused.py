"""The fused mesh-resident segmentation step as a task-library citizen.

The blockwise watershed/CC task chains (SURVEY.md §3.2/§3.5) exist for
volumes larger than device memory; when the working ROI *fits* in HBM, five
tasks and thousands of chunk round-trips collapse into ONE compiled SPMD
program — the same fused step the benchmark measures
(:func:`cluster_tools_tpu.parallel.pipeline.make_ws_ccl_step`: halo exchange
over ICI, per-shard DT watershed, cross-shard fragment stitch and
union-find CC merge as collectives).  This task is the workflow-API bridge
to that fast path: read the ROI, run the step over the device mesh, write
``ws``/``cc`` labels back blockwise.

The reference has no analogue — its runtime cannot express "one program
over many nodes" at all; this is where the TPU-first redesign pays off
directly through the same task/config machinery users already drive.
"""

from __future__ import annotations

import numpy as np

from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import file_reader


class FusedSegmentationBase(BaseTask):
    """Whole-ROI fused watershed + merged CC on the device mesh.

    Params: ``input_path/input_key`` (boundary map), ``output_path`` +
    ``ws_key``/``cc_key`` (either may be omitted to skip that output).
    Config: ``threshold``, ``halo``, ``dt_max_distance``,
    ``min_seed_distance``, ``stitch_ws_threshold``, ``exact_edt``,
    ``max_labels_per_shard``, ``impl``, ``decomposition`` — the
    fused-pipeline knobs; ``decomposition="grid"`` shards the ROI over z
    AND y instead of z-slabs.  ``execution="split"`` runs the step as the
    four-program staged chain (``parallel.split_pipeline``) instead of the
    fused monolith — bit-identical outputs, per-program compile cost in
    the tiled-CCL class; the mode for backends where the monolith's
    compile time, not runtime, is the binding constraint.

    The ROI must fit in device memory (sharded over the mesh); this task
    refuses inputs whose sharded extents (z; plus y for "grid") do not
    divide over the spatial mesh axes.
    """

    task_name = "fused_segmentation"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "threshold": 0.25,
            "halo": 4,
            "dt_max_distance": None,
            "min_seed_distance": 0.0,
            "stitch_ws_threshold": None,
            "exact_edt": False,
            "max_labels_per_shard": None,
            "impl": "auto",
            # "slab" shards z only; "grid" factors the devices over z AND y
            # (the 2-axis spatial decomposition) — both extents must divide
            "decomposition": "slab",
            # "fused" = one compiled program; "split" = the staged
            # four-program chain (same outputs, compile-cap friendly)
            "execution": "fused",
        }

    def run_impl(self):
        import jax

        from ..parallel.mesh import make_mesh
        from ..parallel.pipeline import make_ws_ccl_step
        from ..parallel.split_pipeline import make_ws_ccl_split

        from ..runtime import handoff

        cfg = self.get_config()
        # fusable input edge: a live in-memory boundary-map handle is
        # consumed without a storage read
        inp = handoff.resolve_dataset(cfg["input_path"], cfg["input_key"])
        shape = inp.shape
        roi_begin = tuple(cfg.get("roi_begin") or (0,) * len(shape))
        roi_end = tuple(cfg.get("roi_end") or shape)
        roi = tuple(slice(b, e) for b, e in zip(roi_begin, roi_end))
        roi_shape = tuple(e - b for b, e in zip(roi_begin, roi_end))
        if len(roi_shape) != 3:
            raise ValueError(f"fused segmentation is 3-D only, got {roi_shape}")

        # one ROI = batch of 1: every device goes to the spatial axes
        n_dev = len(jax.devices())
        decomposition = str(cfg.get("decomposition", "slab"))
        if decomposition == "grid" and n_dev > 1:
            # factor devices over z and y, z getting the larger share
            sy = next(
                d for d in range(int(n_dev**0.5), 0, -1) if n_dev % d == 0
            )
            sz = n_dev // sy
            mesh = make_mesh(
                axis_names=("dp", "spz", "spy"), grid=(1, sz, sy)
            )
            sp_axis = ("spz", "spy")
            divides = (roi_shape[0] % sz == 0) and (roi_shape[1] % sy == 0)
            sp_desc = f"spz={sz} spy={sy}"
        elif decomposition in ("slab", "grid"):
            mesh = make_mesh(axis_names=("dp", "sp"), grid=(1, n_dev))
            sp_axis = "sp"
            divides = roi_shape[0] % n_dev == 0
            sp_desc = f"sp={n_dev}"
        else:
            raise ValueError(
                f"decomposition must be 'slab' or 'grid', got {decomposition!r}"
            )
        if not divides:
            raise ValueError(
                f"ROI extents {roi_shape} do not divide over the spatial "
                f"mesh axes ({sp_desc})"
            )

        halo = int(np.max(cfg.get("halo") or 0))
        dt_max = cfg.get("dt_max_distance")
        if dt_max is None and halo and not cfg.get("exact_edt"):
            # per-shard EDT is halo-capped by default (blockwise reference
            # semantics); with exact_edt, None means truly global radii —
            # the saturation exact_edt exists to remove must stay removable
            dt_max = float(halo)
        execution = str(cfg.get("execution", "fused"))
        if execution not in ("fused", "split"):
            raise ValueError(
                f"execution must be 'fused' or 'split', got {execution!r}"
            )
        build_step = make_ws_ccl_step if execution == "fused" else make_ws_ccl_split
        step = build_step(
            mesh,
            halo=halo,
            threshold=float(cfg["threshold"]),
            sp_axis=sp_axis,
            dt_max_distance=dt_max,
            min_seed_distance=float(cfg.get("min_seed_distance") or 0.0),
            max_labels_per_shard=cfg.get("max_labels_per_shard"),
            impl=str(cfg.get("impl", "auto")),
            exact_edt=bool(cfg.get("exact_edt", False)),
            stitch_ws_threshold=cfg.get("stitch_ws_threshold"),
        )
        self.logger.info(
            f"{execution} step on mesh {sp_desc}, roi {roi_shape}, halo={halo}"
        )
        vol = np.asarray(inp[roi]).astype(np.float32)
        ws, cc, n_fg, overflow = jax.block_until_ready(step(vol[None]))
        if bool(np.asarray(overflow)):
            raise RuntimeError(
                "fused step overflowed a label capacity; raise "
                "max_labels_per_shard or use the blockwise task chain"
            )

        out_f = file_reader(cfg["output_path"])
        block_shape = tuple(cfg["block_shape"])
        written = {}
        for key_cfg, data in (("ws_key", ws), ("cc_key", cc)):
            key = cfg.get(key_cfg)
            if not key:
                continue
            arr = np.asarray(data[0]).astype(np.uint64)
            ds = out_f.require_dataset(
                key, shape=shape, chunks=block_shape, dtype="uint64"
            )
            # the whole ROI is already host-resident: one sliced write
            ds[roi] = arr
            written[key] = int(arr.max())
        return {
            # float32 psum: exact below 2**24 per shard; round-to-nearest
            # (not truncate) so a 1-ulp-low representation can't report
            # off-by-one.  Counts past 2**24 are approximate by design.
            "n_foreground": int(round(float(np.asarray(n_fg)))),
            "mesh": sp_desc,
            "written": written,
        }


class FusedSegmentationLocal(FusedSegmentationBase):
    target = "local"


class FusedSegmentationTPU(FusedSegmentationBase):
    target = "tpu"


class FusedSegmentationWorkflow(WorkflowBase):
    """One-task workflow wrapper so the CLI/registry can launch it."""

    task_name = "fused_segmentation_workflow"

    def requires(self):
        from . import fused as fused_mod

        return [
            get_task_cls(fused_mod, "FusedSegmentation", self.target)(
                tmp_folder=self.tmp_folder,
                config_dir=self.config_dir,
                max_jobs=self.max_jobs,
                dependencies=self.dependencies,
                **self.params,
            )
        ]

    def run_impl(self):
        # surface the inner task's output stats in the workflow's own
        # success manifest — failures_report and operators read the
        # workflow manifest, and a bare {} hid what the fused path wrote
        try:
            doc = self.requires()[0].output().read()
        except OSError:
            return {}
        return {
            k: doc[k]
            for k in ("n_foreground", "written", "mesh")
            if k in doc
        }
