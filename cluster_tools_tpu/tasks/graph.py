"""Region-adjacency-graph extraction as a blockwise task chain.

Re-design of the reference's ``cluster_tools/graph/`` (SURVEY.md §2a
"graph", §3.3): there, ``initial_sub_graphs.py`` ran the ``nifty.distributed``
C++ per-block RAG extractor against N5, ``merge_sub_graphs.py`` merged block
graphs up a scale hierarchy, and ``map_edge_ids.py`` produced
block-edge→global-edge ID maps for features/multicut.  Here the per-block
scan is a jitted device kernel (:mod:`..ops.rag`) and the graph artifacts are
small npz files in ``tmp_folder/graph``:

    InitialSubGraphs  (host IO pool + device scans)  block_<id>.npz {nodes, uv, sizes}
    MergeSubGraphs    (driver)                        graph.npz {nodes, uv, edges, sizes}
    MapEdgeIds        (host IO pool)                  edge_ids_<id>.npy

``nodes``/``uv`` carry the original (uint64) segment labels; ``edges`` is the
same edge list in dense node indices (row into ``nodes``) for solver use.
Label 0 is background/ignore and never becomes a node.
"""

from __future__ import annotations

import os
import numpy as np

from ..ops.rag import block_rag, find_edge_ids, merge_edge_lists
from ..runtime import handoff
from ..runtime.task import BaseTask, WorkflowBase
from ..utils.volume_utils import Blocking, blocks_in_volume


def graph_dir(tmp_folder: str) -> str:
    d = os.path.join(tmp_folder, "graph")
    os.makedirs(d, exist_ok=True)
    return d


def block_graph_path(tmp_folder: str, block_id: int) -> str:
    return os.path.join(graph_dir(tmp_folder), f"block_{block_id}.npz")


def global_graph_path(tmp_folder: str) -> str:
    return os.path.join(graph_dir(tmp_folder), "graph.npz")


def edge_ids_path(tmp_folder: str, block_id: int) -> str:
    return os.path.join(graph_dir(tmp_folder), f"edge_ids_{block_id}.npy")


def load_global_graph(tmp_folder: str):
    """Load the merged graph: (nodes, uv, edges, sizes).  Served from the
    in-memory handoff when the producing task published one (task-graph
    fusion), else from the npz artifact."""
    f = handoff.load_arrays(global_graph_path(tmp_folder))
    return f["nodes"], f["uv"], f["edges"], f["sizes"]


def _upper_halo_bb(block, shape):
    """Inner bb extended by +1 voxel on upper faces (clipped): the RAG halo
    convention of :mod:`..ops.rag` — each voxel-face pair owned by one block."""
    return tuple(
        slice(b, min(e + 1, s)) for b, e, s in zip(block.begin, block.end, shape)
    )


class InitialSubGraphsBase(BaseTask):
    """Per-block RAG extraction (reference: ``initial_sub_graphs.py``).

    Params: ``input_path/input_key`` (the label/supervoxel volume).
    """

    task_name = "initial_sub_graphs"

    def run_impl(self):
        cfg = self.get_config()
        # fusable edge (watershed -> graph): consume the supervoxel volume
        # from the producer's in-memory handoff when one is live
        ds = handoff.resolve_dataset(cfg["input_path"], cfg["input_key"])
        shape = ds.shape
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        # block graphs are published in memory for MergeSubGraphs; stale
        # markers from a previous process are invalidated here
        self.declare_handoff_producer()

        def process(block_id: int):
            block = blocking.get_block(block_id)
            seg = np.asarray(ds[_upper_halo_bb(block, shape)])
            # return_nodes: the inner node set comes out of the extraction's
            # own dense-label pass instead of a second host np.unique scan
            # over the block's voxels (ISSUE 1 fused-path satellite)
            uv, sizes, _, nodes = block_rag(
                seg, inner_shape=block.shape, return_nodes=True
            )
            nodes = nodes.astype(np.uint64)
            self.save_handoff_arrays(
                block_graph_path(self.tmp_folder, block_id),
                nodes=nodes,
                uv=uv,
                sizes=sizes,
            )

        n = self.host_block_map(block_ids, process)
        return {"n_blocks": n}


class InitialSubGraphsLocal(InitialSubGraphsBase):
    target = "local"


class InitialSubGraphsTPU(InitialSubGraphsBase):
    target = "tpu"


class MergeSubGraphsBase(BaseTask):
    """Merge per-block graphs into the global graph (reference:
    ``merge_sub_graphs.py``; the scale hierarchy collapses to one tree-merge
    on the driver since block graphs are tiny host artifacts here)."""

    task_name = "merge_sub_graphs"

    def run_impl(self):
        cfg = self.get_config()
        shape = handoff.resolve_dataset(
            cfg["input_path"], cfg["input_key"]
        ).shape
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        edge_lists, node_lists = [], []
        for b in block_ids:
            f = handoff.load_arrays(block_graph_path(self.tmp_folder, b))
            edge_lists.append((f["uv"], f["sizes"]))
            node_lists.append(f["nodes"])
        uv, sizes = merge_edge_lists(edge_lists)
        nodes = (
            np.unique(np.concatenate(node_lists))
            if node_lists
            else np.zeros(0, np.uint64)
        )
        # dense edge representation for solvers: rows index into `nodes`
        edges = np.searchsorted(nodes, uv).astype(np.int64)
        self.save_handoff_arrays(
            global_graph_path(self.tmp_folder),
            nodes=nodes,
            uv=uv,
            edges=edges,
            sizes=sizes,
        )
        return {"n_nodes": len(nodes), "n_edges": len(uv)}


class MergeSubGraphsLocal(MergeSubGraphsBase):
    target = "local"


class MergeSubGraphsTPU(MergeSubGraphsBase):
    target = "tpu"


class MapEdgeIdsBase(BaseTask):
    """Map each block's edges to global edge ids (reference:
    ``map_edge_ids.py``) — consumed by features merge and multicut
    subproblem extraction."""

    task_name = "map_edge_ids"

    def run_impl(self):
        cfg = self.get_config()
        shape = handoff.resolve_dataset(
            cfg["input_path"], cfg["input_key"]
        ).shape
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        _, uv_global, _, _ = load_global_graph(self.tmp_folder)
        self.declare_handoff_producer()

        def process(block_id: int):
            uv = handoff.load_arrays(
                block_graph_path(self.tmp_folder, block_id)
            )["uv"]
            ids = find_edge_ids(uv_global, uv)
            self.save_handoff_array(
                edge_ids_path(self.tmp_folder, block_id), ids
            )

        n = self.host_block_map(block_ids, process)
        return {"n_blocks": n}


class MapEdgeIdsLocal(MapEdgeIdsBase):
    target = "local"


class MapEdgeIdsTPU(MapEdgeIdsBase):
    target = "tpu"


class GraphWorkflow(WorkflowBase):
    """InitialSubGraphs -> MergeSubGraphs -> MapEdgeIds."""

    task_name = "graph_workflow"

    def requires(self):
        from . import graph as graph_mod
        from ..runtime.task import get_task_cls

        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        p = self.params
        keys = {
            k: p[k]
            for k in ("input_path", "input_key", "block_shape", "roi_begin", "roi_end")
            if k in p
        }
        t1 = get_task_cls(graph_mod, "InitialSubGraphs", self.target)(
            **common, dependencies=self.dependencies, **keys
        )
        t2 = get_task_cls(graph_mod, "MergeSubGraphs", self.target)(
            **common, dependencies=[t1], **keys
        )
        t3 = get_task_cls(graph_mod, "MapEdgeIds", self.target)(
            **common, dependencies=[t2], **keys
        )
        return [t3]
