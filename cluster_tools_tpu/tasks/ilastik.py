"""Pixel classification: filter bank + linear classifier, blockwise.

Re-design of the reference's ``cluster_tools/ilastik/`` (SURVEY.md §2a
"ilastik": blockwise ilastik pixel-classification prediction).  Instead of
shelling out to ilastik headless, the rebuild implements the same
capability natively: an ilastik-style feature bank (multi-scale gaussian
smoothing, gradient magnitude, laplacian of gaussian — all separable
device kernels from :mod:`..ops.filters`) feeding a logistic-regression
classifier, trained from sparse scribble annotations with optax.

The filter bank + matmul classifier is one fused XLA program per block —
exactly the kind of dense pipeline the MXU wants.

Checkpoint format: npz with ``W`` [n_features, n_classes], ``b``
[n_classes], ``sigmas`` (the bank scales, for reproducibility).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..ops.filters import gaussian_smooth, gradient_magnitude
from ..runtime.executor import (
    BlockwiseExecutor,
    is_sub_block,
    region_verifier,
)
from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader, pad_block_to

DEFAULT_SIGMAS = (0.7, 1.6, 3.5)


@partial(jax.jit, static_argnames=("sigmas",))
def feature_bank(
    x: jnp.ndarray, sigmas: Tuple[float, ...] = DEFAULT_SIGMAS
) -> jnp.ndarray:
    """Ilastik-style per-voxel features: for each sigma — gaussian
    smoothing, gaussian gradient magnitude, laplacian of gaussian — plus
    the raw intensity.  Returns (*shape, n_features)."""
    feats = [x]
    for s in sigmas:
        sm = gaussian_smooth(x, sigma=float(s))
        feats.append(sm)
        feats.append(gradient_magnitude(x, sigma=float(s)))
        # laplacian of gaussian via second differences of the smoothed map
        lap = jnp.zeros_like(sm)
        for axis in range(x.ndim):
            lap = lap + (
                jnp.roll(sm, 1, axis) + jnp.roll(sm, -1, axis) - 2 * sm
            )
        feats.append(lap)
    return jnp.stack(feats, axis=-1)


def n_features(sigmas: Sequence[float] = DEFAULT_SIGMAS) -> int:
    return 1 + 3 * len(sigmas)


def fit_linear_classifier(
    X: np.ndarray, y: np.ndarray, n_steps: int = 300, lr: float = 0.5,
    seed: int = 0,
):
    """Logistic regression on featurized examples; returns (W, b) numpy."""
    import optax

    n_classes = int(y.max()) + 1
    # standardize features for conditioning; fold into W/b afterwards
    mu, sd = X.mean(0), X.std(0) + 1e-6
    Xn = (X - mu) / sd

    key = jax.random.PRNGKey(seed)
    W = 0.01 * jax.random.normal(key, (X.shape[1], n_classes))
    b = jnp.zeros((n_classes,))
    opt = optax.adam(lr)
    state = opt.init((W, b))
    Xj, yj = jnp.asarray(Xn), jnp.asarray(y)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            W, b = p
            logits = Xj @ W + b
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yj
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, state = opt.update(grads, state)
        return optax.apply_updates(params, updates), state, loss

    params = (W, b)
    for _ in range(n_steps):
        params, state, loss = step(params, state)
    W, b = params
    # un-standardize: logits = ((x - mu)/sd) W + b = x (W/sd) + (b - mu/sd W)
    W_raw = np.asarray(W) / sd[:, None]
    b_raw = np.asarray(b) - (mu / sd) @ np.asarray(W)
    return W_raw.astype(np.float32), b_raw.astype(np.float32)


def train_pixel_classifier(
    raw: np.ndarray,
    labels: np.ndarray,
    sigmas: Sequence[float] = DEFAULT_SIGMAS,
    n_steps: int = 300,
    lr: float = 0.5,
    seed: int = 0,
):
    """Train logistic regression on sparse annotations (labels: 0 =
    unlabeled, 1..K = classes).  Returns (W, b) as numpy arrays."""
    feats = np.asarray(feature_bank(jnp.asarray(raw, jnp.float32), tuple(sigmas)))
    mask = labels > 0
    X = feats[mask].astype(np.float32)
    y = labels[mask].astype(np.int32) - 1
    return fit_linear_classifier(X, y, n_steps=n_steps, lr=lr, seed=seed)


# ---------------------------------------------------------------------------
# vigra RandomForest ingestion: the serialized classifier inside an .ilp
# (reference capability: predict from an existing trained project without
# retraining; SURVEY.md §2a "ilastik").  The blob is plain HDF5 in vigra's
# RF serialization: per tree an int32 ``topology_`` (header
# ``[column_count, class_count]``, root at offset 2; interior threshold
# nodes are ``[type, param_addr, left_addr, right_addr, column]`` with
# ``parameters_[param_addr + 1]`` the split threshold; leaves carry the
# 0x40000000 tag and ``parameters_[param_addr + 1 : + 1 + K]`` the class
# histogram) and a float64 ``parameters_``.  Prediction is the standard RF
# ensemble: per-leaf histogram normalized to a distribution, averaged over
# all trees of all ``Forest*`` groups (ilastik trains several small
# forests in parallel lanes and concatenates them).
#
# The evaluator is TPU-shaped: trees are densified into fixed-size node
# tables and every voxel walks root->leaf in a fixed-depth gather loop
# (``lax.fori_loop``) — no data-dependent control flow, so the whole
# featurize+forest block stays one fused XLA program.
# ---------------------------------------------------------------------------

_VIGRA_LEAF_TAG = 0x40000000
_VIGRA_THRESHOLD_NODE = 0  # the only interior node type ilastik produces


def parse_vigra_forest(group) -> dict:
    """Parse one vigra RandomForest HDF5 group into dense node tables.

    Returns numpy arrays (n_trees padded to the widest tree):
    ``feature`` [T, N] int32, ``threshold`` [T, N] float32, ``children``
    [T, N, 2] int32 (self-loop on leaves), ``leaf_probs`` [T, N, K]
    float32 (normalized; zero rows on interior/padding nodes), ``is_leaf``
    [T, N] bool, plus ``class_count``/``column_count``/``depth``.
    Raises ``ValueError`` on layouts that are not a vigra RandomForest
    serialization and on node types other than threshold splits /
    const-prob leaves.
    """
    try:
        ext = group["_ext_param"]
    except KeyError:
        raise ValueError(
            f"{group.name}: no _ext_param subgroup — present but not a "
            "vigra RandomForest serialization (a different classifier "
            "backend?)"
        ) from None
    class_count = int(np.asarray(ext["class_count_"]).ravel()[0])
    column_count = int(np.asarray(ext["column_count_"]).ravel()[0])
    tree_keys = sorted(
        (k for k in group.keys() if k.startswith("Tree_")),
        key=lambda k: int(k.split("_")[-1]),
    )
    if not tree_keys:
        raise ValueError("vigra forest group has no Tree_* entries")
    trees = []
    for tk in tree_keys:
        try:
            topo = np.asarray(group[tk]["topology_"]).ravel().astype(np.int64)
            par = np.asarray(group[tk]["parameters_"]).ravel().astype(np.float64)
        except KeyError:
            raise ValueError(
                f"{group.name}/{tk}: missing topology_/parameters_ — not a "
                "vigra RandomForest tree serialization"
            ) from None
        if topo[0] != column_count or topo[1] != class_count:
            raise ValueError(
                f"{tk}: topology header {topo[:2].tolist()} does not match "
                f"_ext_param (columns={column_count}, classes={class_count})"
            )
        # walk addresses -> dense node ids
        addr2id: dict = {}
        order = []
        stack = [2]
        while stack:
            a = int(stack.pop())
            if a in addr2id:
                continue
            addr2id[a] = len(order)
            order.append(a)
            t = int(topo[a])
            if not (t & _VIGRA_LEAF_TAG):
                if t != _VIGRA_THRESHOLD_NODE:
                    raise ValueError(
                        f"{tk}: unsupported vigra node type {t} at {a} "
                        "(only threshold splits + const-prob leaves)"
                    )
                stack.append(int(topo[a + 3]))
                stack.append(int(topo[a + 2]))
        n = len(order)
        feat = np.zeros(n, np.int32)
        thr = np.zeros(n, np.float32)
        child = np.zeros((n, 2), np.int32)
        leafp = np.zeros((n, class_count), np.float32)
        leaf = np.zeros(n, bool)
        for a in order:
            i = addr2id[a]
            t = int(topo[a])
            pa = int(topo[a + 1])
            if t & _VIGRA_LEAF_TAG:
                leaf[i] = True
                child[i] = (i, i)  # self-loop: extra gather steps are no-ops
                h = par[pa + 1 : pa + 1 + class_count]
                s = h.sum()
                leafp[i] = (h / s if s > 0 else np.full(class_count, 1.0 / class_count))
            else:
                feat[i] = int(topo[a + 4])
                thr[i] = par[pa + 1]
                child[i] = (addr2id[int(topo[a + 2])], addr2id[int(topo[a + 3])])
        trees.append((feat, thr, child, leafp, leaf))
    width = max(len(t[0]) for t in trees)

    def pad(arr, fill=0):
        out = np.full((len(trees), width) + arr[0].shape[1:], fill, arr[0].dtype)
        for i, a in enumerate(arr):
            out[i, : len(a)] = a
        return out

    feature = pad([t[0] for t in trees])
    threshold = pad([t[1] for t in trees])
    children = pad([t[2] for t in trees])
    leaf_probs = pad([t[3] for t in trees])
    is_leaf = pad([t[4] for t in trees], fill=True)
    # depth bound for the fixed-length walk: longest root->leaf path
    depth = 0
    for feat, thr, child, leafp, leaf in trees:
        d = np.zeros(len(feat), np.int32)
        for i in range(len(feat)):  # ids are in DFS order: parents first
            if not leaf[i]:
                d[child[i, 0]] = d[child[i, 1]] = d[i] + 1
        depth = max(depth, int(d.max()) if len(d) else 0)
    return {
        "feature": feature,
        "threshold": threshold,
        "children": children,
        "leaf_probs": leaf_probs,
        "is_leaf": is_leaf,
        "class_count": class_count,
        "column_count": column_count,
        "depth": depth,
    }


def load_ilp_forest(path: str) -> dict:
    """Load + concatenate every ``ClassifierForests/Forest*`` in an .ilp.

    Returns the dense node tables of :func:`parse_vigra_forest` with all
    lanes' trees stacked (ilastik's parallel-lane ensemble).  Raises
    ``KeyError`` when the project carries no serialized classifier.
    """
    import h5py

    with h5py.File(path, "r") as f:
        grp = f["PixelClassification/ClassifierForests"]
        forests = [
            parse_vigra_forest(grp[k])
            for k in sorted(grp.keys())
            if k.startswith("Forest")
        ]
    if not forests:
        raise KeyError(f"{path}: ClassifierForests holds no Forest* groups")
    k0 = forests[0]
    for fo in forests[1:]:
        if (
            fo["class_count"] != k0["class_count"]
            or fo["column_count"] != k0["column_count"]
        ):
            raise ValueError("inconsistent class/column counts across lanes")
    width = max(f_["feature"].shape[1] for f_ in forests)

    def cat(key, fill=0):
        parts = []
        for fo in forests:
            a = fo[key]
            if a.shape[1] < width:
                pad_shape = (a.shape[0], width - a.shape[1]) + a.shape[2:]
                a = np.concatenate(
                    [a, np.full(pad_shape, fill, a.dtype)], axis=1
                )
            parts.append(a)
        return np.concatenate(parts, axis=0)

    return {
        "feature": cat("feature"),
        "threshold": cat("threshold"),
        "children": cat("children"),
        "leaf_probs": cat("leaf_probs"),
        "is_leaf": cat("is_leaf", fill=True),
        "class_count": k0["class_count"],
        "column_count": k0["column_count"],
        "depth": max(f_["depth"] for f_ in forests),
    }


@partial(jax.jit, static_argnames=("depth",))
def forest_predict_proba(
    feature: jnp.ndarray,
    threshold: jnp.ndarray,
    children: jnp.ndarray,
    leaf_probs: jnp.ndarray,
    X: jnp.ndarray,
    depth: int,
) -> jnp.ndarray:
    """Ensemble class probabilities, [n, K], for features ``X`` [n, F].

    Fixed-depth descent: every sample takes exactly ``depth`` gather steps
    per tree (leaves self-loop), vmapped over trees — static shapes, no
    per-sample control flow, so XLA fuses it with the filter bank.
    """

    def one_tree(feat_t, thr_t, child_t, probs_t):
        def body(_, idx):
            go_right = X[jnp.arange(X.shape[0]), feat_t[idx]] >= thr_t[idx]
            return child_t[idx, go_right.astype(jnp.int32)]

        idx = jax.lax.fori_loop(
            0, depth, body, jnp.zeros(X.shape[0], jnp.int32)
        )
        return probs_t[idx]

    per_tree = jax.vmap(one_tree)(feature, threshold, children, leaf_probs)
    return per_tree.mean(axis=0)


# ---------------------------------------------------------------------------
# ilastik .ilp project ingestion (reference capability: execute an existing
# ilastik pixel-classification project; SURVEY.md §2a "ilastik")
# ---------------------------------------------------------------------------

# ilastik feature-id strings -> (scale-parameterized) device filters;
# eigenvalue features contribute 3 channels each (the ilastik convention)
ILP_SUPPORTED_FEATURES = (
    "GaussianSmoothing",
    "LaplacianOfGaussian",
    "GaussianGradientMagnitude",
    "DifferenceOfGaussians",
    "HessianOfGaussianEigenvalues",
    "StructureTensorEigenvalues",
)


def _ilp_single_feature(x: jnp.ndarray, fid: str, sigma: float) -> jnp.ndarray:
    """One selection's channels: (*shape, c) with c = 1 or 3."""
    from ..ops.filters import hessian_eigenvalues, structure_tensor_eigenvalues

    if fid == "GaussianSmoothing":
        return gaussian_smooth(x, sigma=sigma)[..., None]
    if fid == "GaussianGradientMagnitude":
        return gradient_magnitude(x, sigma=sigma)[..., None]
    if fid == "LaplacianOfGaussian":
        sm = gaussian_smooth(x, sigma=sigma)
        lap = jnp.zeros_like(sm)
        for axis in range(x.ndim):
            lap = lap + (jnp.roll(sm, 1, axis) + jnp.roll(sm, -1, axis) - 2 * sm)
        return lap[..., None]
    if fid == "DifferenceOfGaussians":
        # ilastik's DoG pairs sigma with 0.66*sigma
        return (
            gaussian_smooth(x, sigma=sigma)
            - gaussian_smooth(x, sigma=0.66 * sigma)
        )[..., None]
    if fid == "HessianOfGaussianEigenvalues":
        return hessian_eigenvalues(x, sigma=sigma)
    if fid == "StructureTensorEigenvalues":
        return structure_tensor_eigenvalues(x, sigma=sigma)
    raise ValueError(f"unsupported ilastik feature id {fid!r}")


def ilp_feature_channels(selections) -> int:
    """Total feature-bank column count for (feature_id, sigma) selections —
    the single owner of the per-feature channel rule (eigenvalue features
    contribute 3 channels, everything else 1; must match
    :func:`_ilp_single_feature`)."""
    return sum(3 if fid.endswith("Eigenvalues") else 1 for fid, _ in selections)


@partial(jax.jit, static_argnames=("selections",))
def ilp_feature_bank(
    x: jnp.ndarray, selections: Tuple[Tuple[str, float], ...]
) -> jnp.ndarray:
    """Featurize with an .ilp project's (feature_id, sigma) selections.

    Channel count is ``sum(3 if eigenvalue feature else 1)``, in selection
    order — matching ilastik's feature-matrix layout.
    """
    feats = [_ilp_single_feature(x, fid, float(s)) for fid, s in selections]
    return jnp.concatenate(feats, axis=-1)


def _parse_block_slice(s: str) -> Tuple[slice, ...]:
    """ilastik blockSlice attr: '[1:4,0:10,5:9]' (may carry a channel dim)."""
    s = s.strip().strip("[]")
    out = []
    for part in s.split(","):
        lo, hi = part.split(":")
        out.append(slice(int(lo), int(hi)))
    return tuple(out)


def _load_ilp_selections(f) -> Tuple[Tuple[str, float], ...]:
    """(feature_id, sigma) pairs from an open .ilp's ``FeatureSelections``
    (ids x scales masked by ``SelectionMatrix``), in ilastik's feature-major
    order — the column order both the forest and the retrained classifier
    rely on.  Raises on unsupported feature ids."""
    fs = f["FeatureSelections"]
    ids = [
        i.decode() if isinstance(i, bytes) else str(i)
        for i in fs["FeatureIds"][:]
    ]
    scales = [float(s) for s in fs["Scales"][:]]
    matrix = np.asarray(fs["SelectionMatrix"][:], bool)
    selections = []
    for fi, fid in enumerate(ids):
        for si, sig in enumerate(scales):
            if matrix[fi, si]:
                if fid not in ILP_SUPPORTED_FEATURES:
                    raise ValueError(
                        f"ilastik feature {fid!r} is not supported "
                        f"(supported: {ILP_SUPPORTED_FEATURES})"
                    )
                selections.append((fid, sig))
    return tuple(selections)


def load_ilp_project(path: str):
    """Parse an ilastik pixel-classification project (.ilp h5 file).

    Returns ``(selections, label_blocks)``:

    - ``selections``: tuple of (feature_id, sigma) pairs from
      ``FeatureSelections`` (ids x scales masked by ``SelectionMatrix``),
    - ``label_blocks``: list of (slices, uint8 labels) sparse annotation
      blocks from ``PixelClassification/LabelSets`` (0 = unlabeled).

    This is the *retraining* path (project annotations -> native
    classifier); :func:`import_ilp` prefers the serialized vigra forest
    (:func:`load_ilp_forest`), which predicts without labels or raw data.
    A project without either a forest or label sets cannot be ingested.
    """
    import h5py

    with h5py.File(path, "r") as f:
        selections = _load_ilp_selections(f)
        label_blocks = []
        ls = f.get("PixelClassification/LabelSets")
        if ls is not None:
            for lane in ls.values():
                for blk in lane.values():
                    bs = blk.attrs.get("blockSlice")
                    if bs is None:
                        continue
                    if isinstance(bs, bytes):
                        bs = bs.decode()
                    data = np.asarray(blk[:], np.uint8)
                    sl = _parse_block_slice(bs)
                    # ilastik appends a channel axis to label blocks
                    if data.ndim == len(sl) and data.shape[-1] == 1:
                        data = data[..., 0]
                        sl = sl[:-1]
                    label_blocks.append((sl, data))
    if not label_blocks:
        raise ValueError(
            f"{path}: no label annotations to re-train from — if the "
            "project carries a trained classifier, ingest it directly with "
            "import_ilp/load_ilp_forest instead of this retraining path"
        )
    return tuple(selections), label_blocks


def train_from_ilp(
    ilp_path: str,
    raw: np.ndarray,
    checkpoint_path: str,
    n_steps: int = 300,
    lr: float = 0.5,
    seed: int = 0,
) -> int:
    """Fit the native classifier from an .ilp project's features + labels.

    ``raw`` is the annotated raw volume (ilastik projects reference it by
    external path; the caller resolves it).  Writes the standard npz
    checkpoint consumed by :class:`IlastikPredictionBase` (with the .ilp
    ``selections`` recorded) and returns the number of classes.
    """
    selections, label_blocks = load_ilp_project(ilp_path)
    labels = np.zeros(raw.shape, np.uint8)
    for sl, data in label_blocks:
        labels[sl] = data
    feats = np.asarray(
        ilp_feature_bank(jnp.asarray(raw, jnp.float32), selections)
    )
    mask = labels > 0
    X = feats[mask].astype(np.float32)
    y = labels[mask].astype(np.int32) - 1
    W, b = fit_linear_classifier(X, y, n_steps=n_steps, lr=lr, seed=seed)
    np.savez(
        checkpoint_path,
        W=W,
        b=b,
        sigmas=np.zeros(0, np.float32),  # unused on the ilp path
        ilp_features=np.array([f"{fid}:{s}" for fid, s in selections]),
    )
    return W.shape[1]


def import_ilp(
    ilp_path: str,
    checkpoint_path: str,
    raw: "np.ndarray | None" = None,
    n_steps: int = 300,
    lr: float = 0.5,
    seed: int = 0,
) -> int:
    """Ingest an .ilp for prediction; returns the class count.

    Prefers the project's own trained vigra forest (exact reproduction of
    its predictions, no raw volume needed); falls back to re-fitting the
    native classifier from the project's annotations when no serialized
    classifier exists (then ``raw`` is required).  Either way the written
    npz checkpoint drives :class:`IlastikPredictionBase` unchanged.
    """
    import h5py

    # retrain only when the project genuinely carries NO serialized
    # classifier; a PRESENT but unparseable forest (non-vigra backend,
    # unknown node type, header mismatch, inconsistent lanes) raises
    # through as ValueError — silently retraining over it would hide the
    # diagnostic and change predictions
    with h5py.File(ilp_path, "r") as f:
        grp = f.get("PixelClassification/ClassifierForests")
        has_classifier = grp is not None and any(
            k.startswith("Forest") for k in grp.keys()
        )
    forest = load_ilp_forest(ilp_path) if has_classifier else None
    if forest is not None:
        with h5py.File(ilp_path, "r") as f:
            selections = _load_ilp_selections(f)
        n_feat = ilp_feature_channels(selections)
        if n_feat != forest["column_count"]:
            raise ValueError(
                f"forest expects {forest['column_count']} feature columns "
                f"but the project's selections produce {n_feat} — the .ilp "
                "was saved mid-edit; re-train or re-save it"
            )
        np.savez(
            checkpoint_path,
            W=np.zeros((0, 0), np.float32),
            b=np.zeros(0, np.float32),
            sigmas=np.zeros(0, np.float32),
            ilp_features=np.array([f"{fid}:{s}" for fid, s in selections]),
            rf_feature=forest["feature"],
            rf_threshold=forest["threshold"],
            rf_children=forest["children"],
            rf_leaf_probs=forest["leaf_probs"],
            rf_depth=np.int32(forest["depth"]),
        )
        return int(forest["class_count"])
    if raw is None:
        raise ValueError(
            f"{ilp_path}: no serialized classifier and no raw volume given "
            "— pass raw= to re-fit from the project's annotations"
        )
    return train_from_ilp(
        ilp_path, raw, checkpoint_path, n_steps=n_steps, lr=lr, seed=seed
    )


class IlastikPredictionBase(BaseTask):
    """Blockwise pixel-classification prediction (reference:
    ``IlastikPredictionBase``).

    Params: ``input_path/input_key`` (raw), ``output_path/output_key``
    (class probabilities, ``(K,) + volume`` float32), ``checkpoint_path``
    (npz with W/b/sigmas), ``halo`` (filter support; default covers the
    largest sigma).
    """

    task_name = "ilastik_prediction"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "halo": [12, 12, 12],
        }

    def run_impl(self):
        from ..runtime import handoff

        cfg = self.get_config()
        inp = handoff.resolve_dataset(cfg["input_path"], cfg["input_key"])
        shape = inp.shape
        block_shape = tuple(cfg["block_shape"])
        halo = tuple(cfg.get("halo") or [0] * len(shape))
        forest = None
        with np.load(cfg["checkpoint_path"]) as f:
            W, b = jnp.asarray(f["W"]), jnp.asarray(f["b"])
            sigmas = tuple(float(s) for s in f["sigmas"])
            selections = None
            if "ilp_features" in f and len(f["ilp_features"]):
                selections = tuple(
                    (s.rsplit(":", 1)[0], float(s.rsplit(":", 1)[1]))
                    for s in f["ilp_features"].tolist()
                )
            if "rf_feature" in f:
                forest = {
                    "feature": jnp.asarray(f["rf_feature"]),
                    "threshold": jnp.asarray(f["rf_threshold"]),
                    "children": jnp.asarray(f["rf_children"]),
                    "leaf_probs": jnp.asarray(f["rf_leaf_probs"]),
                    "depth": int(f["rf_depth"]),
                }
        n_classes = (
            forest["leaf_probs"].shape[-1] if forest is not None else W.shape[1]
        )

        # MemoryTarget output: the probability map stays in RAM for a
        # downstream thresholding/CC consumer, spill under the ladder
        out = self.handoff_dataset(
            cfg["output_path"], cfg["output_key"],
            shape=(n_classes,) + shape,
            chunks=(1,) + block_shape,
            dtype="float32",
        )
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        done = set(self.blocks_done())
        blocks_all = [blocking.get_block(b, halo) for b in block_ids]
        todo = [b for b in blocks_all if b.block_id not in done]
        outer = tuple(b + 2 * h for b, h in zip(block_shape, halo))

        def load(block):
            data = np.asarray(inp[block.outer_bb]).astype(np.float32)
            if is_sub_block(block):
                # degrade-split fragment: keep its own (smaller) shape —
                # sub-blocks never enter a stacked batch, and the smaller
                # allocation is the point of the split
                return (data,)
            return (pad_block_to(data, outer, mode="edge"),)

        def kernel(x):
            if selections is not None:
                feats = ilp_feature_bank(x, selections)
            else:
                feats = feature_bank(x, sigmas)
            if forest is not None:
                flat = feats.reshape(-1, feats.shape[-1])
                probs = forest_predict_proba(
                    forest["feature"], forest["threshold"],
                    forest["children"], forest["leaf_probs"],
                    flat, forest["depth"],
                ).reshape(feats.shape[:-1] + (n_classes,))
            else:
                probs = jax.nn.softmax(feats @ W + b, axis=-1)
            return jnp.moveaxis(probs, -1, 0)

        def store(block, raw):
            rel = block.inner_in_outer_bb
            out[(slice(None),) + block.bb] = np.asarray(raw)[(slice(None),) + rel]

        executor = BlockwiseExecutor(
            target=self.target,
            device_batch=int(cfg.get("device_batch", 1)),
            io_threads=int(cfg.get("io_threads") or max(1, self.max_jobs)),
            max_retries=int(cfg.get("io_retries", 2)),
            backoff_base=float(cfg.get("io_backoff_s", 0.05)),
        )
        # float probability outputs: the built-in NaN/inf check quarantines
        # blocks corrupted by a bad forest / feature overflow
        executor.map_blocks(
            kernel,
            blocks_all,
            load,
            store,
            on_block_done=lambda b: self.log_block_success(b.block_id),
            done_block_ids=done,
            failures_path=self.failures_path,
            task_name=self.uid,
            block_deadline_s=cfg.get("block_deadline_s"),
            watchdog_period_s=cfg.get("watchdog_period_s"),
            store_verify_fn=region_verifier(
                out, bb_of=lambda b: (slice(None),) + b.bb
            ),
            schedule=str(cfg.get("block_schedule") or "morton"),
            sweep_mode=str(cfg.get("sweep_mode") or "auto"),
            sharded_batch=cfg.get("sharded_batch"),
            device_pool=str(cfg.get("device_pool") or "auto"),
            device_pool_bytes=cfg.get("device_pool_bytes"),
            # opt-in OOM split (config allow_block_split): filter-bank +
            # per-voxel classifier is shape-local, so sub-block outputs tile
            # the parent exactly when halo covers the largest filter support
            splittable=bool(cfg.get("allow_block_split", False)),
            split_halo=halo,
            min_block_shape=cfg.get("min_block_shape"),
            degrade_wait_s=float(cfg.get("degrade_wait_s", 5.0)),
            inflight_byte_budget=cfg.get("inflight_byte_budget"),
        )
        return {"n_blocks": len(todo), "n_classes": int(n_classes)}


class IlastikPredictionLocal(IlastikPredictionBase):
    target = "local"


class IlastikPredictionTPU(IlastikPredictionBase):
    target = "tpu"


class IlastikPredictionWorkflow(WorkflowBase):
    task_name = "ilastik_prediction_workflow"

    def requires(self):
        from . import ilastik as il_mod

        return [
            get_task_cls(il_mod, "IlastikPrediction", self.target)(
                tmp_folder=self.tmp_folder,
                config_dir=self.config_dir,
                max_jobs=self.max_jobs,
                dependencies=self.dependencies,
                **self.params,
            )
        ]
