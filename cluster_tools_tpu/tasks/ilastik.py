"""Pixel classification: filter bank + linear classifier, blockwise.

Re-design of the reference's ``cluster_tools/ilastik/`` (SURVEY.md §2a
"ilastik": blockwise ilastik pixel-classification prediction).  Instead of
shelling out to ilastik headless, the rebuild implements the same
capability natively: an ilastik-style feature bank (multi-scale gaussian
smoothing, gradient magnitude, laplacian of gaussian — all separable
device kernels from :mod:`..ops.filters`) feeding a logistic-regression
classifier, trained from sparse scribble annotations with optax.

The filter bank + matmul classifier is one fused XLA program per block —
exactly the kind of dense pipeline the MXU wants.

Checkpoint format: npz with ``W`` [n_features, n_classes], ``b``
[n_classes], ``sigmas`` (the bank scales, for reproducibility).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..ops.filters import gaussian_smooth, gradient_magnitude
from ..runtime.executor import BlockwiseExecutor
from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader, pad_block_to

DEFAULT_SIGMAS = (0.7, 1.6, 3.5)


@partial(jax.jit, static_argnames=("sigmas",))
def feature_bank(
    x: jnp.ndarray, sigmas: Tuple[float, ...] = DEFAULT_SIGMAS
) -> jnp.ndarray:
    """Ilastik-style per-voxel features: for each sigma — gaussian
    smoothing, gaussian gradient magnitude, laplacian of gaussian — plus
    the raw intensity.  Returns (*shape, n_features)."""
    feats = [x]
    for s in sigmas:
        sm = gaussian_smooth(x, sigma=float(s))
        feats.append(sm)
        feats.append(gradient_magnitude(x, sigma=float(s)))
        # laplacian of gaussian via second differences of the smoothed map
        lap = jnp.zeros_like(sm)
        for axis in range(x.ndim):
            lap = lap + (
                jnp.roll(sm, 1, axis) + jnp.roll(sm, -1, axis) - 2 * sm
            )
        feats.append(lap)
    return jnp.stack(feats, axis=-1)


def n_features(sigmas: Sequence[float] = DEFAULT_SIGMAS) -> int:
    return 1 + 3 * len(sigmas)


def fit_linear_classifier(
    X: np.ndarray, y: np.ndarray, n_steps: int = 300, lr: float = 0.5,
    seed: int = 0,
):
    """Logistic regression on featurized examples; returns (W, b) numpy."""
    import optax

    n_classes = int(y.max()) + 1
    # standardize features for conditioning; fold into W/b afterwards
    mu, sd = X.mean(0), X.std(0) + 1e-6
    Xn = (X - mu) / sd

    key = jax.random.PRNGKey(seed)
    W = 0.01 * jax.random.normal(key, (X.shape[1], n_classes))
    b = jnp.zeros((n_classes,))
    opt = optax.adam(lr)
    state = opt.init((W, b))
    Xj, yj = jnp.asarray(Xn), jnp.asarray(y)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            W, b = p
            logits = Xj @ W + b
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yj
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, state = opt.update(grads, state)
        return optax.apply_updates(params, updates), state, loss

    params = (W, b)
    for _ in range(n_steps):
        params, state, loss = step(params, state)
    W, b = params
    # un-standardize: logits = ((x - mu)/sd) W + b = x (W/sd) + (b - mu/sd W)
    W_raw = np.asarray(W) / sd[:, None]
    b_raw = np.asarray(b) - (mu / sd) @ np.asarray(W)
    return W_raw.astype(np.float32), b_raw.astype(np.float32)


def train_pixel_classifier(
    raw: np.ndarray,
    labels: np.ndarray,
    sigmas: Sequence[float] = DEFAULT_SIGMAS,
    n_steps: int = 300,
    lr: float = 0.5,
    seed: int = 0,
):
    """Train logistic regression on sparse annotations (labels: 0 =
    unlabeled, 1..K = classes).  Returns (W, b) as numpy arrays."""
    feats = np.asarray(feature_bank(jnp.asarray(raw, jnp.float32), tuple(sigmas)))
    mask = labels > 0
    X = feats[mask].astype(np.float32)
    y = labels[mask].astype(np.int32) - 1
    return fit_linear_classifier(X, y, n_steps=n_steps, lr=lr, seed=seed)


# ---------------------------------------------------------------------------
# ilastik .ilp project ingestion (reference capability: execute an existing
# ilastik pixel-classification project; SURVEY.md §2a "ilastik")
# ---------------------------------------------------------------------------

# ilastik feature-id strings -> (scale-parameterized) device filters;
# eigenvalue features contribute 3 channels each (the ilastik convention)
ILP_SUPPORTED_FEATURES = (
    "GaussianSmoothing",
    "LaplacianOfGaussian",
    "GaussianGradientMagnitude",
    "DifferenceOfGaussians",
    "HessianOfGaussianEigenvalues",
    "StructureTensorEigenvalues",
)


def _ilp_single_feature(x: jnp.ndarray, fid: str, sigma: float) -> jnp.ndarray:
    """One selection's channels: (*shape, c) with c = 1 or 3."""
    from ..ops.filters import hessian_eigenvalues, structure_tensor_eigenvalues

    if fid == "GaussianSmoothing":
        return gaussian_smooth(x, sigma=sigma)[..., None]
    if fid == "GaussianGradientMagnitude":
        return gradient_magnitude(x, sigma=sigma)[..., None]
    if fid == "LaplacianOfGaussian":
        sm = gaussian_smooth(x, sigma=sigma)
        lap = jnp.zeros_like(sm)
        for axis in range(x.ndim):
            lap = lap + (jnp.roll(sm, 1, axis) + jnp.roll(sm, -1, axis) - 2 * sm)
        return lap[..., None]
    if fid == "DifferenceOfGaussians":
        # ilastik's DoG pairs sigma with 0.66*sigma
        return (
            gaussian_smooth(x, sigma=sigma)
            - gaussian_smooth(x, sigma=0.66 * sigma)
        )[..., None]
    if fid == "HessianOfGaussianEigenvalues":
        return hessian_eigenvalues(x, sigma=sigma)
    if fid == "StructureTensorEigenvalues":
        return structure_tensor_eigenvalues(x, sigma=sigma)
    raise ValueError(f"unsupported ilastik feature id {fid!r}")


@partial(jax.jit, static_argnames=("selections",))
def ilp_feature_bank(
    x: jnp.ndarray, selections: Tuple[Tuple[str, float], ...]
) -> jnp.ndarray:
    """Featurize with an .ilp project's (feature_id, sigma) selections.

    Channel count is ``sum(3 if eigenvalue feature else 1)``, in selection
    order — matching ilastik's feature-matrix layout.
    """
    feats = [_ilp_single_feature(x, fid, float(s)) for fid, s in selections]
    return jnp.concatenate(feats, axis=-1)


def _parse_block_slice(s: str) -> Tuple[slice, ...]:
    """ilastik blockSlice attr: '[1:4,0:10,5:9]' (may carry a channel dim)."""
    s = s.strip().strip("[]")
    out = []
    for part in s.split(","):
        lo, hi = part.split(":")
        out.append(slice(int(lo), int(hi)))
    return tuple(out)


def load_ilp_project(path: str):
    """Parse an ilastik pixel-classification project (.ilp h5 file).

    Returns ``(selections, label_blocks)``:

    - ``selections``: tuple of (feature_id, sigma) pairs from
      ``FeatureSelections`` (ids x scales masked by ``SelectionMatrix``),
    - ``label_blocks``: list of (slices, uint8 labels) sparse annotation
      blocks from ``PixelClassification/LabelSets`` (0 = unlabeled).

    The classifier itself is re-fit from the project's own annotations: the
    serialized forest blob is a vigra RandomForest binary whose undocumented
    topology layout we refuse to guess at; the annotations plus feature
    selections reproduce the project's behavior with the native classifier.
    A project without label sets therefore cannot be ingested.
    """
    import h5py

    with h5py.File(path, "r") as f:
        fs = f["FeatureSelections"]
        ids = [
            i.decode() if isinstance(i, bytes) else str(i)
            for i in fs["FeatureIds"][:]
        ]
        scales = [float(s) for s in fs["Scales"][:]]
        matrix = np.asarray(fs["SelectionMatrix"][:], bool)
        selections = []
        for fi, fid in enumerate(ids):
            for si, sig in enumerate(scales):
                if matrix[fi, si]:
                    if fid not in ILP_SUPPORTED_FEATURES:
                        raise ValueError(
                            f"ilastik feature {fid!r} is not supported "
                            f"(supported: {ILP_SUPPORTED_FEATURES})"
                        )
                    selections.append((fid, sig))
        label_blocks = []
        ls = f.get("PixelClassification/LabelSets")
        if ls is not None:
            for lane in ls.values():
                for blk in lane.values():
                    bs = blk.attrs.get("blockSlice")
                    if bs is None:
                        continue
                    if isinstance(bs, bytes):
                        bs = bs.decode()
                    data = np.asarray(blk[:], np.uint8)
                    sl = _parse_block_slice(bs)
                    # ilastik appends a channel axis to label blocks
                    if data.ndim == len(sl) and data.shape[-1] == 1:
                        data = data[..., 0]
                        sl = sl[:-1]
                    label_blocks.append((sl, data))
    if not label_blocks:
        raise ValueError(
            f"{path}: no label annotations found — the serialized vigra "
            "forest alone cannot be executed; re-save the project with its "
            "training labels included"
        )
    return tuple(selections), label_blocks


def train_from_ilp(
    ilp_path: str,
    raw: np.ndarray,
    checkpoint_path: str,
    n_steps: int = 300,
    lr: float = 0.5,
    seed: int = 0,
) -> int:
    """Fit the native classifier from an .ilp project's features + labels.

    ``raw`` is the annotated raw volume (ilastik projects reference it by
    external path; the caller resolves it).  Writes the standard npz
    checkpoint consumed by :class:`IlastikPredictionBase` (with the .ilp
    ``selections`` recorded) and returns the number of classes.
    """
    selections, label_blocks = load_ilp_project(ilp_path)
    labels = np.zeros(raw.shape, np.uint8)
    for sl, data in label_blocks:
        labels[sl] = data
    feats = np.asarray(
        ilp_feature_bank(jnp.asarray(raw, jnp.float32), selections)
    )
    mask = labels > 0
    X = feats[mask].astype(np.float32)
    y = labels[mask].astype(np.int32) - 1
    W, b = fit_linear_classifier(X, y, n_steps=n_steps, lr=lr, seed=seed)
    np.savez(
        checkpoint_path,
        W=W,
        b=b,
        sigmas=np.zeros(0, np.float32),  # unused on the ilp path
        ilp_features=np.array([f"{fid}:{s}" for fid, s in selections]),
    )
    return W.shape[1]


class IlastikPredictionBase(BaseTask):
    """Blockwise pixel-classification prediction (reference:
    ``IlastikPredictionBase``).

    Params: ``input_path/input_key`` (raw), ``output_path/output_key``
    (class probabilities, ``(K,) + volume`` float32), ``checkpoint_path``
    (npz with W/b/sigmas), ``halo`` (filter support; default covers the
    largest sigma).
    """

    task_name = "ilastik_prediction"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "halo": [12, 12, 12],
        }

    def run_impl(self):
        cfg = self.get_config()
        inp = file_reader(cfg["input_path"])[cfg["input_key"]]
        shape = inp.shape
        block_shape = tuple(cfg["block_shape"])
        halo = tuple(cfg.get("halo") or [0] * len(shape))
        with np.load(cfg["checkpoint_path"]) as f:
            W, b = jnp.asarray(f["W"]), jnp.asarray(f["b"])
            sigmas = tuple(float(s) for s in f["sigmas"])
            selections = None
            if "ilp_features" in f and len(f["ilp_features"]):
                selections = tuple(
                    (s.rsplit(":", 1)[0], float(s.rsplit(":", 1)[1]))
                    for s in f["ilp_features"].tolist()
                )
        n_classes = W.shape[1]

        out = file_reader(cfg["output_path"]).require_dataset(
            cfg["output_key"],
            shape=(n_classes,) + shape,
            chunks=(1,) + block_shape,
            dtype="float32",
        )
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        done = set(self.blocks_done())
        todo = [blocking.get_block(b, halo) for b in block_ids if b not in done]
        outer = tuple(b + 2 * h for b, h in zip(block_shape, halo))

        def load(block):
            data = np.asarray(inp[block.outer_bb]).astype(np.float32)
            return (pad_block_to(data, outer, mode="edge"),)

        def kernel(x):
            if selections is not None:
                feats = ilp_feature_bank(x, selections)
            else:
                feats = feature_bank(x, sigmas)
            logits = feats @ W + b
            probs = jax.nn.softmax(logits, axis=-1)
            return jnp.moveaxis(probs, -1, 0)

        def store(block, raw):
            rel = block.inner_in_outer_bb
            out[(slice(None),) + block.bb] = np.asarray(raw)[(slice(None),) + rel]

        executor = BlockwiseExecutor(
            target=self.target,
            device_batch=int(cfg.get("device_batch", 1)),
            io_threads=max(1, self.max_jobs),
        )
        executor.map_blocks(
            kernel,
            todo,
            load,
            store,
            on_block_done=lambda b: self.log_block_success(b.block_id),
        )
        return {"n_blocks": len(todo), "n_classes": int(n_classes)}


class IlastikPredictionLocal(IlastikPredictionBase):
    target = "local"


class IlastikPredictionTPU(IlastikPredictionBase):
    target = "tpu"


class IlastikPredictionWorkflow(WorkflowBase):
    task_name = "ilastik_prediction_workflow"

    def requires(self):
        from . import ilastik as il_mod

        return [
            get_task_cls(il_mod, "IlastikPrediction", self.target)(
                tmp_folder=self.tmp_folder,
                config_dir=self.config_dir,
                max_jobs=self.max_jobs,
                dependencies=self.dependencies,
                **self.params,
            )
        ]
