"""Blockwise CNN inference: boundary/affinity prediction over the mesh.

Re-design of the reference's ``cluster_tools/inference/`` (SURVEY.md §2a,
§3.4): there, each slurm job loaded a PyTorch model onto its GPU and looped
blocks (read block+halo -> normalize -> model -> crop halo -> write C
channels).  Here one driver process runs the flax model batched over the
device mesh through the :class:`BlockwiseExecutor` — the whole forward is a
single jitted SPMD program, blocks sharded across devices, with the same
double-buffered host IO.

Params: ``input_path/input_key`` (raw), ``output_path/output_key``
(multi-channel float32, shape ``(C,) + volume``), ``checkpoint_path``
(flax msgpack or flat npz of params; None -> randomly initialized weights,
for pipeline smoke tests), ``model`` config dict (``name`` + kwargs for
:func:`..models.get_model`), ``halo``, ``normalize_percentile`` or fixed
``normalize_range``, ``activation`` ('sigmoid'/'softmax'/None).
"""

from __future__ import annotations

import os
from typing import Any, Dict

import numpy as np
import jax
import jax.numpy as jnp

from ..runtime.executor import (
    BlockwiseExecutor,
    is_sub_block,
    region_verifier,
)
from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader, pad_block_to


def load_checkpoint(path: str, model, sample_shape):
    """Load params: ``.msgpack`` (flax.serialization), ``.npz`` (flat
    '/'-joined keys), or ``.pt``/``.pth`` (torch state_dict, converted —
    see :mod:`cluster_tools_tpu.models.torch_import`)."""
    import flax

    if path.endswith((".pt", ".pth")):
        from ..models.torch_import import load_torch_checkpoint

        return load_torch_checkpoint(path, model, sample_shape)
    template = model.init(
        jax.random.PRNGKey(0), jnp.zeros(sample_shape, jnp.float32)
    )
    if path.endswith(".npz"):
        import flax.traverse_util as tu

        with np.load(path) as f:
            flat = {tuple(k.split("/")): f[k] for k in f.files}
        if next(iter(flat))[0] != "params":
            flat = {("params",) + k: v for k, v in flat.items()}
        return tu.unflatten_dict(flat)
    with open(path, "rb") as f:
        return flax.serialization.from_bytes(template, f.read())


def save_checkpoint(path: str, params) -> None:
    """Save flax params as flat npz (portable, no pickle)."""
    import flax.traverse_util as tu

    flat = tu.flatten_dict(params)
    np.savez(path, **{"/".join(map(str, k)): np.asarray(v) for k, v in flat.items()})


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


class InferenceBase(BaseTask):
    """Blockwise model prediction (reference: ``InferenceBase``)."""

    task_name = "inference"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "halo": [8, 8, 8],
            "model": {"name": "unet3d", "out_channels": 1},
            "checkpoint_path": None,
            "activation": "sigmoid",
            "normalize_percentile": None,
            "normalize_range": None,
        }

    def run_impl(self):
        from ..runtime import handoff

        cfg = self.get_config()
        inp = handoff.resolve_dataset(cfg["input_path"], cfg["input_key"])
        shape = inp.shape
        block_shape = tuple(cfg["block_shape"])
        halo = tuple(cfg.get("halo") or [0] * len(shape))
        from ..models import get_model  # lazy: flax only needed here

        model_cfg: Dict[str, Any] = dict(cfg.get("model") or {})
        model_name = model_cfg.pop("name", "unet3d")
        ckpt = cfg.get("checkpoint_path")
        variables = None
        if model_name == "auto":
            # "bring your own torch U-Net": architecture inferred from the
            # checkpoint's tensor census, no hand-written model config
            if not ckpt:
                raise ValueError(
                    "model name 'auto' infers the architecture from a "
                    "torch checkpoint — set checkpoint_path to a .pt/.pth"
                )
            from ..models.torch_import import import_torch_unet

            # remaining model-config keys override the inferred
            # architecture (e.g. dtype, norm)
            model, variables = import_torch_unet(ckpt, **model_cfg)
        else:
            model = get_model(model_name, **model_cfg)
        out_channels = getattr(model, "out_channels", 1)
        depth = getattr(model, "depth", 0)
        mult = 2 ** int(depth)

        # static kernel shape: outer block rounded up to the U-Net multiple
        outer = tuple(
            _round_up(b + 2 * h, mult) for b, h in zip(block_shape, halo)
        )
        sample = (1,) + outer + (1,)
        if variables is not None:
            pass  # imported together with the model above
        elif ckpt:
            variables = load_checkpoint(ckpt, model, sample)
        else:
            self.logger.info("no checkpoint_path: using random init (smoke mode)")
            variables = model.init(
                jax.random.PRNGKey(0), jnp.zeros(sample, jnp.float32)
            )

        # MemoryTarget output (docs/PERFORMANCE.md "Task-graph fusion"):
        # the probability map stays in RAM for a downstream watershed /
        # thresholding consumer, spilling to this path under the ladder
        out = self.handoff_dataset(
            cfg["output_path"], cfg["output_key"],
            shape=(out_channels,) + shape,
            chunks=(1,) + block_shape,
            dtype="float32",
        )
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        done = set(self.blocks_done())
        blocks_all = [blocking.get_block(b, halo) for b in block_ids]
        todo = [b for b in blocks_all if b.block_id not in done]

        pct = cfg.get("normalize_percentile")
        rng_norm = cfg.get("normalize_range")
        activation = cfg.get("activation", "sigmoid")

        allow_split = bool(cfg.get("allow_block_split", False))
        if allow_split and rng_norm is None:
            # per-block normalization statistics change with the read
            # region, so a split sub-block would be normalized differently
            # from its unsplit parent — only a fixed range is split-safe
            raise ValueError(
                "allow_block_split=True requires normalize_range "
                "(per-block percentile/min-max normalization is not "
                "split-safe)"
            )

        def load(block):
            data = np.asarray(inp[block.outer_bb]).astype(np.float32)
            if rng_norm is not None:
                lo, hi = float(rng_norm[0]), float(rng_norm[1])
            elif pct is not None:
                lo, hi = np.percentile(data, [100 - pct, pct])
            else:
                lo, hi = float(data.min()), float(data.max())
            data = (data - lo) / max(hi - lo, 1e-6)
            if is_sub_block(block):
                # degrade-split fragment: pad to its OWN U-Net multiple —
                # the smaller allocation is the point of the split (it
                # never enters a stacked batch, so the static shape does
                # not apply)
                target = tuple(_round_up(s, mult) for s in data.shape)
            else:
                target = outer
            return (pad_block_to(data, target)[..., None],)

        def kernel(x):
            logits = model.apply(variables, x[None])[0]
            if activation == "sigmoid":
                y = jax.nn.sigmoid(logits)
            elif activation == "softmax":
                y = jax.nn.softmax(logits, axis=-1)
            else:
                y = logits
            return jnp.moveaxis(y, -1, 0)  # -> (C, z, y, x)

        def store(block, raw):
            rel = block.inner_in_outer_bb
            out[(slice(None),) + block.bb] = np.asarray(raw)[(slice(None),) + rel]

        executor = BlockwiseExecutor(
            target=self.target,
            device_batch=int(cfg.get("device_batch", 1)),
            io_threads=int(cfg.get("io_threads") or max(1, self.max_jobs)),
            max_retries=int(cfg.get("io_retries", 2)),
            backoff_base=float(cfg.get("io_backoff_s", 0.05)),
        )
        # float probability outputs: the executor's built-in NaN/inf check
        # quarantines any block a bad kernel or checkpoint corrupts
        executor.map_blocks(
            kernel,
            blocks_all,
            load,
            store,
            on_block_done=lambda b: self.log_block_success(b.block_id),
            done_block_ids=done,
            failures_path=self.failures_path,
            task_name=self.uid,
            block_deadline_s=cfg.get("block_deadline_s"),
            watchdog_period_s=cfg.get("watchdog_period_s"),
            store_verify_fn=region_verifier(
                out, bb_of=lambda b: (slice(None),) + b.bb
            ),
            schedule=str(cfg.get("block_schedule") or "morton"),
            sweep_mode=str(cfg.get("sweep_mode") or "auto"),
            sharded_batch=cfg.get("sharded_batch"),
            device_pool=str(cfg.get("device_pool") or "auto"),
            device_pool_bytes=cfg.get("device_pool_bytes"),
            # opt-in OOM split (config allow_block_split): the conv kernel
            # is shape-local, so sub-block outputs tile the parent's region
            # exactly when halo covers the receptive field and the
            # normalization range is fixed (enforced above)
            splittable=allow_split,
            split_halo=halo,
            min_block_shape=cfg.get("min_block_shape"),
            degrade_wait_s=float(cfg.get("degrade_wait_s", 5.0)),
            inflight_byte_budget=cfg.get("inflight_byte_budget"),
        )
        return {
            "n_blocks": len(todo),
            "out_channels": int(out_channels),
            "model": model_name,
        }


class InferenceLocal(InferenceBase):
    target = "local"


class InferenceTPU(InferenceBase):
    target = "tpu"


class InferenceWorkflow(WorkflowBase):
    task_name = "inference_workflow"

    def requires(self):
        from . import inference as inf_mod

        return [
            get_task_cls(inf_mod, "Inference", self.target)(
                tmp_folder=self.tmp_folder,
                config_dir=self.config_dir,
                max_jobs=self.max_jobs,
                dependencies=self.dependencies,
                **self.params,
            )
        ]
