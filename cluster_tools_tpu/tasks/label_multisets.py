"""Label multisets: per-cell label histograms for multiscale label data.

Re-design of the reference's ``cluster_tools/label_multisets/`` (SURVEY.md
§2a): paintera represents downscaled label data as a *label multiset* per
voxel — the set of contained s0 labels with their counts — so that coarse
levels stay exact about what they contain.  The rebuild stores the same
information in an open container layout (one npz per block) next to an
``argmax`` dataset (the winning label per cell, what viewers render):

    <output_key>/argmax               uint64 dataset, mode-downsampled
    tmp/label_multisets/s<level>/block_<id>.npz
        offsets  [n_cells+1]  CSR offsets into entries
        entry_labels / entry_counts   concatenated per-cell histograms

Scale s+1 multisets are built from scale-s multisets (exact count
accumulation, not re-sampling), mirroring the reference's
``DownscaleMultisetBase``.
"""

from __future__ import annotations

import os
from typing import List, Sequence

import numpy as np

from ..runtime.executor import region_verifier
from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


def multiset_dir(tmp_folder: str, level: int) -> str:
    d = os.path.join(tmp_folder, "label_multisets", f"s{level}")
    os.makedirs(d, exist_ok=True)
    return d


def cell_multisets(seg: np.ndarray, factor: Sequence[int]):
    """Per-cell label histograms for one block: CSR arrays
    (offsets, labels, counts) over cells in C order, plus the argmax grid."""
    factor = tuple(int(f) for f in factor)
    pad = [(0, (-s) % f) for s, f in zip(seg.shape, factor)]
    sentinel = np.uint64(np.iinfo(np.uint64).max)
    if any(p[1] for p in pad):
        # sentinel padding keeps counts exact on non-divisible shapes; the
        # sentinel is dropped from every cell histogram below
        seg = np.pad(seg, pad, mode="constant", constant_values=sentinel)
    new_shape = []
    for s, f in zip(seg.shape, factor):
        new_shape += [s // f, f]
    cells = seg.reshape(new_shape)
    order = [2 * i for i in range(seg.ndim)] + [
        2 * i + 1 for i in range(seg.ndim)
    ]
    cells = cells.transpose(order).reshape(
        -1, int(np.prod(factor))
    )
    offsets = [0]
    labels_out: List[np.ndarray] = []
    counts_out: List[np.ndarray] = []
    argmax = np.zeros(len(cells), np.uint64)
    for i, cell in enumerate(cells):
        u, c = np.unique(cell, return_counts=True)
        keep = u != sentinel
        u, c = u[keep], c[keep]
        labels_out.append(u.astype(np.uint64))
        counts_out.append(c.astype(np.int64))
        offsets.append(offsets[-1] + len(u))
        # winner: most frequent non-zero label if any, else 0
        fg = u != 0
        argmax[i] = u[fg][np.argmax(c[fg])] if fg.any() else 0
    grid = tuple(s // f for s, f in zip(seg.shape, factor))
    return (
        np.asarray(offsets, np.int64),
        np.concatenate(labels_out) if labels_out else np.zeros(0, np.uint64),
        np.concatenate(counts_out) if counts_out else np.zeros(0, np.int64),
        argmax.reshape(grid),
    )


class CreateMultisetBase(BaseTask):
    """Scale-1 multisets + argmax from the s0 segmentation (reference:
    ``CreateMultisetBase``).  Params: ``input_path/input_key``,
    ``output_path/output_key``, ``scale_factor``."""

    task_name = "create_multiset"

    @staticmethod
    def default_task_config():
        return {"threads_per_job": 1, "device_batch": 1, "scale_factor": [2, 2, 2]}

    def run_impl(self):
        cfg = self.get_config()
        ds = file_reader(cfg["input_path"])[cfg["input_key"]]
        shape = ds.shape
        factor = tuple(int(f) for f in cfg.get("scale_factor", [2, 2, 2]))
        out_shape = tuple((s + f - 1) // f for s, f in zip(shape, factor))
        block_shape = tuple(cfg["block_shape"])
        out = file_reader(cfg["output_path"]).require_dataset(
            cfg["output_key"], shape=out_shape, chunks=block_shape, dtype="uint64"
        )
        # blocks over the OUTPUT grid; input window = block * factor
        blocking = Blocking(out_shape, block_shape)
        block_ids = blocks_in_volume(
            out_shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = multiset_dir(self.tmp_folder, 1)

        def process(block_id):
            block = blocking.get_block(block_id)
            in_bb = tuple(
                slice(b.start * f, min(b.stop * f, s))
                for b, f, s in zip(block.bb, factor, shape)
            )
            seg = np.asarray(ds[in_bb])
            offsets, labels, counts, argmax = cell_multisets(seg, factor)
            np.savez(
                os.path.join(d, f"block_{block_id}.npz"),
                offsets=offsets,
                labels=labels,
                counts=counts,
                cells=np.asarray(argmax.shape, np.int64),
            )
            out[block.bb] = argmax

        n = self.host_block_map(
            block_ids, process,
            store_verify_fn=region_verifier(out), blocking=blocking,
        )
        out.update_attrs(
            downsamplingFactors=list(factor), isLabelMultiset=True
        )
        return {"n_blocks": n, "out_shape": list(out_shape)}


class CreateMultisetLocal(CreateMultisetBase):
    target = "local"


class CreateMultisetTPU(CreateMultisetBase):
    target = "tpu"


class DownscaleMultisetBase(BaseTask):
    """Scale s -> s+1 by *exact* count accumulation from the scale-s
    multisets (reference: ``DownscaleMultisetBase``).  Single driver task:
    the multiset artifacts are host-side CSR files."""

    task_name = "downscale_multiset"

    @staticmethod
    def default_task_config():
        return {"threads_per_job": 1, "device_batch": 1, "scale_factor": [2, 2, 2]}

    def run_impl(self):
        cfg = self.get_config()
        level = int(cfg["level"])  # produce s<level+1> from s<level>
        factor = tuple(int(f) for f in cfg.get("scale_factor", [2, 2, 2]))
        src_dir = multiset_dir(self.tmp_folder, level)
        dst_dir = multiset_dir(self.tmp_folder, level + 1)
        shape = tuple(cfg["level_shape"])  # grid shape at `level`
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        out_shape = tuple((s + f - 1) // f for s, f in zip(shape, factor))
        out = file_reader(cfg["output_path"]).require_dataset(
            cfg["output_key"], shape=out_shape, chunks=block_shape, dtype="uint64"
        )

        # load the whole level (CSR per block) into a dict cell -> histogram
        from collections import defaultdict

        hist = defaultdict(dict)
        for b in range(blocking.n_blocks):
            p = os.path.join(src_dir, f"block_{b}.npz")
            if not os.path.exists(p):
                continue
            block = blocking.get_block(b)
            with np.load(p) as f:
                offsets, labels, counts = f["offsets"], f["labels"], f["counts"]
                cells = tuple(f["cells"])
            grid = np.array(
                np.unravel_index(np.arange(int(np.prod(cells))), cells)
            ).T
            for ci, (o0, o1) in enumerate(zip(offsets[:-1], offsets[1:])):
                coord = tuple(
                    (g + b0) // f
                    for g, b0, f in zip(grid[ci], block.begin, factor)
                )
                h = hist[coord]
                for lab, cnt in zip(labels[o0:o1], counts[o0:o1]):
                    h[int(lab)] = h.get(int(lab), 0) + int(cnt)

        # write s(level+1) blocks
        out_blocking = Blocking(out_shape, block_shape)
        for b in range(out_blocking.n_blocks):
            block = out_blocking.get_block(b)
            n_cells = int(np.prod(block.shape))
            offsets = [0]
            labs, cnts = [], []
            argmax = np.zeros(block.shape, np.uint64)
            for ci, coord in enumerate(np.ndindex(*block.shape)):
                g = tuple(c + b0 for c, b0 in zip(coord, block.begin))
                h = hist.get(g, {})
                u = np.array(sorted(h), np.uint64)
                c = np.array([h[int(k)] for k in u], np.int64)
                labs.append(u)
                cnts.append(c)
                offsets.append(offsets[-1] + len(u))
                fg = u != 0
                argmax[coord] = u[fg][np.argmax(c[fg])] if fg.any() else 0
            np.savez(
                os.path.join(dst_dir, f"block_{b}.npz"),
                offsets=np.asarray(offsets, np.int64),
                labels=np.concatenate(labs) if labs else np.zeros(0, np.uint64),
                counts=np.concatenate(cnts) if cnts else np.zeros(0, np.int64),
                cells=np.asarray(block.shape, np.int64),
            )
            out[block.bb] = argmax
        out.update_attrs(isLabelMultiset=True)
        return {"level": level + 1, "out_shape": list(out_shape)}


class DownscaleMultisetLocal(DownscaleMultisetBase):
    target = "local"


class DownscaleMultisetTPU(DownscaleMultisetBase):
    target = "tpu"
