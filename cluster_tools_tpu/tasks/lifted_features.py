"""Sparse lifted edges + lifted costs from node labels.

Re-design of the reference's ``cluster_tools/lifted_features/`` (SURVEY.md
§2a): build a sparse lifted neighborhood — node pairs within graph distance
``max_graph_distance`` that are not direct RAG neighbors — and derive lifted
costs from a node-label attribution (e.g. nucleus / semantic labels mapped
onto supervoxels by the node_labels workflow): same label -> attractive,
different labels -> repulsive.

Both tasks are driver-side: they act on the merged graph artifacts (tiny
next to the volume); the voxel-scale work happened in the graph/node_labels
passes.

Artifacts (in ``tmp_folder/lifted``):

    lifted_edges.npy  int64 [m, 2]  dense node ids, lexsorted
    lifted_costs.npy  float64 [m]
"""

from __future__ import annotations

import os

import numpy as np

from ..runtime.task import BaseTask
from .graph import load_global_graph
from .node_labels import node_labels_path


def lifted_dir(tmp_folder: str) -> str:
    d = os.path.join(tmp_folder, "lifted")
    os.makedirs(d, exist_ok=True)
    return d


def lifted_edges_path(tmp_folder: str) -> str:
    return os.path.join(lifted_dir(tmp_folder), "lifted_edges.npy")


def lifted_costs_path(tmp_folder: str) -> str:
    return os.path.join(lifted_dir(tmp_folder), "lifted_costs.npy")


def lifted_problem_path(tmp_folder: str) -> str:
    """The costed lifted problem: {edges, costs} — distinct from the raw
    neighborhood artifact so reruns with a different attribution re-filter
    from the full neighborhood."""
    return os.path.join(lifted_dir(tmp_folder), "lifted_problem.npz")


def sparse_lifted_neighborhood(
    n_nodes: int, edges: np.ndarray, max_graph_distance: int
) -> np.ndarray:
    """Node pairs at graph distance in [2, max_graph_distance]: boolean
    sparse matrix powers of the adjacency (reference:
    ``SparseLiftedNeighborhoodBase``, nifty BFS)."""
    from scipy.sparse import coo_matrix, eye

    if len(edges) == 0 or max_graph_distance < 2:
        return np.zeros((0, 2), np.int64)
    data = np.ones(len(edges), bool)
    a = coo_matrix(
        (data, (edges[:, 0], edges[:, 1])), shape=(n_nodes, n_nodes)
    )
    a = ((a + a.T) > 0).tocsr()
    reach = a.copy()
    acc = a.copy()
    for _ in range(max_graph_distance - 1):
        reach = ((reach @ a) > 0).tocsr()
        acc = ((acc + reach) > 0).tocsr()
    lifted = acc.astype(np.int8) - a.astype(np.int8) - eye(n_nodes, dtype=np.int8)
    lifted = (lifted > 0).tocoo()
    uv = np.stack([lifted.row, lifted.col], axis=1).astype(np.int64)
    uv = uv[uv[:, 0] < uv[:, 1]]
    order = np.lexsort((uv[:, 1], uv[:, 0]))
    return uv[order]


class SparseLiftedNeighborhoodBase(BaseTask):
    """Params: ``max_graph_distance`` (default 2)."""

    task_name = "sparse_lifted_neighborhood"

    @staticmethod
    def default_task_config():
        return {"threads_per_job": 1, "device_batch": 1, "max_graph_distance": 2}

    def run_impl(self):
        cfg = self.get_config()
        nodes, _, edges, _ = load_global_graph(self.tmp_folder)
        uv = sparse_lifted_neighborhood(
            len(nodes),
            edges.astype(np.int64),
            int(cfg.get("max_graph_distance", 2)),
        )
        np.save(lifted_edges_path(self.tmp_folder), uv)
        return {"n_lifted_edges": int(len(uv))}


class SparseLiftedNeighborhoodLocal(SparseLiftedNeighborhoodBase):
    target = "local"


class SparseLiftedNeighborhoodTPU(SparseLiftedNeighborhoodBase):
    target = "tpu"


class CostsFromNodeLabelsBase(BaseTask):
    """Lifted costs from a node-label attribution (reference: the lifted
    cost tasks fed by nucleus/semantic labels).

    Reads the node_labels table (segment id -> attributed label); lifted
    pairs where BOTH endpoints are attributed get cost ``+w_attractive``
    when the labels agree and ``-w_repulsive`` when they differ; pairs with
    unattributed endpoints are dropped (cost undefined).

    ``include_local_edges`` (default True) also adds attributed *direct*
    RAG-neighbor pairs to the lifted set: the attribution evidence then
    biases adjacent supervoxels too (nucleus-style workflows need this —
    an ambiguous local boundary between two same-nucleus supervoxels should
    merge), while the pure >=2-hop set only constrains long range.
    """

    task_name = "costs_from_node_labels"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "w_attractive": 1.0,
            "w_repulsive": 1.0,
            "include_local_edges": True,
        }

    def run_impl(self):
        cfg = self.get_config()
        nodes, _, local_edges, _ = load_global_graph(self.tmp_folder)
        uv = np.load(lifted_edges_path(self.tmp_folder))
        if cfg.get("include_local_edges", True) and len(local_edges):
            uv = np.unique(
                np.concatenate([uv, local_edges.astype(np.int64)]), axis=0
            )
        with np.load(node_labels_path(self.tmp_folder)) as f:
            keys, values = f["keys"], f["values"]
        # segment (original uint64) -> attribution, via the dense node table
        attr = np.zeros(len(nodes), np.uint64)
        idx = np.searchsorted(keys, nodes)
        idx_c = np.clip(idx, 0, max(len(keys) - 1, 0))
        if len(keys):
            matched = keys[idx_c] == nodes
            attr[matched] = values[idx_c[matched]]
        a_u = attr[uv[:, 0]]
        a_v = attr[uv[:, 1]]
        labeled = (a_u != 0) & (a_v != 0)
        uv = uv[labeled]
        same = a_u[labeled] == a_v[labeled]
        costs = np.where(
            same,
            float(cfg.get("w_attractive", 1.0)),
            -float(cfg.get("w_repulsive", 1.0)),
        ).astype(np.float64)
        # distinct artifact: never overwrite the neighborhood task's output
        np.savez(lifted_problem_path(self.tmp_folder), edges=uv, costs=costs)
        return {
            "n_lifted_edges": int(len(uv)),
            "n_attractive": int(same.sum()),
        }


class CostsFromNodeLabelsLocal(CostsFromNodeLabelsBase):
    target = "local"


class CostsFromNodeLabelsTPU(CostsFromNodeLabelsBase):
    target = "tpu"
