"""Hierarchical lifted multicut (reference: ``cluster_tools/lifted_multicut/``,
SURVEY.md §2a): the multicut domain-decomposition scheme with the lifted
objective — sparse long-range edges whose costs apply whenever their
endpoints end up in different clusters.

Same task structure as :mod:`.multicut` (SolveLiftedSubproblems ->
ReduceLiftedProblem per scale, then SolveLiftedGlobal), with the lifted
edge set carried through every reduction: contracted endpoints map through
the node labeling, internal lifted edges (endpoints merged) drop out, and
parallel lifted edges accumulate.

State: ``tmp_folder/lifted_multicut/problem_s<level>.npz``
{edges, costs, lifted_edges, lifted_costs, node_labeling}; the final
assignment table is write-task-compatible (``lmc_assignments.npz``).
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..ops.multicut import (
    contract_graph,
    lifted_greedy_additive,
    lifted_multicut_energy,
)
from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import file_reader
from .costs import costs_path
from .graph import load_global_graph
from .lifted_features import lifted_problem_path
from .multicut import _scale_block_nodes


def lmc_dir(tmp_folder: str) -> str:
    d = os.path.join(tmp_folder, "lifted_multicut")
    os.makedirs(d, exist_ok=True)
    return d


def lmc_problem_path(tmp_folder: str, scale: int) -> str:
    return os.path.join(lmc_dir(tmp_folder), f"problem_s{scale}.npz")


def lmc_cut_edges_path(tmp_folder: str, scale: int) -> str:
    return os.path.join(lmc_dir(tmp_folder), f"cut_edges_s{scale}.npz")


def lmc_assignments_path(tmp_folder: str) -> str:
    return os.path.join(lmc_dir(tmp_folder), "lmc_assignments.npz")


def _load_problem(tmp_folder: str, scale: int):
    if scale == 0:
        from ..runtime import handoff

        _, _, edges, _ = load_global_graph(tmp_folder)
        costs = handoff.load_array(costs_path(tmp_folder)).astype(np.float64)
        with np.load(lifted_problem_path(tmp_folder)) as f:
            lifted_edges = f["edges"].astype(np.int64)
            lifted_costs = f["costs"].astype(np.float64)
        n_nodes = int(edges.max()) + 1 if len(edges) else 0
        node_labeling = np.arange(n_nodes, dtype=np.int64)
        return edges.astype(np.int64), costs, lifted_edges, lifted_costs, node_labeling
    with np.load(lmc_problem_path(tmp_folder, scale)) as f:
        return (
            f["edges"].astype(np.int64),
            f["costs"].astype(np.float64),
            f["lifted_edges"].astype(np.int64),
            f["lifted_costs"].astype(np.float64),
            f["node_labeling"].astype(np.int64),
        )


class SolveLiftedSubproblemsBase(BaseTask):
    """Per-block lifted subproblems at one scale (reference:
    ``solve_lifted_subproblems.py``)."""

    task_name = "solve_lifted_subproblems"

    def run_impl(self):
        cfg = self.get_config()
        scale = int(cfg.get("scale", 0))
        edges, costs, ledges, lcosts, node_labeling = _load_problem(
            self.tmp_folder, scale
        )
        block_nodes = _scale_block_nodes(self.tmp_folder, cfg, scale, node_labeling)

        cut = np.zeros(len(edges), dtype=bool)
        seen = np.zeros(len(edges), dtype=bool)

        def process(item):
            block_id, nodes = item
            if len(nodes) < 2:
                return None
            sub_mask = np.isin(edges[:, 0], nodes) & np.isin(edges[:, 1], nodes)
            if not sub_mask.any():
                return None
            sub_edges = edges[sub_mask]
            sub_costs = costs[sub_mask]
            lsub_mask = (
                np.isin(ledges[:, 0], nodes) & np.isin(ledges[:, 1], nodes)
                if len(ledges)
                else np.zeros(0, bool)
            )
            # compact ids over local + lifted endpoints
            all_e = (
                np.concatenate([sub_edges, ledges[lsub_mask]])
                if lsub_mask.any()
                else sub_edges
            )
            sub_nodes, inv = np.unique(all_e, return_inverse=True)
            inv = inv.reshape(all_e.shape)
            n_local = len(sub_edges)
            labels = lifted_greedy_additive(
                len(sub_nodes),
                inv[:n_local],
                sub_costs,
                inv[n_local:],
                lcosts[lsub_mask],
            )
            is_cut = labels[inv[:n_local, 0]] != labels[inv[:n_local, 1]]
            return sub_mask, is_cut

        with ThreadPoolExecutor(max_workers=max(1, self.max_jobs)) as pool:
            for res in pool.map(process, sorted(block_nodes.items())):
                if res is None:
                    continue
                sub_mask, is_cut = res
                idx = np.flatnonzero(sub_mask)
                seen[idx] = True
                cut[idx[is_cut]] = True

        np.savez(lmc_cut_edges_path(self.tmp_folder, scale), cut=cut, seen=seen)
        return {
            "scale": scale,
            "n_subproblems": len(block_nodes),
            "n_cut": int(cut.sum()),
        }


class SolveLiftedSubproblemsLocal(SolveLiftedSubproblemsBase):
    target = "local"


class SolveLiftedSubproblemsTPU(SolveLiftedSubproblemsBase):
    target = "tpu"


class ReduceLiftedProblemBase(BaseTask):
    """Contract merge edges; carry lifted edges to the reduced id space
    (reference: ``reduce_lifted_problem.py``)."""

    task_name = "reduce_lifted_problem"

    def run_impl(self):
        cfg = self.get_config()
        scale = int(cfg.get("scale", 0))
        edges, costs, ledges, lcosts, node_labeling = _load_problem(
            self.tmp_folder, scale
        )
        with np.load(lmc_cut_edges_path(self.tmp_folder, scale)) as f:
            cut, seen = f["cut"], f["seen"]
        n_nodes = int(node_labeling.max()) + 1 if len(node_labeling) else 0

        from ..ops.unionfind import union_find_host

        roots = union_find_host(edges[seen & ~cut], n_nodes)
        _, new_ids = np.unique(roots, return_inverse=True)
        new_ids = new_ids.astype(np.int64)

        new_edges, new_costs = contract_graph(edges, costs, new_ids)
        new_ledges, new_lcosts = contract_graph(ledges, lcosts, new_ids)
        np.savez(
            lmc_problem_path(self.tmp_folder, scale + 1),
            edges=new_edges,
            costs=new_costs,
            lifted_edges=new_ledges,
            lifted_costs=new_lcosts,
            node_labeling=new_ids[node_labeling],
        )
        return {
            "scale": scale,
            "n_nodes": int(new_ids.max()) + 1 if len(new_ids) else 0,
            "n_edges": len(new_edges),
            "n_lifted_edges": len(new_ledges),
        }


class ReduceLiftedProblemLocal(ReduceLiftedProblemBase):
    target = "local"


class ReduceLiftedProblemTPU(ReduceLiftedProblemBase):
    target = "tpu"


class SolveLiftedGlobalBase(BaseTask):
    """Final lifted solve + assignment table (reference:
    ``solve_lifted_global.py``).

    ``solver_shards > 1`` shards the solve over the Morton-octant reduce
    tree exactly like :class:`..multicut.SolveGlobalBase`, with the lifted
    edge set carried through every level: contracted endpoints relabel,
    internal lifted edges join the node's lifted GAEC solve, parallel
    lifted edges accumulate.  The lifted node solver is boundary-blind
    (no frontier formulation for the lifted objective yet); the
    single-host lifted GAEC remains the ``solver_shards=1`` case and the
    ``degraded:unsharded_solve`` fallback."""

    task_name = "solve_lifted_global"

    def run_impl(self):
        from ..ops import contraction as contraction_mod
        from ..parallel import reduce_tree as reduce_tree_mod
        from ..runtime import handoff
        from .multicut import _octant_node_shards, _solver_manifest

        cfg = self.get_config()
        scale = int(cfg.get("scale", 0))
        edges, costs, ledges, lcosts, node_labeling = _load_problem(
            self.tmp_folder, scale
        )
        n_nodes = int(node_labeling.max()) + 1 if len(node_labeling) else 0
        shards = int(cfg.get("solver_shards", 1) or 1)
        solver_snap = contraction_mod.solver_snapshot()
        tree_snap = reduce_tree_mod.solve_snapshot()

        def unsharded():
            return (
                lifted_greedy_additive(n_nodes, edges, costs, ledges, lcosts)
                if len(edges)
                else np.zeros(n_nodes, np.int64)
            )

        if shards > 1 and len(edges):
            # partition as a thunk: see multicut.SolveGlobalBase — failure
            # to build it degrades instead of failing the task
            labels, solve_info = reduce_tree_mod.solve_with_reduce_tree(
                n_nodes, edges, costs,
                node_shard=lambda: _octant_node_shards(
                    self.tmp_folder, cfg, scale, node_labeling, n_nodes,
                    shards,
                ),
                solver_shards=shards,
                fanout=int(cfg.get("reduce_fanout", 2) or 2),
                # lifted edges have no frontier formulation
                # (ops.multicut.lifted_frontier_capable) — the plane
                # degrades itself, but the knob stays config-reachable
                reduce_plane=str(cfg.get("reduce_plane", "auto") or "auto"),
                hop_deadline_s=cfg.get("hop_deadline_s"),
                failures_path=self.failures_path,
                task_name=self.uid,
                unsharded=unsharded,
                lifted_edges=ledges,
                lifted_payload=lcosts,
                workers=int(cfg.get("solver_workers", 1) or 1),
                scratch_dir=os.path.join(
                    lmc_dir(self.tmp_folder), "reduce_tree"
                ),
                max_workers=max(1, self.max_jobs),
            )
        else:
            labels = unsharded()
            solve_info = {"sharded": False, "shards": 1}
        final = labels[node_labeling]
        nodes_table, _, edges0, _ = load_global_graph(self.tmp_folder)
        with np.load(lifted_problem_path(self.tmp_folder)) as f:
            le0, lc0 = f["edges"].astype(np.int64), f["costs"].astype(np.float64)
        energy = lifted_multicut_energy(
            edges0.astype(np.int64),
            handoff.load_array(costs_path(self.tmp_folder)).astype(np.float64),
            le0,
            lc0,
            final,
        )
        np.savez(
            lmc_assignments_path(self.tmp_folder),
            keys=nodes_table,
            values=(final + 1).astype(np.uint64),
        )
        return {
            "n_segments": int(final.max()) + 1 if len(final) else 0,
            "energy": energy,
            "solver": _solver_manifest(
                energy, edges, labels,
                contraction_mod.solver_delta(solver_snap),
                reduce_tree_mod.solve_delta(tree_snap),
                solve_info,
            ),
        }


class SolveLiftedGlobalLocal(SolveLiftedGlobalBase):
    target = "local"


class SolveLiftedGlobalTPU(SolveLiftedGlobalBase):
    target = "tpu"


class LiftedMulticutWorkflow(WorkflowBase):
    """The lifted scale loop + global solve, given graph/costs/lifted
    artifacts.  Params as :class:`.multicut.MulticutWorkflow`."""

    task_name = "lifted_multicut_workflow"

    def requires(self):
        from . import lifted_multicut as lmc_mod

        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        p = self.params
        n_scales = int(p.get("n_scales", 1))
        keys = {
            k: p[k]
            for k in (
                "input_path", "input_key", "block_shape", "roi_begin",
                "roi_end", "solver_shards", "reduce_fanout", "solver_workers",
            )
            if k in p
        }
        deps = list(self.dependencies)
        for s in range(n_scales):
            t_solve = get_task_cls(lmc_mod, "SolveLiftedSubproblems", self.target)(
                **common, dependencies=deps, scale=s, **keys
            )
            t_reduce = get_task_cls(lmc_mod, "ReduceLiftedProblem", self.target)(
                **common, dependencies=[t_solve], scale=s, **keys
            )
            deps = [t_reduce]
        t_global = get_task_cls(lmc_mod, "SolveLiftedGlobal", self.target)(
            **common, dependencies=deps, scale=n_scales, **keys
        )
        return [t_global]
