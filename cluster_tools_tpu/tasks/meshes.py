"""Per-object surface meshes from a segmentation (reference:
``cluster_tools/meshes/`` — SURVEY.md §2a tags meshes as a
possibly-present extra; provided so migrating users find the capability).

Re-design, not a port: the reference ran marching cubes (elf) per object.
Here each object's surface is extracted as its exposed voxel faces —
exact, watertight, orientation-consistent quads split into triangles,
with vertices deduplicated on the corner grid — optionally relaxed by a
few Laplacian smoothing iterations (the classic post-pass that removes
the staircase bias while keeping the mesh closed).  This is the same
representation neuroglancer's base-resolution precomputed meshes use,
needs no lookup tables, and vectorizes over the whole bounding box.

Orientation: triangles wind so normals point OUT of the object; the
divergence-theorem signed volume of the mesh equals the voxel count
exactly (regression-tested), which downstream consumers can use as a
cheap integrity check.

Artifacts: ``meshes/<id>.npz`` {vertices [n, 3] float64 (z, y, x in
global coords), faces [m, 3] int64} and optional ``<id>.obj``.
"""

from __future__ import annotations

import os

import numpy as np

from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import file_reader
from .morphology import MorphologyWorkflow, morphology_path


def mesh_dir(tmp_folder: str) -> str:
    d = os.path.join(tmp_folder, "meshes")
    os.makedirs(d, exist_ok=True)
    return d


# ring orientation of the two in-plane axes (u, w) = the other two axes in
# ascending order: e_u x e_w = +e_k for k in {0, 2}, -e_k for k = 1
_RING_SIGN = {0: 1.0, 1: -1.0, 2: 1.0}


def _face_quads(mask: np.ndarray, axis: int, positive: bool):
    """Quad corner coordinates [q, 4, 3] for exposed faces along ``axis``.

    A face is exposed where the object voxel's ``axis``-neighbor (in the
    ``positive`` direction) is background; the quad lies on the corner
    plane between them, wound so the normal points toward background.
    """
    m = np.pad(mask, [(1, 1) if a == axis else (0, 0) for a in range(3)])
    inside = np.take(m, range(1, m.shape[axis] - 1), axis=axis)
    nb = np.take(
        m,
        range(2, m.shape[axis]) if positive else range(0, m.shape[axis] - 2),
        axis=axis,
    )
    exposed = inside & ~nb
    vox = np.argwhere(exposed).astype(np.float64)  # [q, 3]
    if len(vox) == 0:
        return np.zeros((0, 4, 3))
    u, w = [a for a in range(3) if a != axis]
    plane = vox[:, axis] + (1.0 if positive else 0.0)
    ring = [(0.0, 0.0), (1.0, 0.0), (1.0, 1.0), (0.0, 1.0)]
    sign = _RING_SIGN[axis] * (1.0 if positive else -1.0)
    if sign < 0:
        ring = ring[::-1]
    quads = np.empty((len(vox), 4, 3))
    for c, (du, dw) in enumerate(ring):
        quads[:, c, axis] = plane
        quads[:, c, u] = vox[:, u] + du
        quads[:, c, w] = vox[:, w] + dw
    return quads


def mesh_object(
    mask: np.ndarray,
    offset=(0, 0, 0),
    smoothing_iterations: int = 0,
    smoothing_lambda: float = 0.5,
):
    """Mesh one binary object: returns (vertices [n, 3], faces [m, 3]).

    Vertices are in global (z, y, x) coordinates (``offset`` = bounding-box
    origin); faces wind outward.
    """
    quads = np.concatenate(
        [
            _face_quads(mask, axis, positive)
            for axis in range(3)
            for positive in (True, False)
        ]
    )
    if len(quads) == 0:
        return np.zeros((0, 3)), np.zeros((0, 3), np.int64)
    # dedup corners on the (Z+1, Y+1, X+1) corner grid
    dims = np.asarray(mask.shape, np.int64) + 1
    flat = quads.reshape(-1, 3).astype(np.int64)
    lin = (flat[:, 0] * dims[1] + flat[:, 1]) * dims[2] + flat[:, 2]
    uniq, inverse = np.unique(lin, return_inverse=True)
    vertices = np.stack(
        [uniq // (dims[1] * dims[2]), (uniq // dims[2]) % dims[1], uniq % dims[2]],
        axis=1,
    ).astype(np.float64)
    corner_ids = inverse.reshape(-1, 4)
    faces = np.concatenate(
        [corner_ids[:, [0, 1, 2]], corner_ids[:, [0, 2, 3]]]
    ).astype(np.int64)

    if smoothing_iterations > 0:
        # uniform-weight Laplacian relaxation over the face edge graph
        e = np.concatenate([faces[:, [0, 1]], faces[:, [1, 2]], faces[:, [2, 0]]])
        lam = float(smoothing_lambda)
        deg = np.zeros(len(vertices))
        np.add.at(deg, e[:, 0], 1.0)
        np.add.at(deg, e[:, 1], 1.0)
        deg = np.maximum(deg, 1.0)[:, None]
        for _ in range(int(smoothing_iterations)):
            acc = np.zeros_like(vertices)
            np.add.at(acc, e[:, 0], vertices[e[:, 1]])
            np.add.at(acc, e[:, 1], vertices[e[:, 0]])
            vertices = vertices + lam * (acc / deg - vertices)

    return vertices + np.asarray(offset, np.float64), faces


def mesh_signed_volume(vertices: np.ndarray, faces: np.ndarray) -> float:
    """Divergence-theorem volume; equals the voxel count for an unsmoothed
    outward-wound voxel-face mesh."""
    v0, v1, v2 = (vertices[faces[:, i]] for i in range(3))
    return float(np.einsum("ij,ij->i", v0, np.cross(v1, v2)).sum() / 6.0)


def write_obj(path: str, vertices: np.ndarray, faces: np.ndarray):
    """Wavefront OBJ export (x y z vertex order, 1-based faces)."""
    with open(path, "w") as f:
        for z, y, x in vertices:
            f.write(f"v {x:.4f} {y:.4f} {z:.4f}\n")
        for a, b, c in faces + 1:
            f.write(f"f {a} {b} {c}\n")


class MeshesBase(BaseTask):
    """Mesh objects using the morphology table's bounding boxes (same
    discovery pattern as skeletons).  Params: ``input_path/input_key``
    (segmentation), optional ``object_ids``, ``min_size``,
    ``smoothing_iterations``, ``smoothing_lambda``, ``export_obj``."""

    task_name = "meshes"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "min_size": 1,
            "smoothing_iterations": 0,
            "smoothing_lambda": 0.5,
            "export_obj": False,
            "object_ids": None,
        }

    def run_impl(self):
        cfg = self.get_config()
        ds = file_reader(cfg["input_path"])[cfg["input_key"]]
        with np.load(morphology_path(self.tmp_folder)) as f:
            ids, sizes = f["ids"], f["sizes"]
            bb_min, bb_max = f["bb_min"], f["bb_max"]
        wanted = cfg.get("object_ids")
        min_size = int(cfg.get("min_size") or 1)
        sel = sizes >= min_size
        if wanted is not None:
            sel &= np.isin(ids, np.asarray(wanted, dtype=ids.dtype))
        smooth_n = int(cfg.get("smoothing_iterations") or 0)
        smooth_lam = float(cfg.get("smoothing_lambda", 0.5))
        export_obj = bool(cfg.get("export_obj", False))
        d = mesh_dir(self.tmp_folder)

        todo = [int(i) for i in np.flatnonzero(sel)]

        def process(idx):
            obj = ids[idx]
            lo, hi = bb_min[idx], bb_max[idx]
            bb = tuple(slice(int(a), int(b)) for a, b in zip(lo, hi))
            mask = np.asarray(ds[bb]) == obj
            vertices, faces = mesh_object(
                mask, offset=lo,
                smoothing_iterations=smooth_n, smoothing_lambda=smooth_lam,
            )
            np.savez(
                os.path.join(d, f"{int(obj)}.npz"),
                vertices=vertices, faces=faces,
            )
            if export_obj:
                write_obj(os.path.join(d, f"{int(obj)}.obj"), vertices, faces)

        n = self.host_block_map(todo, process)
        return {"n_objects": n}


class MeshesLocal(MeshesBase):
    target = "local"


class MeshesTPU(MeshesBase):
    target = "tpu"


class MeshWorkflow(WorkflowBase):
    """morphology (for bounding boxes) -> meshes."""

    task_name = "mesh_workflow"

    def requires(self):
        from . import meshes as me_mod

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        grid = {
            k: p[k]
            for k in ("input_path", "input_key", "block_shape", "roi_begin", "roi_end")
            if k in p
        }
        morph = MorphologyWorkflow(
            **common, target=self.target, dependencies=self.dependencies, **grid
        )
        me = get_task_cls(me_mod, "Meshes", self.target)(
            **common,
            dependencies=[morph],
            **grid,
            **{
                k: p[k]
                for k in (
                    "min_size",
                    "smoothing_iterations",
                    "smoothing_lambda",
                    "export_obj",
                    "object_ids",
                )
                if k in p
            },
        )
        return [me]


class MeshesWorkflow(MeshWorkflow):
    """Alias matching the reference's naming."""

    task_name = "meshes_workflow"
