"""Per-object morphology statistics, blockwise + merge.

Re-design of the reference's ``cluster_tools/morphology/`` (SURVEY.md §2a):
``block_morphology.py`` accumulated per-object partial statistics per block,
``merge_morphology.py`` combined them into the global morphology table
(sizes, centers of mass, bounding boxes per segment id).

Per block the accumulation is vectorized over the dense per-block label set
(unique + scatter-adds over voxel coordinate grids); the merge is a
segment-sum over the concatenated per-block partials.  The final table is an
npz keyed by sorted segment id:

    morphology.npz: ids [n], sizes [n], com [n, d] (center of mass, voxel
    coords), bb_min [n, d], bb_max [n, d] (inclusive-exclusive bounding box)
"""

from __future__ import annotations

import os

import numpy as np

from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


def _morph_dir(tmp_folder: str) -> str:
    d = os.path.join(tmp_folder, "morphology")
    os.makedirs(d, exist_ok=True)
    return d


def morphology_path(tmp_folder: str) -> str:
    return os.path.join(_morph_dir(tmp_folder), "morphology.npz")


def block_morphology(labels: np.ndarray, offset) -> dict:
    """Partial morphology of one block: per local object, voxel count,
    coordinate sum (for center of mass), and bounding box — in *global*
    coordinates given the block ``offset``."""
    ids, inv = np.unique(labels, return_inverse=True)
    inv = inv.ravel()
    fg = ids != 0
    n = len(ids)
    counts = np.bincount(inv, minlength=n).astype(np.int64)
    ndim = labels.ndim
    coord_sum = np.zeros((n, ndim), np.float64)
    bb_min = np.zeros((n, ndim), np.int64)
    bb_max = np.zeros((n, ndim), np.int64)
    grids = np.meshgrid(
        *[np.arange(s, dtype=np.int64) for s in labels.shape], indexing="ij"
    )
    for d in range(ndim):
        g = grids[d].ravel() + int(offset[d])
        coord_sum[:, d] = np.bincount(inv, weights=g, minlength=n)
        mn = np.full(n, np.iinfo(np.int64).max)
        np.minimum.at(mn, inv, g)
        mx = np.full(n, -1)
        np.maximum.at(mx, inv, g)
        bb_min[:, d] = mn
        bb_max[:, d] = mx + 1  # exclusive
    return {
        "ids": ids[fg].astype(np.uint64),
        "counts": counts[fg],
        "coord_sum": coord_sum[fg],
        "bb_min": bb_min[fg],
        "bb_max": bb_max[fg],
    }


class BlockMorphologyBase(BaseTask):
    """Per-block partial morphology (reference: ``block_morphology.py``)."""

    task_name = "block_morphology"

    def run_impl(self):
        cfg = self.get_config()
        ds = file_reader(cfg["input_path"])[cfg["input_key"]]
        shape = ds.shape
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = _morph_dir(self.tmp_folder)

        def process(block_id):
            block = blocking.get_block(block_id)
            part = block_morphology(np.asarray(ds[block.bb]), block.begin)
            np.savez(os.path.join(d, f"block_{block_id}.npz"), **part)

        n = self.host_block_map(block_ids, process)
        return {"n_blocks": n}


class BlockMorphologyLocal(BlockMorphologyBase):
    target = "local"


class BlockMorphologyTPU(BlockMorphologyBase):
    target = "tpu"


class MergeMorphologyBase(BaseTask):
    """Merge partial morphologies -> global table (reference:
    ``merge_morphology.py``)."""

    task_name = "merge_morphology"

    def run_impl(self):
        cfg = self.get_config()
        shape = file_reader(cfg["input_path"])[cfg["input_key"]].shape
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = _morph_dir(self.tmp_folder)
        parts = []
        for b in block_ids:
            p = os.path.join(d, f"block_{b}.npz")
            if os.path.exists(p):
                with np.load(p) as f:
                    parts.append({k: f[k] for k in f.files})
        if not parts:
            np.savez(
                morphology_path(self.tmp_folder),
                ids=np.zeros(0, np.uint64),
                sizes=np.zeros(0, np.int64),
                com=np.zeros((0, len(shape))),
                bb_min=np.zeros((0, len(shape)), np.int64),
                bb_max=np.zeros((0, len(shape)), np.int64),
            )
            return {"n_objects": 0}
        all_ids = np.concatenate([p["ids"] for p in parts])
        ids, inv = np.unique(all_ids, return_inverse=True)
        inv = inv.ravel()
        n = len(ids)
        ndim = len(shape)
        sizes = np.zeros(n, np.int64)
        np.add.at(sizes, inv, np.concatenate([p["counts"] for p in parts]))
        coord_sum = np.zeros((n, ndim), np.float64)
        bb_min = np.full((n, ndim), np.iinfo(np.int64).max)
        bb_max = np.zeros((n, ndim), np.int64)
        cs = np.concatenate([p["coord_sum"] for p in parts])
        mn = np.concatenate([p["bb_min"] for p in parts])
        mx = np.concatenate([p["bb_max"] for p in parts])
        for dd in range(ndim):
            np.add.at(coord_sum[:, dd], inv, cs[:, dd])
            np.minimum.at(bb_min[:, dd], inv, mn[:, dd])
            np.maximum.at(bb_max[:, dd], inv, mx[:, dd])
        com = coord_sum / sizes[:, None]
        np.savez(
            morphology_path(self.tmp_folder),
            ids=ids,
            sizes=sizes,
            com=com,
            bb_min=bb_min,
            bb_max=bb_max,
        )
        return {"n_objects": int(n)}


class MergeMorphologyLocal(MergeMorphologyBase):
    target = "local"


class MergeMorphologyTPU(MergeMorphologyBase):
    target = "tpu"


class MorphologyWorkflow(WorkflowBase):
    """block_morphology -> merge_morphology."""

    task_name = "morphology_workflow"

    def requires(self):
        from . import morphology as m_mod

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        kw = {
            k: p[k]
            for k in ("input_path", "input_key", "block_shape", "roi_begin", "roi_end")
            if k in p
        }
        t1 = get_task_cls(m_mod, "BlockMorphology", self.target)(
            **common, dependencies=self.dependencies, **kw
        )
        t2 = get_task_cls(m_mod, "MergeMorphology", self.target)(
            **common, dependencies=[t1], **kw
        )
        return [t2]
