"""Hierarchical multicut: blockwise subproblems -> reduce -> global solve.

Re-design of the reference's ``cluster_tools/multicut/`` (SURVEY.md §2a
"multicut", §3.3; the domain-decomposition scheme of Pape et al. 2017):

    for scale s in 0..S-1:
        SolveSubproblems  per scale-s block: extract the sub-graph of the
                          current (reduced) problem induced by the block's
                          nodes, solve multicut on it, record which edges it
                          cuts
        ReduceProblem     contract every edge *no* subproblem cut
                          (union-find), sum parallel-edge costs -> a smaller
                          problem; scale-(s+1) blocks are 2x larger per axis
    SolveGlobal           solve the final reduced problem with a registry
                          solver, compose labelings back to original nodes

State between tasks lives in ``tmp_folder/multicut/problem_s<level>.npz``:
``edges``/``costs`` of the current reduced graph (dense current ids) and
``node_labeling`` mapping original dense graph nodes -> current ids.  The
final output is a write-task-compatible assignment table
(``mc_assignments.npz``: sorted uint64 ``keys`` -> uint64 ``values``).

The subproblem/global solvers are the host solvers of
:mod:`..ops.multicut` — solver inputs are reduced graphs, tiny next to the
volume; the voxel-scale work (RAG scan, feature accumulation, relabeling)
is where the device time goes.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..ops.multicut import contract_graph, multicut_energy
from ..runtime import handoff
from ..runtime.task import BaseTask, WorkflowBase
from ..utils.segmentation_utils import get_multicut_solver
from ..utils.volume_utils import Blocking, blocks_in_volume
from .costs import costs_path
from .graph import block_graph_path, load_global_graph


def mc_dir(tmp_folder: str) -> str:
    d = os.path.join(tmp_folder, "multicut")
    os.makedirs(d, exist_ok=True)
    return d


def problem_path(tmp_folder: str, scale: int) -> str:
    return os.path.join(mc_dir(tmp_folder), f"problem_s{scale}.npz")


def cut_edges_path(tmp_folder: str, scale: int) -> str:
    return os.path.join(mc_dir(tmp_folder), f"cut_edges_s{scale}.npz")


def assignments_path(tmp_folder: str) -> str:
    return os.path.join(mc_dir(tmp_folder), "mc_assignments.npz")


def _load_problem(tmp_folder: str, scale: int):
    """Problem at ``scale``: s0 is built from the graph + costs artifacts
    (fusable edges: served from live in-memory handoffs when the producing
    tasks published them, else from the npz/npy artifacts)."""
    if scale == 0:
        _, _, edges, _ = load_global_graph(tmp_folder)
        costs = handoff.load_array(costs_path(tmp_folder)).astype(np.float64)
        n_nodes = int(edges.max()) + 1 if len(edges) else 0
        node_labeling = np.arange(n_nodes, dtype=np.int64)
        return edges.astype(np.int64), costs, node_labeling
    f = handoff.load_arrays(problem_path(tmp_folder, scale))
    return (
        f["edges"].astype(np.int64),
        f["costs"].astype(np.float64),
        f["node_labeling"].astype(np.int64),
    )


def _octant_node_shards(tmp_folder, cfg, scale, node_labeling, n_nodes, n_shards):
    """Node -> shard assignment by Morton block octants (docs/PERFORMANCE.md
    "Distributed agglomeration"): the *scale-0* blocks (the finest
    geometry the run has — their node sets map through ``node_labeling``
    to current ids, so coarser solve scales shard just as well) are
    ordered along the Z-order curve and split into ``n_shards``
    contiguous runs — each shard an octant-shaped neighborhood of the
    block grid, so the edges crossing shards are (near-)minimal boundary
    faces.  A node appearing in several blocks takes the first
    (lowest-Morton) block's shard — deterministic.  Returns int64
    [n_nodes], or None when the grid has no blocks to shard by."""
    from ..parallel.reduce_tree import morton_argsort

    block_nodes = _scale_block_nodes(tmp_folder, cfg, 0, node_labeling)
    if not block_nodes:
        return None
    shape = handoff.resolve_dataset(cfg["input_path"], cfg["input_key"]).shape
    blocking_s = Blocking(shape, tuple(cfg["block_shape"]))
    ids = sorted(block_nodes)
    pos = np.array([blocking_s.block_grid_position(b) for b in ids])
    order = morton_argsort(pos)
    node_shard = np.full(int(n_nodes), -1, np.int64)
    k = max(1, min(int(n_shards), len(ids)))
    for rank, oi in enumerate(order):
        shard = rank * k // len(ids)
        nodes = block_nodes[ids[oi]]
        if len(nodes) == 0:
            continue
        fresh = nodes[node_shard[nodes] < 0]
        node_shard[fresh] = shard
    node_shard[node_shard < 0] = 0  # nodes outside every block: shard 0
    return node_shard


def _scale_block_nodes(tmp_folder, cfg, scale, node_labeling):
    """Node sets (current ids) per scale-``scale`` block.

    Scale-s blocks are ``block_shape * 2**s``; their node sets come from the
    scale-0 per-block graphs, mapped through the original-label -> dense ->
    current chain."""
    shape = handoff.resolve_dataset(cfg["input_path"], cfg["input_key"]).shape
    block_shape0 = tuple(cfg["block_shape"])
    nodes_table, _, _, _ = load_global_graph(tmp_folder)
    block_shape_s = tuple(b * (2 ** scale) for b in block_shape0)
    blocking_s = Blocking(shape, block_shape_s)
    blocking_0 = Blocking(shape, block_shape0)
    roi = (cfg.get("roi_begin"), cfg.get("roi_end"))
    ids_0 = set(blocks_in_volume(shape, block_shape0, *roi))
    ids_s = blocks_in_volume(shape, block_shape_s, *roi)

    out = {}
    factor = 2 ** scale
    for bs in ids_s:
        pos_s = blocking_s.block_grid_position(bs)
        node_set = []
        # all scale-0 blocks inside this scale-s block
        ranges = [
            range(p * factor, min((p + 1) * factor, g))
            for p, g in zip(pos_s, blocking_0.grid_shape)
        ]
        for pos0 in np.stack(
            np.meshgrid(*ranges, indexing="ij"), axis=-1
        ).reshape(-1, len(ranges)):
            b0 = blocking_0.grid_position_to_id(pos0)
            if b0 not in ids_0:
                continue
            labels = handoff.load_arrays(
                block_graph_path(tmp_folder, b0)
            )["nodes"]
            dense = np.searchsorted(nodes_table, labels)
            node_set.append(node_labeling[dense])
        out[bs] = (
            np.unique(np.concatenate(node_set))
            if node_set
            else np.zeros(0, np.int64)
        )
    return out


def _solver_manifest(energy, edges, labels, solver_delta, tree_delta,
                     solve_info):
    """The observability block every solve task puts in its success
    manifest (ISSUE 9 satellite): objective energy, edges in vs surviving
    inter-cluster edges, contraction round count (numpy-rung exact; the
    native rung is bit-parity but does not report its loop count), and
    the reduce-tree shape when the solve ran sharded.  The same counters
    flow additively into ``io_metrics.json`` via the deltas
    ``BaseTask.run`` merges; ``make failures-report`` renders both."""
    edges = np.asarray(edges)
    labels = np.asarray(labels)
    edges_out = (
        int((labels[edges[:, 0]] != labels[edges[:, 1]]).sum())
        if len(edges) else 0
    )
    out = {
        "energy": float(energy) if energy is not None else None,
        "edges_in": int(len(edges)),
        "edges_out": edges_out,
        "rounds": int(
            (solver_delta or {}).get("solver_rounds", 0)
            + (tree_delta or {}).get("tree_rounds", 0)
        ),
        "solver_calls": int((solver_delta or {}).get("solver_calls", 0)),
    }
    out.update(solve_info or {})
    return out


class SolveSubproblemsBase(BaseTask):
    """Per-block multicut subproblems at one scale (reference:
    ``solve_subproblems.py``).  Params: ``scale``, ``agglomerator`` (solver
    key), plus the graph-defining params (input path/key, block_shape).

    The default subproblem solver is the round-based parallel GAEC
    (:mod:`..ops.contraction`): subproblem quality only seeds the reduce
    step (each scale re-examines the cut), and the vectorized rounds keep
    per-block solves O(rounds) instead of O(E log E) Python heap pops as
    fragment counts approach the 512^3 headline's ~800k."""

    task_name = "solve_subproblems"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "agglomerator": "gaec_parallel",
        }

    def run_impl(self):
        from ..ops import contraction as contraction_mod

        cfg = self.get_config()
        scale = int(cfg.get("scale", 0))
        solver = get_multicut_solver(cfg.get("agglomerator", "gaec_parallel"))
        edges, costs, node_labeling = _load_problem(self.tmp_folder, scale)
        solver_snap = contraction_mod.solver_snapshot()
        block_nodes = _scale_block_nodes(self.tmp_folder, cfg, scale, node_labeling)

        cut = np.zeros(len(edges), dtype=bool)
        seen = np.zeros(len(edges), dtype=bool)

        def process(item):
            block_id, nodes = item
            if len(nodes) < 2:
                return None
            in_set_u = np.isin(edges[:, 0], nodes)
            in_set_v = np.isin(edges[:, 1], nodes)
            sub_mask = in_set_u & in_set_v
            if not sub_mask.any():
                return None
            sub_edges = edges[sub_mask]
            sub_costs = costs[sub_mask]
            # compact node ids for the solver
            sub_nodes, sub_e = np.unique(sub_edges, return_inverse=True)
            sub_e = sub_e.reshape(sub_edges.shape)
            labels = solver(len(sub_nodes), sub_e, sub_costs)
            is_cut = labels[sub_e[:, 0]] != labels[sub_e[:, 1]]
            return sub_mask, is_cut

        with ThreadPoolExecutor(max_workers=max(1, self.max_jobs)) as pool:
            for res in pool.map(process, sorted(block_nodes.items())):
                if res is None:
                    continue
                sub_mask, is_cut = res
                idx = np.flatnonzero(sub_mask)
                seen[idx] = True
                cut[idx[is_cut]] = True

        # an edge merges only if some subproblem saw it and none cut it;
        # edges outside every subproblem (e.g. spanning block boundaries)
        # stay for the next scale / the global solve
        self.save_handoff_arrays(
            cut_edges_path(self.tmp_folder, scale), cut=cut, seen=seen
        )
        sd = contraction_mod.solver_delta(solver_snap)
        return {
            "scale": scale,
            "n_subproblems": len(block_nodes),
            "n_cut": int(cut.sum()),
            "n_edges": len(edges),
            # per-scale solver attribution: the subproblem solves' rounds
            # and edge movement (numpy-rung rounds; see _solver_manifest)
            "solver": {
                "solver_calls": int(sd.get("solver_calls", 0)),
                "rounds": int(sd.get("solver_rounds", 0)),
                "edges_in": int(sd.get("solver_edges_in", 0)),
                "edges_out": int(sd.get("solver_edges_out", 0)),
            },
        }


class SolveSubproblemsLocal(SolveSubproblemsBase):
    target = "local"


class SolveSubproblemsTPU(SolveSubproblemsBase):
    target = "tpu"


class ReduceProblemBase(BaseTask):
    """Contract all edges no subproblem cut -> problem at scale+1
    (reference: ``reduce_problem.py``)."""

    task_name = "reduce_problem"

    def run_impl(self):
        cfg = self.get_config()
        scale = int(cfg.get("scale", 0))
        edges, costs, node_labeling = _load_problem(self.tmp_folder, scale)
        f = handoff.load_arrays(cut_edges_path(self.tmp_folder, scale))
        cut, seen = f["cut"], f["seen"]
        n_nodes = int(node_labeling.max()) + 1 if len(node_labeling) else 0

        from ..ops.unionfind import union_find_host

        merge_pairs = edges[seen & ~cut]
        roots = union_find_host(merge_pairs, n_nodes)
        _, new_ids = np.unique(roots, return_inverse=True)
        new_ids = new_ids.astype(np.int64)

        new_edges, new_costs = contract_graph(edges, costs, new_ids)
        new_labeling = new_ids[node_labeling]
        self.save_handoff_arrays(
            problem_path(self.tmp_folder, scale + 1),
            edges=new_edges,
            costs=new_costs,
            node_labeling=new_labeling,
        )
        return {
            "scale": scale,
            "n_nodes": int(new_ids.max()) + 1 if len(new_ids) else 0,
            "n_edges": len(new_edges),
        }


class ReduceProblemLocal(ReduceProblemBase):
    target = "local"


class ReduceProblemTPU(ReduceProblemBase):
    target = "tpu"


class SolveGlobalBase(BaseTask):
    """Solve the final reduced problem and emit the node-assignment table
    (reference: ``solve_global.py``).  Params: ``scale`` (the final level),
    ``agglomerator``.

    With ``solver_shards > 1`` (docs/PERFORMANCE.md "Distributed
    agglomeration") the solve shards over the Morton-octant reduce tree
    (:mod:`..parallel.reduce_tree`): frontier-aware contraction rounds per
    shard, boundary edges merged up a ``reduce_fanout``-ary tree —
    in-process, or over a ``solver_workers``-process multihost worker
    group.  The configured ``agglomerator`` stays the single-host solver
    (the degenerate ``solver_shards=1`` case AND the
    ``degraded:unsharded_solve`` fallback); the sharded path always runs
    the round-based contraction engine, whose frontier abstention is what
    bounds the energy gap (``make bench-solve``)."""

    task_name = "solve_global"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "agglomerator": "kernighan-lin",
        }

    def run_impl(self):
        from ..ops import contraction as contraction_mod
        from ..parallel import reduce_tree as reduce_tree_mod

        cfg = self.get_config()
        scale = int(cfg.get("scale", 0))
        solver = get_multicut_solver(cfg.get("agglomerator", "kernighan-lin"))
        edges, costs, node_labeling = _load_problem(self.tmp_folder, scale)
        n_nodes = int(node_labeling.max()) + 1 if len(node_labeling) else 0
        shards = int(cfg.get("solver_shards", 1) or 1)
        solver_snap = contraction_mod.solver_snapshot()
        tree_snap = reduce_tree_mod.solve_snapshot()

        def unsharded():
            # preemption safety (SURVEY.md §5.3): checkpoint-capable
            # solvers persist their partition every outer sweep; a killed
            # run resumes mid-solve instead of restarting the global solve
            # from scratch
            ckpt = None
            solver_kw = {}
            if getattr(solver, "supports_checkpoint", False) and len(edges):
                from ..ops.multicut import SolverCheckpoint

                ckpt = SolverCheckpoint(
                    os.path.join(
                        mc_dir(self.tmp_folder),
                        f"solve_global_s{scale}.ckpt.npz",
                    ),
                    edges,
                    costs,
                )
                solver_kw["checkpoint"] = ckpt
            labels = (
                solver(n_nodes, edges, costs, **solver_kw)
                if len(edges)
                else np.zeros(n_nodes, np.int64)
            )
            if ckpt is not None:
                ckpt.clear()
            return labels

        if shards > 1 and len(edges):
            # partition as a thunk: building it re-opens block geometry,
            # and any failure there must degrade, not fail the task
            labels, solve_info = reduce_tree_mod.solve_with_reduce_tree(
                n_nodes, edges, costs,
                node_shard=lambda: _octant_node_shards(
                    self.tmp_folder, cfg, scale, node_labeling, n_nodes,
                    shards,
                ),
                solver_shards=shards,
                fanout=int(cfg.get("reduce_fanout", 2) or 2),
                failures_path=self.failures_path,
                task_name=self.uid,
                unsharded=unsharded,
                workers=int(cfg.get("solver_workers", 1) or 1),
                scratch_dir=os.path.join(mc_dir(self.tmp_folder), "reduce_tree"),
                max_workers=max(1, self.max_jobs),
                # collective reduce plane knobs (docs/PERFORMANCE.md):
                # auto rides device collectives when eligible, collective
                # demands them (degrades attributed), packet never does
                reduce_plane=str(cfg.get("reduce_plane", "auto") or "auto"),
                hop_deadline_s=cfg.get("hop_deadline_s"),
            )
        else:
            labels = unsharded()
            solve_info = {"sharded": False, "shards": 1}
        final = labels[node_labeling]  # original dense node -> segment
        nodes_table, _, edges0, _ = load_global_graph(self.tmp_folder)
        energy = multicut_energy(
            edges0.astype(np.int64),
            handoff.load_array(costs_path(self.tmp_folder)).astype(np.float64),
            final,
        )
        self.save_handoff_arrays(
            assignments_path(self.tmp_folder),
            keys=nodes_table,
            values=(final + 1).astype(np.uint64),
        )
        # the solve is no longer a black box: energy, contraction rounds,
        # and edge movement land in the manifest (and, via the counter
        # deltas BaseTask.run merges, in io_metrics.json)
        return {
            "n_segments": int(final.max()) + 1 if len(final) else 0,
            "energy": energy,
            "solver": _solver_manifest(
                energy, edges, labels,
                contraction_mod.solver_delta(solver_snap),
                reduce_tree_mod.solve_delta(tree_snap),
                solve_info,
            ),
        }


class SolveGlobalLocal(SolveGlobalBase):
    target = "local"


class SolveGlobalTPU(SolveGlobalBase):
    target = "tpu"


class MulticutWorkflow(WorkflowBase):
    """The scale loop + global solve, given graph/features/costs artifacts.

    Params: ``n_scales`` (subproblem levels, default 1), ``agglomerator``,
    plus graph params (``input_path/input_key`` = supervoxels,
    ``block_shape``)."""

    task_name = "multicut_workflow"

    def requires(self):
        from . import multicut as mc_mod
        from ..runtime.task import get_task_cls

        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        p = self.params
        n_scales = int(p.get("n_scales", 1))
        keys = {
            k: p[k]
            for k in (
                "input_path",
                "input_key",
                "block_shape",
                "roi_begin",
                "roi_end",
                "agglomerator",
                "solver_shards",
                "reduce_fanout",
                "solver_workers",
                "reduce_plane",
                "hop_deadline_s",
            )
            if k in p
        }
        deps = list(self.dependencies)
        for s in range(n_scales):
            t_solve = get_task_cls(mc_mod, "SolveSubproblems", self.target)(
                **common, dependencies=deps, scale=s, **keys
            )
            t_reduce = get_task_cls(mc_mod, "ReduceProblem", self.target)(
                **common, dependencies=[t_solve], scale=s, **keys
            )
            deps = [t_reduce]
        t_global = get_task_cls(mc_mod, "SolveGlobal", self.target)(
            **common, dependencies=deps, scale=n_scales, **keys
        )
        return [t_global]
