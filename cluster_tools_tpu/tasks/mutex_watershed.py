"""Blockwise mutex watershed over affinity maps.

Re-design of the reference's ``cluster_tools/mutex_watershed/`` (SURVEY.md
§2a): per-block MWS on affinities with offset vectors (+halo), globally
unique labels via block-offset encoding, optional mask.  Cross-block
consistency comes from the stitching tasks (:mod:`.stitching`) — the
rebuild's equivalent of the reference's two-pass variant: faces are merged
by the mean attractive affinity between the adjacent labels, then a
union-find assignment is applied blockwise.

Params: ``input_path/input_key`` (affinities, leading channel axis),
``output_path/output_key``, ``offsets`` (list of int vectors, first ndim
must be the unit offsets), ``strides``, optional ``mask_path/mask_key``,
``halo``.
"""

from __future__ import annotations

import numpy as np

from ..ops.mws import mutex_watershed
from ..runtime.executor import region_verifier
from ..runtime import handoff
from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


DEFAULT_OFFSETS = [
    [-1, 0, 0], [0, -1, 0], [0, 0, -1],
    [-2, 0, 0], [0, -3, 0], [0, 0, -3],
    [-3, -3, 0], [-3, 0, -3], [0, -3, -3],
]


class MwsBlocksBase(BaseTask):
    """Per-block mutex watershed (reference: ``MwsBlocksBase``)."""

    task_name = "mws_blocks"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "offsets": DEFAULT_OFFSETS,
            "strides": None,
            "halo": [4, 4, 4],
        }

    def run_impl(self):
        cfg = self.get_config()
        # fusable input edge: resolve a live in-memory affinity handle
        ds_in = handoff.resolve_dataset(cfg["input_path"], cfg["input_key"])
        offsets = [list(map(int, o)) for o in cfg.get("offsets") or DEFAULT_OFFSETS]
        shape = ds_in.shape[1:]
        ndim = len(shape)
        for off in offsets[:ndim]:
            if sum(abs(o) for o in off) != 1:
                raise ValueError(
                    f"offsets[:{ndim}] must be unit (attractive) offsets, got {off}"
                )
        block_shape = tuple(cfg["block_shape"])
        halo = tuple(cfg.get("halo") or [0] * ndim)
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        out = file_reader(cfg["output_path"]).require_dataset(
            cfg["output_key"], shape=shape, chunks=block_shape, dtype="uint64"
        )
        mask_ds = None
        if cfg.get("mask_path"):
            mask_ds = file_reader(cfg["mask_path"])[cfg["mask_key"]]
        strides = cfg.get("strides")
        n_outer = int(
            np.prod([b + 2 * h for b, h in zip(block_shape, halo)])
        )

        def process(block_id):
            block = blocking.get_block(block_id, halo)
            affs = np.asarray(ds_in[(slice(None),) + block.outer_bb]).astype(
                np.float64
            )
            mask = (
                np.asarray(mask_ds[block.outer_bb]) > 0
                if mask_ds is not None
                else None
            )
            labels = mutex_watershed(affs, offsets, mask=mask, strides=strides)
            inner = labels[block.inner_in_outer_bb]
            glob = np.where(
                inner > 0,
                np.uint64(block.block_id) * np.uint64(n_outer + 1)
                + inner.astype(np.uint64),
                np.uint64(0),
            )
            out[block.bb] = glob

        # hardened host path (docs/ANALYSIS.md CT001): retries, deadline
        # watchdog and Morton schedule come from the task config inside
        # host_block_map; the store verifier re-reads each block's written
        # region against its digest sidecar so a corrupt chunk is repaired
        # by the retry re-run while this task still owns the block
        n = self.host_block_map(
            block_ids, process,
            store_verify_fn=region_verifier(out), blocking=blocking,
        )
        return {"n_blocks": n}


class MwsBlocksLocal(MwsBlocksBase):
    target = "local"


class MwsBlocksTPU(MwsBlocksBase):
    target = "tpu"


class MwsWorkflow(WorkflowBase):
    """MWS blocks, then affinity-consensus stitching + relabel (the
    cross-block-consistency pass; reference: ``TwoPassMws`` / MWS stitching
    workflows).  Set ``stitch=False`` for independent blocks only."""

    task_name = "mws_workflow"

    def requires(self):
        from . import mutex_watershed as mws_mod
        from .stitching import StitchingWorkflow

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        t1 = get_task_cls(mws_mod, "MwsBlocks", self.target)(
            **common,
            dependencies=self.dependencies,
            **{
                k: p[k]
                for k in (
                    "input_path",
                    "input_key",
                    "output_path",
                    "output_key",
                    "offsets",
                    "strides",
                    "halo",
                    "mask_path",
                    "mask_key",
                    "block_shape",
                    "roi_begin",
                    "roi_end",
                )
                if k in p
            },
        )
        if not p.get("stitch", True):
            return [t1]
        stitch = StitchingWorkflow(
            **common,
            target=self.target,
            dependencies=[t1],
            seg_path=p["output_path"],
            seg_key=p["output_key"],
            input_path=p["input_path"],
            input_key=p["input_key"],
            # score each face by the attractive channel along its axis;
            # high affinity = merge
            axis_channels=list(range(3)),
            merge_mode="greater",
            **{
                k: p[k]
                for k in ("stitch_threshold", "block_shape", "roi_begin", "roi_end")
                if k in p
            },
        )
        return [stitch]
