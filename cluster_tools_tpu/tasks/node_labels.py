"""Node labels: map segment ids to labels of an overlapping volume.

Re-design of the reference's ``cluster_tools/node_labels/`` (SURVEY.md §2a):
``block_node_labels.py`` counted (segment, overlap-label) co-occurrences per
block; ``merge_node_labels.py`` summed the votes and assigned each segment
its max-overlap label.  Typical uses: transfer ground-truth ids onto
supervoxels, or semantic classes onto segments.

Artifacts: ``node_labels/block_<id>.npz`` {pairs [m, 2], counts [m]} and the
final write-task-compatible table ``node_labels/node_labels.npz``
(sorted uint64 ``keys`` = segment ids, ``values`` = max-overlap label).
"""

from __future__ import annotations

import os

import numpy as np

from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


def _nl_dir(tmp_folder: str, name: str = "node_labels") -> str:
    # per-task-name parts dir: the contingency-table task reuses this
    # machinery and must not collide with a node-labels run in the same
    # tmp_folder
    d = os.path.join(tmp_folder, name)
    os.makedirs(d, exist_ok=True)
    return d


def node_labels_path(tmp_folder: str) -> str:
    return os.path.join(_nl_dir(tmp_folder), "node_labels.npz")


def parts_dir_for(task) -> str:
    """Parts dir of a block-vote task, keyed by its task_name."""
    return _nl_dir(task.tmp_folder, task.task_name + "_parts")


def overlap_votes(seg: np.ndarray, overlap: np.ndarray, ignore_overlap_zero=True):
    """Co-occurrence counts of (segment id, overlap label) pairs."""
    m = seg != 0
    if ignore_overlap_zero:
        m &= overlap != 0
    pairs = np.stack([seg[m].ravel(), overlap[m].ravel()], axis=1)
    if len(pairs) == 0:
        return np.zeros((0, 2), np.uint64), np.zeros(0, np.int64)
    uv, counts = np.unique(pairs.astype(np.uint64), axis=0, return_counts=True)
    return uv, counts.astype(np.int64)


class BlockNodeLabelsBase(BaseTask):
    """Per-block overlap votes (reference: ``block_node_labels.py``).

    Params: ``input_path/input_key`` (segments), ``labels_path/labels_key``
    (the overlapping label volume); ``ignore_overlap_zero`` (default True:
    background of the overlap volume casts no votes).
    """

    task_name = "block_node_labels"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "ignore_overlap_zero": True,
        }

    def run_impl(self):
        cfg = self.get_config()
        ds_seg = file_reader(cfg["input_path"])[cfg["input_key"]]
        ds_lab = file_reader(cfg["labels_path"])[cfg["labels_key"]]
        shape = ds_seg.shape
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        ignore0 = bool(cfg.get("ignore_overlap_zero", True))
        d = parts_dir_for(self)

        def process(block_id):
            bb = blocking.get_block(block_id).bb
            uv, counts = overlap_votes(
                np.asarray(ds_seg[bb]), np.asarray(ds_lab[bb]), ignore0
            )
            np.savez(os.path.join(d, f"block_{block_id}.npz"), pairs=uv, counts=counts)

        n = self.host_block_map(block_ids, process)
        return {"n_blocks": n}


class BlockNodeLabelsLocal(BlockNodeLabelsBase):
    target = "local"


class BlockNodeLabelsTPU(BlockNodeLabelsBase):
    target = "tpu"


class MergeNodeLabelsBase(BaseTask):
    """Sum votes and take the max-overlap label per segment (reference:
    ``merge_node_labels.py``)."""

    task_name = "merge_node_labels"

    def run_impl(self):
        cfg = self.get_config()
        shape = file_reader(cfg["input_path"])[cfg["input_key"]].shape
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = _nl_dir(self.tmp_folder, "block_node_labels_parts")
        all_pairs, all_counts = [], []
        for b in block_ids:
            p = os.path.join(d, f"block_{b}.npz")
            if os.path.exists(p):
                with np.load(p) as f:
                    all_pairs.append(f["pairs"])
                    all_counts.append(f["counts"])
        if not all_pairs or not sum(len(p) for p in all_pairs):
            np.savez(
                node_labels_path(self.tmp_folder),
                keys=np.zeros(0, np.uint64),
                values=np.zeros(0, np.uint64),
            )
            return {"n_segments": 0}
        pairs = np.concatenate([p for p in all_pairs if len(p)])
        counts = np.concatenate([c for c in all_counts if len(c)])
        uv, inv = np.unique(pairs, axis=0, return_inverse=True)
        votes = np.zeros(len(uv), np.int64)
        np.add.at(votes, inv.ravel(), counts)
        # per segment, pick the overlap label with the most votes; ties
        # break to the smaller label (stable through the lexsorted uv order)
        seg_ids, seg_start = np.unique(uv[:, 0], return_index=True)
        values = np.zeros(len(seg_ids), np.uint64)
        bounds = np.append(seg_start, len(uv))
        for i in range(len(seg_ids)):
            sl = slice(bounds[i], bounds[i + 1])
            values[i] = uv[sl][np.argmax(votes[sl]), 1]
        np.savez(
            node_labels_path(self.tmp_folder), keys=seg_ids, values=values
        )
        return {"n_segments": int(len(seg_ids))}


class MergeNodeLabelsLocal(MergeNodeLabelsBase):
    target = "local"


class MergeNodeLabelsTPU(MergeNodeLabelsBase):
    target = "tpu"


class NodeLabelWorkflow(WorkflowBase):
    """block_node_labels -> merge_node_labels."""

    task_name = "node_label_workflow"

    def requires(self):
        from . import node_labels as nl_mod

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        kw = {
            k: p[k]
            for k in (
                "input_path",
                "input_key",
                "labels_path",
                "labels_key",
                "ignore_overlap_zero",
                "block_shape",
                "roi_begin",
                "roi_end",
            )
            if k in p
        }
        t1 = get_task_cls(nl_mod, "BlockNodeLabels", self.target)(
            **common, dependencies=self.dependencies, **kw
        )
        t2 = get_task_cls(nl_mod, "MergeNodeLabels", self.target)(
            **common, dependencies=[t1], **kw
        )
        return [t2]
