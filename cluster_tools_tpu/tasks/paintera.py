"""Paintera-style dataset conversion: block-label indexes + pyramid.

Re-design of the reference's ``cluster_tools/paintera/`` (SURVEY.md §2a):
converting a segmentation into the layout interactive proof-reading tools
need — a multiscale label pyramid plus two lookup structures:

- **unique-labels-per-block**: for every block of every scale, the set of
  labels it contains (``unique_labels/s<level>/block_<id>.npy``),
- **label-to-block mapping**: the inverted index label -> block ids
  (``label_to_blocks.npz``: CSR over sorted labels),
- dataset attributes: ``maxId``, ``resolution``, ``offset``.

The pyramid uses mode ("majority-label") downsampling from
:mod:`.downscaling`; the multiset variant is in :mod:`.label_multisets`.
"""

from __future__ import annotations

import os
from typing import List

import numpy as np

from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


def unique_labels_dir(tmp_folder: str, level: int) -> str:
    d = os.path.join(tmp_folder, "unique_labels", f"s{level}")
    os.makedirs(d, exist_ok=True)
    return d


def label_to_blocks_path(tmp_folder: str) -> str:
    return os.path.join(tmp_folder, "label_to_blocks.npz")


class UniqueBlockLabelsBase(BaseTask):
    """Unique labels per block of one dataset (reference:
    ``UniqueBlockLabelsBase``).  Params: ``input_path/input_key``,
    ``level`` (for the artifact path)."""

    task_name = "unique_block_labels"

    def run_impl(self):
        cfg = self.get_config()
        ds = file_reader(cfg["input_path"])[cfg["input_key"]]
        shape = ds.shape
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = unique_labels_dir(self.tmp_folder, int(cfg.get("level", 0)))

        def process(block_id):
            u = np.unique(np.asarray(ds[blocking.get_block(block_id).bb]))
            np.save(os.path.join(d, f"block_{block_id}.npy"), u[u != 0])

        n = self.host_block_map(block_ids, process)
        return {"n_blocks": n}


class UniqueBlockLabelsLocal(UniqueBlockLabelsBase):
    target = "local"


class UniqueBlockLabelsTPU(UniqueBlockLabelsBase):
    target = "tpu"


class LabelBlockMappingBase(BaseTask):
    """Invert the per-block uniques into label -> blocks (reference:
    ``LabelBlockMappingBase``).  CSR artifact over sorted labels."""

    task_name = "label_block_mapping"

    def run_impl(self):
        cfg = self.get_config()
        shape = file_reader(cfg["input_path"])[cfg["input_key"]].shape
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = unique_labels_dir(self.tmp_folder, int(cfg.get("level", 0)))
        pairs_label: List[np.ndarray] = []
        pairs_block: List[np.ndarray] = []
        for b in block_ids:
            p = os.path.join(d, f"block_{b}.npy")
            if not os.path.exists(p):
                continue
            u = np.load(p)
            pairs_label.append(u)
            pairs_block.append(np.full(len(u), b, np.int64))
        if pairs_label:
            labs = np.concatenate(pairs_label)
            blks = np.concatenate(pairs_block)
            order = np.lexsort((blks, labs))
            labs, blks = labs[order], blks[order]
            uniq, starts = np.unique(labs, return_index=True)
            offsets = np.append(starts, len(labs)).astype(np.int64)
        else:
            uniq = np.zeros(0, np.uint64)
            blks = np.zeros(0, np.int64)
            offsets = np.zeros(1, np.int64)
        np.savez(
            label_to_blocks_path(self.tmp_folder),
            labels=uniq,
            offsets=offsets,
            blocks=blks,
        )
        max_id = int(uniq.max()) if len(uniq) else 0
        # stamp paintera-style attributes on the dataset
        ds = file_reader(cfg["input_path"])[cfg["input_key"]]
        ds.update_attrs(
            maxId=max_id,
            resolution=list(cfg.get("resolution") or [1.0] * len(shape)),
            offset=list(cfg.get("offset") or [0.0] * len(shape)),
        )
        return {"n_labels": int(len(uniq)), "maxId": max_id}


class LabelBlockMappingLocal(LabelBlockMappingBase):
    target = "local"


class LabelBlockMappingTPU(LabelBlockMappingBase):
    target = "tpu"


class PainteraConversionWorkflow(WorkflowBase):
    """segmentation -> label pyramid (mode downsampling) + per-block unique
    labels + label-to-block index + attributes (reference: the paintera
    conversion workflow).

    Params: ``input_path/input_key``, ``output_path``,
    ``output_key_prefix``, ``scale_factors`` (e.g. [[2,2,2],[2,2,2]]),
    ``resolution``, ``offset``."""

    task_name = "paintera_conversion_workflow"

    def requires(self):
        from . import paintera as pt_mod
        from .downscaling import DownscalingWorkflow

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        bs = {k: p[k] for k in ("block_shape",) if k in p}
        pyramid = DownscalingWorkflow(
            **common,
            target=self.target,
            dependencies=self.dependencies,
            input_path=p["input_path"],
            input_key=p["input_key"],
            output_path=p["output_path"],
            output_key_prefix=p.get("output_key_prefix", "paintera"),
            scale_factors=p["scale_factors"],
            mode="mode",
            **bs,
        )
        uniq = get_task_cls(pt_mod, "UniqueBlockLabels", self.target)(
            **common,
            dependencies=[pyramid],
            input_path=p["input_path"],
            input_key=p["input_key"],
            level=0,
            **bs,
        )
        mapping = get_task_cls(pt_mod, "LabelBlockMapping", self.target)(
            **common,
            dependencies=[uniq],
            input_path=p["input_path"],
            input_key=p["input_key"],
            level=0,
            **{k: p[k] for k in ("resolution", "offset") if k in p},
            **bs,
        )
        return [mapping]


class PainteraToBdvWorkflow(WorkflowBase):
    """Convert a paintera-style pyramid into a BigDataViewer-layout dataset
    (reference: ``PainteraToBdvWorkflow``): each scale level is copied to
    ``setup0/timepoint0/s<level>`` with bdv ``downsamplingFactors``
    attributes.

    Params: ``input_path``, ``input_key`` (the s0 label dataset),
    ``input_key_prefix`` (the pyramid levels ``<prefix>/s1..sN``, as written
    by :class:`PainteraConversionWorkflow`), ``output_path``,
    ``scale_factors`` (per level), ``resolution``."""

    task_name = "paintera_to_bdv_workflow"

    def requires(self):
        from .copy_volume import CopyVolumeLocal, CopyVolumeTPU
        from . import copy_volume as cv_mod

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        bs = {k: p[k] for k in ("block_shape",) if k in p}
        prefix = p.get("input_key_prefix", "paintera")
        levels = [p["input_key"]] + [
            f"{prefix}/s{i}" for i in range(1, len(p["scale_factors"]) + 1)
        ]
        tasks = []
        deps = list(self.dependencies)
        for level, key in enumerate(levels):
            t = get_task_cls(cv_mod, "CopyVolume", self.target)(
                **common,
                dependencies=deps,
                input_path=p["input_path"],
                input_key=key,
                output_path=p["output_path"],
                output_key=f"setup0/timepoint0/s{level}",
                **bs,
            )
            tasks.append(t)
            deps = [t]
        return tasks

    def run_impl(self):
        p = self.params
        out = file_reader(p["output_path"])
        res = np.asarray(p.get("resolution") or [1.0, 1.0, 1.0], float)
        cum = np.ones(3, int)
        factors = [[1, 1, 1]] + [list(f) for f in p["scale_factors"]]
        for level, f in enumerate(factors):
            cum = cum * np.asarray(f, int)
            ds = out[f"setup0/timepoint0/s{level}"]
            ds.update_attrs(
                downsamplingFactors=[int(x) for x in cum],
                resolution=[float(r * c) for r, c in zip(res, cum)],
            )
        return {"n_levels": len(factors)}
