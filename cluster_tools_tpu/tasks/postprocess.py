"""Postprocessing: size filtering, background filtering, connected components
on an existing segmentation (reference: ``cluster_tools/postprocess/``,
SURVEY.md §2a).  This module currently covers the size-filter family; the
graph-watershed reassignment variant lands with the graph tasks."""

from __future__ import annotations

import os
import numpy as np

from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


def _sizes_dir(tmp_folder):
    d = os.path.join(tmp_folder, "label_sizes")
    os.makedirs(d, exist_ok=True)
    return d


class BlockLabelSizesBase(BaseTask):
    """Per-block label histograms (unique labels + voxel counts)."""

    task_name = "block_label_sizes"

    def run_impl(self):
        cfg = self.get_config()
        ds = file_reader(cfg["input_path"])[cfg["input_key"]]
        shape = ds.shape
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = _sizes_dir(self.tmp_folder)

        def process(block_id):
            labels = ds[blocking.get_block(block_id).bb]
            u, c = np.unique(labels[labels != 0], return_counts=True)
            np.savez(os.path.join(d, f"block_{block_id}.npz"), labels=u, counts=c)

        n = self.host_block_map(block_ids, process)
        return {"n_blocks": n}


class BlockLabelSizesLocal(BlockLabelSizesBase):
    target = "local"


class BlockLabelSizesTPU(BlockLabelSizesBase):
    target = "tpu"


class SizeFilterAssignmentsBase(BaseTask):
    """Merge histograms -> assignment keeping labels with
    ``min_size <= size < max_size`` (others -> 0), optionally relabeled
    consecutively (``relabel=True``, default)."""

    task_name = "size_filter_assignments"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "min_size": 1,
            "max_size": None,
            "relabel": True,
        }

    def run_impl(self):
        cfg = self.get_config()
        shape = file_reader(cfg["input_path"])[cfg["input_key"]].shape
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = _sizes_dir(self.tmp_folder)
        all_labels = {}
        for b in block_ids:
            f = os.path.join(d, f"block_{b}.npz")
            if not os.path.exists(f):
                continue
            with np.load(f) as npz:
                for lab, cnt in zip(npz["labels"], npz["counts"]):
                    all_labels[int(lab)] = all_labels.get(int(lab), 0) + int(cnt)
        keys = np.array(sorted(all_labels), dtype=np.uint64)
        sizes = np.array([all_labels[int(k)] for k in keys], dtype=np.int64)
        min_size = int(cfg.get("min_size") or 1)
        max_size = cfg.get("max_size")
        keep = sizes >= min_size
        if max_size is not None:
            keep &= sizes < int(max_size)
        if cfg.get("relabel", True):
            values = np.zeros(len(keys), np.uint64)
            values[keep] = np.arange(1, int(keep.sum()) + 1, dtype=np.uint64)
        else:
            values = np.where(keep, keys, np.uint64(0))
        np.savez(
            os.path.join(self.tmp_folder, "size_filter_assignments.npz"),
            keys=keys,
            values=values,
        )
        return {
            "n_labels": int(len(keys)),
            "n_kept": int(keep.sum()),
            "n_filtered": int((~keep).sum()),
        }


class SizeFilterAssignmentsLocal(SizeFilterAssignmentsBase):
    target = "local"


class SizeFilterAssignmentsTPU(SizeFilterAssignmentsBase):
    target = "tpu"


class SizeFilterWorkflow(WorkflowBase):
    """sizes -> filter assignment -> write (reference: ``SizeFilterWorkflow``)."""

    task_name = "size_filter_workflow"

    def requires(self):
        from . import postprocess as pp_mod
        from .relabel import staged_write_tasks

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        bs = {k: p[k] for k in ("block_shape",) if k in p}
        io = dict(input_path=p["input_path"], input_key=p["input_key"])
        t1 = get_task_cls(pp_mod, "BlockLabelSizes", self.target)(
            **common, dependencies=self.dependencies, **io, **bs
        )
        t2 = get_task_cls(pp_mod, "SizeFilterAssignments", self.target)(
            **common,
            dependencies=[t1],
            **io,
            **bs,
            **{k: p[k] for k in ("min_size", "max_size", "relabel") if k in p},
        )
        t3 = staged_write_tasks(
            self,
            [t2],
            assignment_path=os.path.join(
                self.tmp_folder, "size_filter_assignments.npz"
            ),
            input_path=p["input_path"],
            input_key=p["input_key"],
            output_path=p.get("output_path", p["input_path"]),
            output_key=p.get("output_key", p["input_key"]),
            stage_name="size_filter",
            bs=bs,
        )
        return [t3]

    def run_impl(self):
        return {}
