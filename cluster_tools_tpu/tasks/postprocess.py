"""Postprocessing: size filtering, background filtering, connected components
on an existing segmentation (reference: ``cluster_tools/postprocess/``,
SURVEY.md §2a).  Covers the size-filter family (threshold + background
filtering), hole filling, connected components on a segmentation, and the
graph-watershed reassignment variant (``GraphWatershedAssignmentsBase`` /
``GraphWatershedSizeFilterWorkflow`` below), which reassigns filtered
fragments to their surviving graph neighbours via seeded watershed on the
region graph instead of discarding them."""

from __future__ import annotations

import os
import numpy as np

from ..runtime.executor import region_verifier
from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


def _sizes_dir(tmp_folder):
    d = os.path.join(tmp_folder, "label_sizes")
    os.makedirs(d, exist_ok=True)
    return d


class BlockLabelSizesBase(BaseTask):
    """Per-block label histograms (unique labels + voxel counts)."""

    task_name = "block_label_sizes"

    def run_impl(self):
        cfg = self.get_config()
        ds = file_reader(cfg["input_path"])[cfg["input_key"]]
        shape = ds.shape
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = _sizes_dir(self.tmp_folder)

        def process(block_id):
            labels = ds[blocking.get_block(block_id).bb]
            u, c = np.unique(labels[labels != 0], return_counts=True)
            np.savez(os.path.join(d, f"block_{block_id}.npz"), labels=u, counts=c)

        n = self.host_block_map(block_ids, process)
        return {"n_blocks": n}


class BlockLabelSizesLocal(BlockLabelSizesBase):
    target = "local"


class BlockLabelSizesTPU(BlockLabelSizesBase):
    target = "tpu"


class SizeFilterAssignmentsBase(BaseTask):
    """Merge histograms -> assignment keeping labels with
    ``min_size <= size < max_size`` (others -> 0), optionally relabeled
    consecutively (``relabel=True``, default)."""

    task_name = "size_filter_assignments"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "min_size": 1,
            "max_size": None,
            "relabel": True,
        }

    def run_impl(self):
        cfg = self.get_config()
        shape = file_reader(cfg["input_path"])[cfg["input_key"]].shape
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = _sizes_dir(self.tmp_folder)
        all_labels = {}
        for b in block_ids:
            f = os.path.join(d, f"block_{b}.npz")
            if not os.path.exists(f):
                continue
            with np.load(f) as npz:
                for lab, cnt in zip(npz["labels"], npz["counts"]):
                    all_labels[int(lab)] = all_labels.get(int(lab), 0) + int(cnt)
        keys = np.array(sorted(all_labels), dtype=np.uint64)
        sizes = np.array([all_labels[int(k)] for k in keys], dtype=np.int64)
        min_size = int(cfg.get("min_size") or 1)
        max_size = cfg.get("max_size")
        keep = sizes >= min_size
        if max_size is not None:
            keep &= sizes < int(max_size)
        if cfg.get("relabel", True):
            values = np.zeros(len(keys), np.uint64)
            values[keep] = np.arange(1, int(keep.sum()) + 1, dtype=np.uint64)
        else:
            values = np.where(keep, keys, np.uint64(0))
        np.savez(
            os.path.join(self.tmp_folder, "size_filter_assignments.npz"),
            keys=keys,
            values=values,
        )
        return {
            "n_labels": int(len(keys)),
            "n_kept": int(keep.sum()),
            "n_filtered": int((~keep).sum()),
        }


class SizeFilterAssignmentsLocal(SizeFilterAssignmentsBase):
    target = "local"


class SizeFilterAssignmentsTPU(SizeFilterAssignmentsBase):
    target = "tpu"


class SizeFilterWorkflow(WorkflowBase):
    """sizes -> filter assignment -> write (reference: ``SizeFilterWorkflow``)."""

    task_name = "size_filter_workflow"

    def requires(self):
        from . import postprocess as pp_mod
        from .relabel import staged_write_tasks

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        bs = {k: p[k] for k in ("block_shape",) if k in p}
        io = dict(input_path=p["input_path"], input_key=p["input_key"])
        t1 = get_task_cls(pp_mod, "BlockLabelSizes", self.target)(
            **common, dependencies=self.dependencies, **io, **bs
        )
        t2 = get_task_cls(pp_mod, "SizeFilterAssignments", self.target)(
            **common,
            dependencies=[t1],
            **io,
            **bs,
            **{k: p[k] for k in ("min_size", "max_size", "relabel") if k in p},
        )
        t3 = staged_write_tasks(
            self,
            [t2],
            assignment_path=os.path.join(
                self.tmp_folder, "size_filter_assignments.npz"
            ),
            input_path=p["input_path"],
            input_key=p["input_key"],
            output_path=p.get("output_path", p["input_path"]),
            output_key=p.get("output_key", p["input_key"]),
            stage_name="size_filter",
            bs=bs,
        )
        return [t3]

    def run_impl(self):
        return {}


class ConnectedComponentsOnSegmentationWorkflow(WorkflowBase):
    """Split every segment into its spatially connected parts (reference:
    the postprocess CC-on-seg task): the blockwise CC chain with the keyed
    kernel — voxels connect only where the segment label matches."""

    task_name = "cc_on_segmentation_workflow"

    def requires(self):
        from .connected_components import ConnectedComponentsWorkflow

        return [
            ConnectedComponentsWorkflow(
                tmp_folder=self.tmp_folder,
                config_dir=self.config_dir,
                max_jobs=self.max_jobs,
                target=self.target,
                dependencies=self.dependencies,
                keyed=True,
                **self.params,
            )
        ]


def _hole_dir(tmp_folder):
    d = os.path.join(tmp_folder, "fill_holes")
    os.makedirs(d, exist_ok=True)
    return d


class HoleVotesBase(BaseTask):
    """Per block: which background components touch the volume border, and
    per (background component, segment) face-contact counts.

    Params: ``input_path/input_key`` (the segmentation), ``cc_path/cc_key``
    (CC labels of the background mask).
    """

    task_name = "hole_votes"

    def run_impl(self):
        cfg = self.get_config()
        seg_ds = file_reader(cfg["input_path"])[cfg["input_key"]]
        cc_ds = file_reader(cfg["cc_path"])[cfg["cc_key"]]
        shape = seg_ds.shape
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = _hole_dir(self.tmp_folder)

        def process(block_id):
            block = blocking.get_block(block_id)
            # +1 upper halo so cross-block contacts are counted once
            bb = tuple(
                slice(b, min(e + 1, s))
                for b, e, s in zip(block.begin, block.end, shape)
            )
            seg = np.asarray(seg_ds[bb])
            cc = np.asarray(cc_ds[bb])
            votes = {}
            for axis in range(seg.ndim):
                sl_a = [slice(0, n) for n in block.shape]
                sl_b = [slice(0, n) for n in block.shape]
                n_ax = min(block.shape[axis] + 1, seg.shape[axis])
                sl_a[axis] = slice(0, n_ax - 1)
                sl_b[axis] = slice(1, n_ax)
                cc_a, cc_b = cc[tuple(sl_a)], cc[tuple(sl_b)]
                sg_a, sg_b = seg[tuple(sl_a)], seg[tuple(sl_b)]
                for hole, lab in ((cc_a, sg_b), (cc_b, sg_a)):
                    m = (hole > 0) & (lab > 0)
                    if m.any():
                        uv, c = np.unique(
                            np.stack([hole[m], lab[m]], 1).astype(np.uint64),
                            axis=0,
                            return_counts=True,
                        )
                        for (h, l), n_votes in zip(uv, c):
                            key = (int(h), int(l))
                            votes[key] = votes.get(key, 0) + int(n_votes)
            # background components on the volume border are not holes
            border = set()
            for axis in range(seg.ndim):
                for edge, face in ((0, block.begin[axis]), (shape[axis], block.end[axis])):
                    if face != edge:
                        continue
                    sl = [slice(0, n) for n in block.shape]
                    sl[axis] = slice(0, 1) if edge == 0 else slice(block.shape[axis] - 1, block.shape[axis])
                    u = np.unique(cc[tuple(sl)])
                    border.update(int(x) for x in u[u > 0])
            pairs = np.array(sorted(votes), np.uint64).reshape(-1, 2)
            counts = np.array([votes[tuple(map(int, p))] for p in pairs], np.int64)
            np.savez(
                os.path.join(d, f"block_{block_id}.npz"),
                pairs=pairs,
                counts=counts,
                border=np.array(sorted(border), np.uint64),
            )

        n = self.host_block_map(block_ids, process)
        return {"n_blocks": n}


class HoleVotesLocal(HoleVotesBase):
    target = "local"


class HoleVotesTPU(HoleVotesBase):
    target = "tpu"


class MergeHoleAssignmentsBase(BaseTask):
    """Merge votes/border sets -> hole fill table (cc label -> segment
    label); border-touching components map to 0 (stay background)."""

    task_name = "merge_hole_assignments"

    def run_impl(self):
        cfg = self.get_config()
        shape = file_reader(cfg["input_path"])[cfg["input_key"]].shape
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = _hole_dir(self.tmp_folder)
        votes = {}
        border = set()
        for b in block_ids:
            p = os.path.join(d, f"block_{b}.npz")
            if not os.path.exists(p):
                continue
            with np.load(p) as f:
                for (h, l), c in zip(f["pairs"], f["counts"]):
                    key = (int(h), int(l))
                    votes[key] = votes.get(key, 0) + int(c)
                border.update(int(x) for x in f["border"])
        fill = {}
        for (h, l), c in votes.items():
            if h in border:
                continue
            if h not in fill or c > fill[h][1]:
                fill[h] = (l, c)
        keys = np.array(sorted(fill), np.uint64)
        values = np.array([fill[int(k)][0] for k in keys], np.uint64)
        np.savez(
            os.path.join(self.tmp_folder, "hole_assignments.npz"),
            keys=keys,
            values=values,
        )
        return {"n_holes": int(len(keys)), "n_border_components": len(border)}


class MergeHoleAssignmentsLocal(MergeHoleAssignmentsBase):
    target = "local"


class MergeHoleAssignmentsTPU(MergeHoleAssignmentsBase):
    target = "tpu"


class FillHolesWriteBase(BaseTask):
    """Apply the hole table: out = seg where seg > 0 else fill[cc]."""

    task_name = "fill_holes_write"

    def run_impl(self):
        from .write import apply_assignment_np

        cfg = self.get_config()
        seg_ds = file_reader(cfg["input_path"])[cfg["input_key"]]
        cc_ds = file_reader(cfg["cc_path"])[cfg["cc_key"]]
        shape = seg_ds.shape
        block_shape = tuple(cfg["block_shape"])
        with np.load(os.path.join(self.tmp_folder, "hole_assignments.npz")) as f:
            keys, values = f["keys"], f["values"]
        out = file_reader(cfg["output_path"]).require_dataset(
            cfg["output_key"], shape=shape, chunks=block_shape, dtype="uint64"
        )
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )

        def process(block_id):
            bb = blocking.get_block(block_id).bb
            seg = np.asarray(seg_ds[bb]).astype(np.uint64)
            cc = np.asarray(cc_ds[bb]).astype(np.uint64)
            filled = apply_assignment_np(cc, keys, values)
            out[bb] = np.where(seg > 0, seg, filled)

        n = self.host_block_map(
            block_ids, process,
            store_verify_fn=region_verifier(out), blocking=blocking,
        )
        return {"n_blocks": n}


class FillHolesWriteLocal(FillHolesWriteBase):
    target = "local"


class FillHolesWriteTPU(FillHolesWriteBase):
    target = "tpu"


class FillHolesWorkflow(WorkflowBase):
    """Fill internal background cavities of a segmentation (reference:
    ``FillingBase``): CC the background mask, classify components touching
    the volume border as true background, vote each enclosed component to
    its majority surrounding segment, write ``seg | filled``.

    Params: ``input_path/input_key`` (segmentation), ``output_path/
    output_key``."""

    task_name = "fill_holes_workflow"

    def requires(self):
        from . import postprocess as pp_mod
        from .connected_components import ConnectedComponentsWorkflow
        from .thresholded_components import ThresholdLocal, ThresholdTPU
        from . import thresholded_components as tc_mod

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        bs = {k: p[k] for k in ("block_shape",) if k in p}
        scratch = os.path.join(self.tmp_folder, "fill_holes.zarr")
        # background mask: seg == 0
        t_mask = get_task_cls(tc_mod, "Threshold", self.target)(
            **common,
            dependencies=self.dependencies,
            input_path=p["input_path"],
            input_key=p["input_key"],
            output_path=scratch,
            output_key="bg_mask",
            threshold=0.5,
            threshold_mode="less",
            **bs,
        )
        t_cc = ConnectedComponentsWorkflow(
            **common,
            target=self.target,
            dependencies=[t_mask],
            input_path=scratch,
            input_key="bg_mask",
            output_path=scratch,
            output_key="bg_cc",
            **bs,
        )
        t_votes = get_task_cls(pp_mod, "HoleVotes", self.target)(
            **common,
            dependencies=[t_cc],
            input_path=p["input_path"],
            input_key=p["input_key"],
            cc_path=scratch,
            cc_key="bg_cc",
            **bs,
        )
        t_merge = get_task_cls(pp_mod, "MergeHoleAssignments", self.target)(
            **common,
            dependencies=[t_votes],
            input_path=p["input_path"],
            input_key=p["input_key"],
            **bs,
        )
        t_write = get_task_cls(pp_mod, "FillHolesWrite", self.target)(
            **common,
            dependencies=[t_merge],
            input_path=p["input_path"],
            input_key=p["input_key"],
            cc_path=scratch,
            cc_key="bg_cc",
            output_path=p["output_path"],
            output_key=p["output_key"],
            **bs,
        )
        return [t_write]


class GraphWatershedAssignmentsBase(BaseTask):
    """Size filter with graph-watershed reassignment (reference: the
    postprocess ``SizeFilterBase`` graph-watershed variant): instead of
    zeroing small objects, each is absorbed by its strongest-connected kept
    neighbor (lowest mean boundary probability edge), iterated so chains of
    small objects resolve to a kept root.

    Requires graph + features artifacts and the label-size histograms in
    the same tmp_folder.  Params: ``min_size``."""

    task_name = "graph_watershed_assignments"

    @staticmethod
    def default_task_config():
        return {"threads_per_job": 1, "device_batch": 1, "min_size": 100}

    def run_impl(self):
        from .features import features_path
        from .graph import load_global_graph

        cfg = self.get_config()
        from ..runtime import handoff

        nodes, _, edges, _ = load_global_graph(self.tmp_folder)
        feats = handoff.load_array(features_path(self.tmp_folder))
        probs = feats[:, 0].astype(np.float64)
        # node sizes from the label-size histograms
        d = _sizes_dir(self.tmp_folder)
        shape = file_reader(cfg["input_path"])[cfg["input_key"]].shape
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        size_of = {}
        for b in block_ids:
            f = os.path.join(d, f"block_{b}.npz")
            if not os.path.exists(f):
                continue
            with np.load(f) as npz:
                for lab, cnt in zip(npz["labels"], npz["counts"]):
                    size_of[int(lab)] = size_of.get(int(lab), 0) + int(cnt)
        sizes = np.array([size_of.get(int(n), 0) for n in nodes], np.int64)
        min_size = int(cfg.get("min_size", 100))
        small = sizes < min_size

        # graph watershed: repeatedly attach small nodes to their best
        # (lowest boundary prob) neighbor that is already kept/absorbed
        n = len(nodes)
        target = np.arange(n, dtype=np.int64)
        resolved = ~small
        adj = [[] for _ in range(n)]
        for (u, v), pr in zip(edges, probs):
            adj[int(u)].append((int(v), pr))
            adj[int(v)].append((int(u), pr))
        changed = True
        while changed:
            changed = False
            for u in np.flatnonzero(small & ~resolved):
                best, best_p = -1, np.inf
                for v, pr in adj[u]:
                    if resolved[v] and pr < best_p:
                        best, best_p = v, pr
                if best >= 0:
                    target[u] = target[best]
                    resolved[u] = True
                    changed = True
        # unresolvable small islands -> background
        values = np.where(
            resolved,
            nodes[target],
            np.uint64(0),
        ).astype(np.uint64)
        np.savez(
            os.path.join(self.tmp_folder, "graph_ws_assignments.npz"),
            keys=nodes,
            values=values,
        )
        return {
            "n_nodes": int(n),
            "n_small": int(small.sum()),
            "n_unresolved": int((small & ~resolved).sum()),
        }


class GraphWatershedAssignmentsLocal(GraphWatershedAssignmentsBase):
    target = "local"


class GraphWatershedAssignmentsTPU(GraphWatershedAssignmentsBase):
    target = "tpu"


class GraphWatershedSizeFilterWorkflow(WorkflowBase):
    """Size filter that reassigns small objects through the RAG instead of
    deleting them: graph + features + sizes -> graph-watershed assignment
    -> write.  Params: ``input_path/input_key`` (segmentation),
    ``boundary_path/boundary_key`` (the map edges are scored by),
    ``min_size``, ``output_path/output_key``."""

    task_name = "graph_ws_size_filter_workflow"

    def requires(self):
        from . import postprocess as pp_mod
        from .features import EdgeFeaturesWorkflow
        from .graph import GraphWorkflow
        from .relabel import staged_write_tasks

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        bs = {k: p[k] for k in ("block_shape",) if k in p}
        g = GraphWorkflow(
            **common,
            target=self.target,
            dependencies=self.dependencies,
            input_path=p["input_path"],
            input_key=p["input_key"],
            **bs,
        )
        feats = EdgeFeaturesWorkflow(
            **common,
            target=self.target,
            dependencies=[g],
            input_path=p["boundary_path"],
            input_key=p["boundary_key"],
            labels_path=p["input_path"],
            labels_key=p["input_key"],
            **bs,
        )
        sizes = get_task_cls(pp_mod, "BlockLabelSizes", self.target)(
            **common,
            dependencies=self.dependencies,
            input_path=p["input_path"],
            input_key=p["input_key"],
            **bs,
        )
        assign = get_task_cls(pp_mod, "GraphWatershedAssignments", self.target)(
            **common,
            dependencies=[feats, sizes],
            input_path=p["input_path"],
            input_key=p["input_key"],
            **{k: p[k] for k in ("min_size",) if k in p},
            **bs,
        )
        write = staged_write_tasks(
            self,
            [assign],
            assignment_path=os.path.join(
                self.tmp_folder, "graph_ws_assignments.npz"
            ),
            input_path=p["input_path"],
            input_key=p["input_key"],
            output_path=p.get("output_path", p["input_path"]),
            output_key=p.get("output_key", p["input_key"]),
            stage_name="graph_ws_filter",
            bs=bs,
        )
        return [write]
