"""Relabeling: make segmentation labels dense/consecutive.

Reference: ``cluster_tools/relabel/`` (SURVEY.md §2a) — ``find_uniques`` (per
block), ``find_labeling`` (merge -> global relabel table), then the generic
``write`` task applies the table.  Our watershed/CC tasks emit globally
unique but sparse uint64 labels (block-offset encodings), so this workflow is
the standard finisher.
"""

from __future__ import annotations

import os
import numpy as np

from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


def _uniques_dir(tmp_folder: str) -> str:
    d = os.path.join(tmp_folder, "relabel_uniques")
    os.makedirs(d, exist_ok=True)
    return d


class FindUniquesBase(BaseTask):
    """Per-block unique labels -> npy files."""

    task_name = "find_uniques"

    def run_impl(self):
        cfg = self.get_config()
        ds = file_reader(cfg["input_path"])[cfg["input_key"]]
        shape = ds.shape
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = _uniques_dir(self.tmp_folder)

        def process(block_id):
            block = blocking.get_block(block_id)
            u = np.unique(ds[block.bb])
            np.save(os.path.join(d, f"block_{block_id}.npy"), u[u != 0])

        n = self.host_block_map(block_ids, process)
        return {"n_blocks": n}


class FindUniquesLocal(FindUniquesBase):
    target = "local"


class FindUniquesTPU(FindUniquesBase):
    target = "tpu"


class FindLabelingBase(BaseTask):
    """Merge per-block uniques -> dense assignment table (labels 1..K)."""

    task_name = "find_labeling"

    def run_impl(self):
        cfg = self.get_config()
        shape = file_reader(cfg["input_path"])[cfg["input_key"]].shape
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = _uniques_dir(self.tmp_folder)
        files = [
            os.path.join(d, f"block_{b}.npy")
            for b in block_ids
            if os.path.exists(os.path.join(d, f"block_{b}.npy"))
        ]
        uniques = (
            np.unique(np.concatenate([np.load(f) for f in files]))
            if files
            else np.zeros(0, np.uint64)
        )
        values = np.arange(1, len(uniques) + 1, dtype=np.uint64)
        np.savez(
            os.path.join(self.tmp_folder, cfg.get("assignment_name", "relabel_assignments") + ".npz"),
            keys=uniques,
            values=values,
        )
        return {"n_labels": int(len(uniques))}


class FindLabelingLocal(FindLabelingBase):
    target = "local"


class FindLabelingTPU(FindLabelingBase):
    target = "tpu"


def staged_write_tasks(
    workflow: WorkflowBase,
    deps,
    assignment_path: str,
    input_path: str,
    input_key: str,
    output_path: str,
    output_key: str,
    stage_name: str,
    bs,
):
    """Build the final Write step, staging the input labels to a scratch
    dataset first when writing in place.

    In-place application is not crash-idempotent at the block grain: a crash
    between a block's data write and its success marker would re-map already
    relabeled values on resume.  Staging the original labels (a blockwise
    copy) keeps Write's input immutable, restoring idempotency — the same
    pattern the CC workflow uses for its provisional labels.
    """
    from . import copy_volume as cv_mod
    from . import write as write_mod

    common = dict(
        tmp_folder=workflow.tmp_folder,
        config_dir=workflow.config_dir,
        max_jobs=workflow.max_jobs,
    )
    in_place = output_path == input_path and output_key == input_key
    if in_place:
        staged_path = os.path.join(workflow.tmp_folder, f"{stage_name}_src.zarr")
        staged_key = "labels"
        t_copy = get_task_cls(cv_mod, "CopyVolume", workflow.target)(
            **common,
            dependencies=deps,
            input_path=input_path,
            input_key=input_key,
            output_path=staged_path,
            output_key=staged_key,
            **bs,
        )
        deps = [t_copy]
        input_path, input_key = staged_path, staged_key
    t_write = get_task_cls(write_mod, "Write", workflow.target)(
        **common,
        dependencies=deps,
        input_path=input_path,
        input_key=input_key,
        output_path=output_path,
        output_key=output_key,
        assignment_path=assignment_path,
        **bs,
    )
    return t_write


class RelabelWorkflow(WorkflowBase):
    """find_uniques -> find_labeling -> write (reference: relabel workflow)."""

    task_name = "relabel_workflow"

    def requires(self):
        from . import relabel as rl_mod

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        bs = {k: p[k] for k in ("block_shape",) if k in p}
        assignment_name = p.get("assignment_name", "relabel_assignments")
        t1 = get_task_cls(rl_mod, "FindUniques", self.target)(
            **common,
            dependencies=self.dependencies,
            input_path=p["input_path"],
            input_key=p["input_key"],
            **bs,
        )
        t2 = get_task_cls(rl_mod, "FindLabeling", self.target)(
            **common,
            dependencies=[t1],
            input_path=p["input_path"],
            input_key=p["input_key"],
            assignment_name=assignment_name,
            **bs,
        )
        t3 = staged_write_tasks(
            self,
            [t2],
            assignment_path=os.path.join(self.tmp_folder, assignment_name + ".npz"),
            input_path=p["input_path"],
            input_key=p["input_key"],
            output_path=p.get("output_path", p["input_path"]),
            output_key=p.get("output_key", p["input_key"]),
            stage_name="relabel",
            bs=bs,
        )
        return [t3]

    def run_impl(self):
        return {}
