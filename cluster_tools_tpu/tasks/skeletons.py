"""Blockwise object skeletonization + SWC export.

Re-design of the reference's ``cluster_tools/skeletons/`` (SURVEY.md §2a:
blockwise skeletonization + swc/n5 export, via elf/skan).  The rebuild
derives skeletons from medial-axis structure instead of voxel thinning.
Objects are skeletonized per bounding-box crop on the host (scipy EDT —
crops are small and irregular, a poor fit for the device's fixed-shape
EDT cascade):

1. per object: Euclidean distance transform of the bbox crop (host scipy),
2. medial nodes = EDT local maxima inside the object,
3. topology = minimum spanning tree over the medial nodes (edge weight =
   euclidean distance, edges only between nodes within ``link_radius``),
   rooted at the node of maximal EDT.

This yields the skeleton *graph* downstream consumers use (path lengths,
branch topology, radius estimates) without a voxel-thinning pass; radii come
for free from the EDT value at each node.

Artifacts: ``skeletons/<id>.npz`` {nodes [n, 3+1] (z, y, x, radius),
edges [m, 2]} and optional ``<id>.swc``.
"""

from __future__ import annotations

import os

import numpy as np

from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import file_reader
from .morphology import MorphologyWorkflow, morphology_path


def skeleton_dir(tmp_folder: str) -> str:
    d = os.path.join(tmp_folder, "skeletons")
    os.makedirs(d, exist_ok=True)
    return d


def skeletonize_object(
    mask: np.ndarray,
    offset=(0, 0, 0),
    sampling=(1.0, 1.0, 1.0),
    link_radius: float = 10.0,
):
    """Skeletonize one binary object: returns (nodes [n, 4], edges [m, 2]).

    Node columns: z, y, x (global coords) and the medial radius (EDT).
    """
    from scipy import ndimage

    if not mask.any():
        return np.zeros((0, 4)), np.zeros((0, 2), np.int64)
    # pad with one background voxel: beyond the object's bounding box is
    # background, otherwise an object filling its bbox has no EDT zero set
    mask_p = np.pad(mask, 1)
    edt = ndimage.distance_transform_edt(mask_p, sampling=sampling)
    # medial nodes: local maxima of the EDT on the object
    mx = ndimage.maximum_filter(edt, size=3)
    medial = (edt >= mx - 1e-9) & mask_p
    coords = np.argwhere(medial).astype(np.float64) - 1.0
    radii = edt[medial]
    if len(coords) == 0:
        coords = np.argwhere(mask)[:1].astype(np.float64)
        radii = np.array([1.0])
    nodes = np.concatenate(
        [coords + np.asarray(offset, np.float64), radii[:, None]], axis=1
    )
    # MST over medial nodes (kd-tree neighborhood graph)
    n = len(coords)
    if n == 1:
        return nodes, np.zeros((0, 2), np.int64)
    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import minimum_spanning_tree, connected_components
    from scipy.spatial import cKDTree

    world = coords * np.asarray(sampling)
    tree = cKDTree(world)
    pairs = tree.query_pairs(r=float(link_radius), output_type="ndarray")
    if len(pairs) == 0:
        # fall back to nearest-neighbor linkage so the graph is connected
        d, j = tree.query(world, k=2)
        pairs = np.stack([np.arange(n), j[:, 1]], axis=1)
    d = np.linalg.norm(world[pairs[:, 0]] - world[pairs[:, 1]], axis=1)
    g = coo_matrix((d, (pairs[:, 0], pairs[:, 1])), shape=(n, n))
    mst = minimum_spanning_tree(g).tocoo()
    edges = np.stack([mst.row, mst.col], axis=1).astype(np.int64)
    return nodes, edges


def write_swc(path: str, nodes: np.ndarray, edges: np.ndarray):
    """Export a skeleton as SWC (id, type, x, y, z, radius, parent)."""
    n = len(nodes)
    parent = np.full(n, -1, np.int64)
    # orient every connected component from its thickest node (the MST may
    # be a forest when medial clusters are farther apart than link_radius)
    if n:
        adj = [[] for _ in range(n)]
        for a, b in edges:
            adj[a].append(b)
            adj[b].append(a)
        seen = set()
        order = np.argsort(-nodes[:, 3])  # thickest first
        for root in order:
            root = int(root)
            if root in seen:
                continue
            seen.add(root)
            stack = [root]
            while stack:
                cur = stack.pop()
                for nb in adj[cur]:
                    if nb not in seen:
                        seen.add(nb)
                        parent[nb] = cur
                        stack.append(nb)
    with open(path, "w") as f:
        f.write("# id type x y z radius parent\n")
        for i, (z, y, x, r) in enumerate(nodes):
            p = parent[i]
            f.write(
                f"{i + 1} 0 {x:.2f} {y:.2f} {z:.2f} {r:.3f} "
                f"{p + 1 if p >= 0 else -1}\n"
            )


class SkeletonizeBase(BaseTask):
    """Skeletonize objects using the morphology table's bounding boxes
    (reference: ``SkeletonizeBase``).  Params: ``input_path/input_key``
    (segmentation), optional ``object_ids`` (default: all), ``sampling``
    (voxel size), ``link_radius``, ``min_size``, ``export_swc``."""

    task_name = "skeletonize"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "sampling": [1.0, 1.0, 1.0],
            "link_radius": 10.0,
            "min_size": 1,
            "export_swc": False,
            "object_ids": None,
        }

    def run_impl(self):
        cfg = self.get_config()
        ds = file_reader(cfg["input_path"])[cfg["input_key"]]
        with np.load(morphology_path(self.tmp_folder)) as f:
            ids, sizes = f["ids"], f["sizes"]
            bb_min, bb_max = f["bb_min"], f["bb_max"]
        wanted = cfg.get("object_ids")
        min_size = int(cfg.get("min_size") or 1)
        sel = sizes >= min_size
        if wanted is not None:
            sel &= np.isin(ids, np.asarray(wanted, dtype=ids.dtype))
        sampling = tuple(cfg.get("sampling") or (1.0, 1.0, 1.0))
        link_radius = float(cfg.get("link_radius", 10.0))
        export_swc = bool(cfg.get("export_swc", False))
        d = skeleton_dir(self.tmp_folder)

        todo = [int(i) for i in np.flatnonzero(sel)]

        def process(idx):
            obj = ids[idx]
            lo, hi = bb_min[idx], bb_max[idx]
            bb = tuple(slice(int(a), int(b)) for a, b in zip(lo, hi))
            mask = np.asarray(ds[bb]) == obj
            nodes, edges = skeletonize_object(
                mask, offset=lo, sampling=sampling, link_radius=link_radius
            )
            np.savez(os.path.join(d, f"{int(obj)}.npz"), nodes=nodes, edges=edges)
            if export_swc:
                write_swc(os.path.join(d, f"{int(obj)}.swc"), nodes, edges)

        # object index doubles as the "block" id for resume markers
        n = self.host_block_map(todo, process)
        return {"n_objects": n}


class SkeletonizeLocal(SkeletonizeBase):
    target = "local"


class SkeletonizeTPU(SkeletonizeBase):
    target = "tpu"


class SkeletonWorkflow(WorkflowBase):
    """morphology (for bounding boxes) -> skeletonize."""

    task_name = "skeleton_workflow"

    def requires(self):
        from . import skeletons as sk_mod

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        grid = {
            k: p[k]
            for k in ("input_path", "input_key", "block_shape", "roi_begin", "roi_end")
            if k in p
        }
        morph = MorphologyWorkflow(
            **common, target=self.target, dependencies=self.dependencies, **grid
        )
        sk = get_task_cls(sk_mod, "Skeletonize", self.target)(
            **common,
            dependencies=[morph],
            **grid,
            **{
                k: p[k]
                for k in (
                    "sampling",
                    "link_radius",
                    "min_size",
                    "export_swc",
                    "object_ids",
                )
                if k in p
            },
        )
        return [sk]
