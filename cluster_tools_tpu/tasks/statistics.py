"""Blockwise data statistics (reference: ``cluster_tools/statistics/``,
SURVEY.md §2a): per-block partial moments + a merge pass -> global
min/max/mean/std, written to the success manifest and a JSON artifact."""

from __future__ import annotations

import json
import os
import numpy as np

from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils import function_utils as fu
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


def _stats_dir(tmp_folder):
    d = os.path.join(tmp_folder, "block_statistics")
    os.makedirs(d, exist_ok=True)
    return d


class BlockStatisticsBase(BaseTask):
    task_name = "block_statistics"

    def run_impl(self):
        cfg = self.get_config()
        ds = file_reader(cfg["input_path"])[cfg["input_key"]]
        shape = ds.shape
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = _stats_dir(self.tmp_folder)

        def process(block_id):
            data = ds[blocking.get_block(block_id).bb].astype(np.float64)
            np.save(
                os.path.join(d, f"block_{block_id}.npy"),
                np.array(
                    [data.size, data.sum(), (data**2).sum(), data.min(), data.max()]
                ),
            )

        n = self.host_block_map(block_ids, process)
        return {"n_blocks": n}


class BlockStatisticsLocal(BlockStatisticsBase):
    target = "local"


class BlockStatisticsTPU(BlockStatisticsBase):
    target = "tpu"


class MergeStatisticsBase(BaseTask):
    task_name = "merge_statistics"

    def run_impl(self):
        cfg = self.get_config()
        shape = file_reader(cfg["input_path"])[cfg["input_key"]].shape
        block_ids = blocks_in_volume(
            shape, tuple(cfg["block_shape"]), cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = _stats_dir(self.tmp_folder)
        parts = np.stack(
            [
                np.load(os.path.join(d, f"block_{b}.npy"))
                for b in block_ids
                if os.path.exists(os.path.join(d, f"block_{b}.npy"))
            ]
        )
        n = parts[:, 0].sum()
        s1, s2 = parts[:, 1].sum(), parts[:, 2].sum()
        mean = s1 / n
        var = max(s2 / n - mean**2, 0.0)
        stats = {
            "count": float(n),
            "mean": float(mean),
            "std": float(np.sqrt(var)),
            "min": float(parts[:, 3].min()),
            "max": float(parts[:, 4].max()),
        }
        # atomic (CT002): the report is a shared tmp_folder manifest
        fu.atomic_write_json(
            os.path.join(self.tmp_folder, "statistics.json"), stats
        )
        return stats


class MergeStatisticsLocal(MergeStatisticsBase):
    target = "local"


class MergeStatisticsTPU(MergeStatisticsBase):
    target = "tpu"


class DataStatisticsWorkflow(WorkflowBase):
    task_name = "data_statistics_workflow"

    def requires(self):
        from . import statistics as st_mod

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        kw = {
            k: p[k]
            for k in ("input_path", "input_key", "block_shape")
            if k in p
        }
        t1 = get_task_cls(st_mod, "BlockStatistics", self.target)(
            **common, dependencies=self.dependencies, **kw
        )
        t2 = get_task_cls(st_mod, "MergeStatistics", self.target)(
            **common, dependencies=[t1], **kw
        )
        return [t2]

    def run_impl(self):
        return {}
