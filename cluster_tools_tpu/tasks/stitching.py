"""Stitch independent per-block segmentations into consistent labels.

Re-design of the reference's ``cluster_tools/stitching/`` (SURVEY.md §2a):
the reference offered face-consensus assignments and stitch-via-multicut on
the block-boundary graph.  Both are provided here:

- **Face consensus** (:class:`StitchFacesBase` + union-find merge): for each
  adjacent block face, accumulate per label-pair the mean value of an
  underlying map (boundary probability or attractive affinity) over the
  face contacts; pairs passing the threshold merge (union-find), and the
  assignment is applied blockwise by the generic write task.
- **Stitch-via-multicut**: build the block-boundary RAG with the graph +
  features tasks on the *stitched-input* segmentation and run the multicut
  chain — that is exactly the existing GraphWorkflow/MulticutWorkflow
  composition, so it needs no extra code here (see
  ``MulticutSegmentationWorkflow`` with ``skip_ws=True``).

Criterion semantics: ``merge_mode='less'`` (default) merges a face pair if
its mean map value is *below* ``stitch_threshold`` (boundary-map
convention); ``'greater'`` merges above (affinity convention, used by the
MWS workflow with the attractive channels averaged).
``merge_mode='multicut'`` replaces the per-pair threshold with a global
solve: face-pair means become signed costs (``probs_to_costs`` with
``beta = 1 - stitch_threshold``, so a pair is attractive exactly when its
mean is below the threshold) and the round-based parallel GAEC
(:mod:`..ops.contraction`) decides the merges — connectivity-aware
stitching where a borderline face merges only if the contraction chain
around it is net-attractive, the cheap in-task form of the reference's
stitch-via-multicut.
"""

from __future__ import annotations

import os

import numpy as np

from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader
from .features import _read_boundary_map


def _stitch_dir(tmp_folder: str) -> str:
    d = os.path.join(tmp_folder, "stitch_faces")
    os.makedirs(d, exist_ok=True)
    return d


def stitch_assignments_path(tmp_folder: str) -> str:
    return os.path.join(_stitch_dir(tmp_folder), "stitch_assignments.npz")


class StitchFacesBase(BaseTask):
    """Per-block face scan: label-pair statistics across each upper face.

    Params: ``seg_path/seg_key`` (blockwise labels), ``input_path/
    input_key`` (the map driving the merge criterion; optional ``channel``
    reduces a leading channel axis).  For affinity inputs pass
    ``axis_channels`` (one channel index per spatial axis, e.g. [0, 1, 2]
    for the unit offsets): a face along axis ``a`` is then scored by channel
    ``axis_channels[a]`` read on the upper side of the face — exactly the
    affinity of the edges crossing it, instead of a direction-diluted
    average.
    """

    task_name = "stitch_faces"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "channel": None,
            "axis_channels": None,
        }

    def run_impl(self):
        cfg = self.get_config()
        ds_seg = file_reader(cfg["seg_path"])[cfg["seg_key"]]
        ds_map = file_reader(cfg["input_path"])[cfg["input_key"]]
        shape = ds_seg.shape
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        roi_set = set(block_ids)
        channel = cfg.get("channel")
        axis_channels = cfg.get("axis_channels")
        d = _stitch_dir(self.tmp_folder)

        def process(block_id):
            block = blocking.get_block(block_id)
            pairs, sums, counts = [], [], []
            for axis in range(len(shape)):
                nbr = blocking.neighbor_id(block_id, axis, 1)
                if nbr is None or nbr not in roi_set:
                    continue
                face = block.end[axis]
                bb_lo = tuple(
                    slice(face - 1, face) if a == axis else slice(b, e)
                    for a, (b, e) in enumerate(zip(block.begin, block.end))
                )
                bb_hi = tuple(
                    slice(face, face + 1) if a == axis else slice(b, e)
                    for a, (b, e) in enumerate(zip(block.begin, block.end))
                )
                lo = np.asarray(ds_seg[bb_lo]).ravel()
                hi = np.asarray(ds_seg[bb_hi]).ravel()
                if axis_channels is not None:
                    # the crossing edge's affinity lives on the upper-side
                    # voxel in the axis' attractive channel
                    val = _read_boundary_map(
                        ds_map, bb_hi, int(axis_channels[axis])
                    ).ravel().astype(np.float64)
                else:
                    v_lo = _read_boundary_map(ds_map, bb_lo, channel).ravel()
                    v_hi = _read_boundary_map(ds_map, bb_hi, channel).ravel()
                    val = np.maximum(v_lo, v_hi).astype(np.float64)
                both = (lo > 0) & (hi > 0) & (lo != hi)
                if not both.any():
                    continue
                # canonicalize (min, max) so both orientations of a label
                # pair pool into one row — the criterion must act on the
                # pooled per-pair mean, not per-direction subsets
                a = lo[both]
                b = hi[both]
                pq = np.stack(
                    [np.minimum(a, b), np.maximum(a, b)], axis=1
                ).astype(np.uint64)
                uv, inv = np.unique(pq, axis=0, return_inverse=True)
                s = np.zeros(len(uv))
                np.add.at(s, inv.ravel(), val[both])
                c = np.bincount(inv.ravel(), minlength=len(uv))
                pairs.append(uv)
                sums.append(s)
                counts.append(c)
            if pairs:
                np.savez(
                    os.path.join(d, f"block_{block_id}.npz"),
                    pairs=np.concatenate(pairs),
                    sums=np.concatenate(sums),
                    counts=np.concatenate(counts),
                )
            else:
                np.savez(
                    os.path.join(d, f"block_{block_id}.npz"),
                    pairs=np.zeros((0, 2), np.uint64),
                    sums=np.zeros(0),
                    counts=np.zeros(0, np.int64),
                )

        n = self.host_block_map(block_ids, process)
        return {"n_blocks": n}


class StitchFacesLocal(StitchFacesBase):
    target = "local"


class StitchFacesTPU(StitchFacesBase):
    target = "tpu"


class MergeStitchAssignmentsBase(BaseTask):
    """Merge face statistics, apply the criterion, union-find, emit the
    write-compatible assignment table (reference:
    ``SimpleStitchAssignmentsBase``)."""

    task_name = "merge_stitch_assignments"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "stitch_threshold": 0.5,
            "merge_mode": "less",
            # merge_mode='multicut' only: weight each face pair's cost by
            # its contact area before the global GAEC solve
            "weight_by_contact_area": False,
        }

    def run_impl(self):
        cfg = self.get_config()
        ds_seg = file_reader(cfg["seg_path"])[cfg["seg_key"]]
        shape = ds_seg.shape
        block_shape = tuple(cfg["block_shape"])
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        d = _stitch_dir(self.tmp_folder)
        solver_stats = None
        all_pairs, all_sums, all_counts = [], [], []
        for b in block_ids:
            p = os.path.join(d, f"block_{b}.npz")
            if os.path.exists(p):
                with np.load(p) as f:
                    all_pairs.append(f["pairs"])
                    all_sums.append(f["sums"])
                    all_counts.append(f["counts"])
        # the node set must cover every label, merged or not: collect block
        # uniques from the segmentation chunks
        uniques = set()

        def collect(block_id):
            u = np.unique(np.asarray(ds_seg[blocking.get_block(block_id).bb]))
            return u[u != 0]

        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=max(1, self.max_jobs)) as pool:
            for u in pool.map(collect, block_ids):
                uniques.update(u.tolist())
        nodes = np.array(sorted(uniques), dtype=np.uint64)

        if all_pairs and sum(len(p) for p in all_pairs):
            pairs = np.concatenate([p for p in all_pairs if len(p)])
            sums = np.concatenate([s for s, p in zip(all_sums, all_pairs) if len(p)])
            counts = np.concatenate(
                [c for c, p in zip(all_counts, all_pairs) if len(p)]
            )
            uv, inv = np.unique(pairs, axis=0, return_inverse=True)
            s = np.zeros(len(uv))
            np.add.at(s, inv.ravel(), sums)
            c = np.zeros(len(uv), np.int64)
            np.add.at(c, inv.ravel(), counts)
            mean = s / np.maximum(c, 1)
            thr = float(cfg.get("stitch_threshold", 0.5))
            mode = cfg.get("merge_mode", "less")
            dense = np.searchsorted(nodes, uv).astype(np.int64)
            if mode == "less":
                merge = mean < thr
            elif mode == "greater":
                merge = mean > thr
            elif mode == "multicut":
                # stitch-via-multicut on the face graph: probs -> costs
                # (cost > 0 iff mean < thr, see compute_costs: attractive
                # when p < 1 - beta), then the parallel GAEC decides which
                # pairs actually merge given the whole graph.  With
                # solver_shards > 1 the solve shards over the reduce tree
                # (docs/PERFORMANCE.md "Distributed agglomeration"); the
                # segmentation labels' id range stands in for octants
                # (blockwise labeling orders ids spatially), and any
                # sharded failure degrades to the single-host GAEC
                from ..ops import contraction as contraction_mod
                from ..ops.contraction import gaec_parallel
                from ..ops.multicut import multicut_energy
                from ..parallel import reduce_tree as reduce_tree_mod
                from .costs import compute_costs
                from .multicut import _solver_manifest

                costs = compute_costs(
                    mean.astype(np.float32),
                    beta=min(max(1.0 - thr, 1e-4), 1.0 - 1e-4),
                    edge_sizes=c.astype(np.float64)
                    if cfg.get("weight_by_contact_area")
                    else None,
                ).astype(np.float64)
                shards = int(cfg.get("solver_shards", 1) or 1)
                solver_snap = contraction_mod.solver_snapshot()
                tree_snap = reduce_tree_mod.solve_snapshot()
                if shards > 1:
                    labels, solve_info = reduce_tree_mod.solve_with_reduce_tree(
                        len(nodes), dense, costs,
                        node_shard=reduce_tree_mod.contiguous_node_shards(
                            len(nodes), shards
                        ),
                        solver_shards=shards,
                        fanout=int(cfg.get("reduce_fanout", 2) or 2),
                        reduce_plane=str(
                            cfg.get("reduce_plane", "auto") or "auto"
                        ),
                        hop_deadline_s=cfg.get("hop_deadline_s"),
                        failures_path=self.failures_path,
                        task_name=self.uid,
                        unsharded=lambda: gaec_parallel(
                            len(nodes), dense, costs
                        ),
                        workers=int(cfg.get("solver_workers", 1) or 1),
                        scratch_dir=os.path.join(d, "reduce_tree"),
                        max_workers=max(1, self.max_jobs),
                    )
                else:
                    labels = gaec_parallel(len(nodes), dense, costs)
                    solve_info = {"sharded": False, "shards": 1}
                solver_stats = _solver_manifest(
                    multicut_energy(dense.astype(np.int64), costs, labels),
                    dense, labels,
                    contraction_mod.solver_delta(solver_snap),
                    reduce_tree_mod.solve_delta(tree_snap),
                    solve_info,
                )
                merge = labels[dense[:, 0]] == labels[dense[:, 1]]
            else:
                raise ValueError(f"unknown merge_mode {mode!r}")
            merge_pairs = dense[merge]
        else:
            merge_pairs = np.zeros((0, 2), np.int64)

        from ..ops.unionfind import union_find_host

        roots = union_find_host(merge_pairs, len(nodes))
        _, assignment = np.unique(roots, return_inverse=True)
        np.savez(
            stitch_assignments_path(self.tmp_folder),
            keys=nodes,
            values=(assignment + 1).astype(np.uint64),
        )
        out = {
            "n_labels": int(len(nodes)),
            "n_merged_pairs": int(len(merge_pairs)),
            "n_components": int(assignment.max()) + 1 if len(assignment) else 0,
        }
        if solver_stats is not None:
            out["solver"] = solver_stats
        return out


class MergeStitchAssignmentsLocal(MergeStitchAssignmentsBase):
    target = "local"


class MergeStitchAssignmentsTPU(MergeStitchAssignmentsBase):
    target = "tpu"


class StitchingWorkflow(WorkflowBase):
    """stitch_faces -> merge_stitch_assignments -> write (in place on the
    segmentation by default; crash-safe via the staged write)."""

    task_name = "stitching_workflow"

    def requires(self):
        from . import stitching as st_mod
        from .relabel import staged_write_tasks

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        grid = {
            k: p[k] for k in ("block_shape", "roi_begin", "roi_end") if k in p
        }
        t1 = get_task_cls(st_mod, "StitchFaces", self.target)(
            **common,
            dependencies=self.dependencies,
            seg_path=p["seg_path"],
            seg_key=p["seg_key"],
            input_path=p["input_path"],
            input_key=p["input_key"],
            **{k: p[k] for k in ("channel", "axis_channels") if k in p},
            **grid,
        )
        t2 = get_task_cls(st_mod, "MergeStitchAssignments", self.target)(
            **common,
            dependencies=[t1],
            seg_path=p["seg_path"],
            seg_key=p["seg_key"],
            **{
                k: p[k]
                for k in (
                    "stitch_threshold", "merge_mode",
                    "solver_shards", "reduce_fanout", "solver_workers",
                )
                if k in p
            },
            **grid,
        )
        t3 = staged_write_tasks(
            self,
            [t2],
            assignment_path=stitch_assignments_path(self.tmp_folder),
            input_path=p["seg_path"],
            input_key=p["seg_key"],
            output_path=p.get("output_path", p["seg_path"]),
            output_key=p.get("output_key", p["seg_key"]),
            stage_name="stitch",
            bs={k: p[k] for k in ("block_shape",) if k in p},
        )
        return [t3]
