"""Threshold -> connected components -> size filter, as one workflow
(reference: ``cluster_tools/thresholded_components/``, SURVEY.md §2a).

The CC machinery already fuses thresholding into its first pass (the device
kernel thresholds on load), so this workflow is: ConnectedComponentsWorkflow
with a threshold, then an optional SizeFilterWorkflow.  A standalone
``Threshold`` task is provided for pipelines that need a materialized binary
mask (e.g. as an input mask for other ops).
"""

from __future__ import annotations

import numpy as np

from ..runtime.executor import region_verifier
from ..runtime import handoff
from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


class ThresholdBase(BaseTask):
    """Materialize a binary (uint8) mask: ``input > / < / == threshold``."""

    task_name = "threshold"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "threshold": 0.5,
            "threshold_mode": "greater",
        }

    def run_impl(self):
        cfg = self.get_config()
        # fusable input edge: a live in-memory producer handle (e.g. an
        # inference probability map) is consumed without a storage read
        inp = handoff.resolve_dataset(cfg["input_path"], cfg["input_key"])
        shape = inp.shape
        block_shape = tuple(cfg["block_shape"])
        out = file_reader(cfg["output_path"]).require_dataset(
            cfg["output_key"], shape=shape, chunks=block_shape, dtype="uint8"
        )
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        thr = float(cfg["threshold"])
        mode = cfg.get("threshold_mode", "greater")
        ops = {
            "greater": lambda d: d > thr,
            "less": lambda d: d < thr,
            "equal": lambda d: d == thr,
        }
        if mode not in ops:
            raise ValueError(f"unknown threshold_mode {mode!r}")

        def process(block_id):
            bb = blocking.get_block(block_id).bb
            out[bb] = ops[mode](inp[bb]).astype(np.uint8)

        # hardened host path (docs/ANALYSIS.md CT001): config-derived
        # retries/deadline/schedule plus per-block post-store integrity
        # verification against the digest sidecars
        n = self.host_block_map(
            block_ids, process,
            store_verify_fn=region_verifier(out), blocking=blocking,
        )
        return {"n_blocks": n}


class ThresholdLocal(ThresholdBase):
    target = "local"


class ThresholdTPU(ThresholdBase):
    target = "tpu"


class ThresholdedComponentsWorkflow(WorkflowBase):
    """CC with thresholding, then optional size filtering.

    Params: CC params (``input_path/input_key/output_path/output_key/
    threshold/threshold_mode``) plus optional ``min_size``/``max_size``.
    """

    task_name = "thresholded_components_workflow"

    def requires(self):
        from .connected_components import ConnectedComponentsWorkflow
        from .postprocess import SizeFilterWorkflow

        p = dict(self.params)
        min_size = p.pop("min_size", None)
        max_size = p.pop("max_size", None)
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
            target=self.target,
        )
        cc = ConnectedComponentsWorkflow(
            **common, dependencies=self.dependencies, **p
        )
        if not min_size and not max_size:
            return [cc]
        sf = SizeFilterWorkflow(
            **common,
            dependencies=[cc],
            input_path=p["output_path"],
            input_key=p["output_key"],
            output_path=p["output_path"],
            output_key=p["output_key"],
            min_size=min_size,
            max_size=max_size,
            **{k: p[k] for k in ("block_shape",) if k in p},
        )
        return [sf]

    def run_impl(self):
        return {}
