"""Blockwise affine volume transformation (reference:
``cluster_tools/transformations/`` — SURVEY.md §2a tags it as a
possibly-present extra; provided here so migrating users find it).

Semantics follow ``scipy.ndimage.affine_transform`` exactly:
``output[o] = input[matrix @ o + offset]`` — ``matrix`` (3x3) and
``offset`` (3,) map OUTPUT coordinates to INPUT coordinates, ``order``
in {0, 1} selects nearest/trilinear, out-of-volume samples read
``fill_value``.

TPU-first design: the trilinear resample is a device gather
(``jax.scipy.ndimage.map_coordinates``, float32) over a fixed-size input
buffer.  Each output block's input footprint is the affine image of the
block box; its size is bounded by ``ceil(|matrix| @ block_shape) + 2``
independent of block position, and edge blocks pad their coordinate
array to the full block size, so every block shares ONE static signature
and the device function compiles exactly once.  Dataset-boundary
clipping pads the buffer with ``fill_value`` (``mode='constant'``
semantics) — no per-block recompiles, no dynamic shapes.

``order=0`` (nearest, the segmentation/label case) is instead an exact
host gather in the ORIGINAL dtype: label ids survive at any integer
width (a float32 device round-trip would silently merge ids above 2^24),
and a pure gather is the one op the device is no better at.
"""

from __future__ import annotations

import numpy as np

from ..runtime.executor import region_verifier
from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


def _resample_fn(buf_shape, order, fill_value, target):
    """Jitted (buffer, local_coords) -> samples, one compile per task.

    Placement follows the task target via the canonical device policy
    (``parallel.mesh.backend_devices``): ``tpu`` runs on the chip,
    everything else (local / cluster nodes) on host CPU — a ``local``
    task must never initialize the accelerator backend."""
    import jax
    import jax.numpy as jnp
    from jax.scipy import ndimage as jndi

    from ..parallel.mesh import backend_devices

    dev = backend_devices("tpu" if target == "tpu" else "local")[0]

    @jax.jit
    def run(buf, coords):
        return jndi.map_coordinates(
            buf, [coords[0], coords[1], coords[2]],
            order=order, mode="constant", cval=fill_value,
        )

    def call(buf, coords):
        return run(jax.device_put(buf, dev), jax.device_put(coords, dev))

    return call


class AffineTransformBase(BaseTask):
    """Params: ``input_path/input_key``, ``output_path/output_key``,
    ``matrix`` (3x3 nested list), ``offset`` (3,), ``out_shape``
    (defaults to the input shape), ``order`` (0 nearest / 1 trilinear,
    default 1), ``fill_value`` (default 0)."""

    task_name = "affine_transform"

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "order": 1,
            "fill_value": 0,
            "out_shape": None,
        }

    def run_impl(self):
        cfg = self.get_config()
        inp = file_reader(cfg["input_path"])[cfg["input_key"]]
        in_shape = tuple(inp.shape)
        if len(in_shape) != 3:
            raise ValueError(
                f"affine_transform expects a 3-D volume, got {in_shape}"
            )
        matrix = np.asarray(cfg["matrix"], np.float64)
        offset = np.asarray(cfg["offset"], np.float64)
        if matrix.shape != (3, 3) or offset.shape != (3,):
            raise ValueError(
                "matrix must be 3x3 and offset length-3 (scipy "
                f"affine_transform semantics); got {matrix.shape} / "
                f"{offset.shape}"
            )
        order = int(cfg.get("order", 1))
        if order not in (0, 1):
            raise ValueError(f"order must be 0 or 1, got {order}")
        fill_value = float(cfg.get("fill_value", 0))
        out_shape = tuple(
            int(s) for s in (cfg.get("out_shape") or in_shape)
        )
        block_shape = tuple(cfg["block_shape"])

        out = file_reader(cfg["output_path"]).require_dataset(
            cfg["output_key"], shape=out_shape, chunks=block_shape,
            dtype=str(inp.dtype),
        )
        blocking = Blocking(out_shape, block_shape)
        block_ids = blocks_in_volume(
            out_shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )

        # static input-footprint bound: the affine image of a block box has
        # per-axis extent <= |matrix| @ block_shape; +2 covers the floor/
        # ceil stencil of trilinear sampling at both ends
        buf_shape = tuple(
            int(np.ceil(np.abs(matrix[i]) @ np.asarray(block_shape))) + 2
            for i in range(3)
        )
        run = (
            _resample_fn(buf_shape, order, fill_value, self.target)
            if order == 1 else None
        )
        n_full = int(np.prod(block_shape))

        def process(block_id):
            bb = blocking.get_block(block_id).bb
            # input coordinates of every output voxel in the block
            grids = np.meshgrid(
                *[np.arange(b.start, b.stop, dtype=np.float64) for b in bb],
                indexing="ij",
            )
            out_coords = np.stack([g.ravel() for g in grids])
            n_vox = out_coords.shape[1]
            in_coords = matrix @ out_coords + offset[:, None]
            lo = np.floor(in_coords.min(axis=1)).astype(np.int64)
            local = in_coords - lo[:, None]
            # scipy semantics: a coordinate outside [0, dim-1] yields pure
            # cval — no partial blending into the outside region
            outside = (
                (in_coords < 0) | (in_coords > np.asarray(in_shape)[:, None] - 1)
            ).any(axis=0)
            out_block_shape = [b.stop - b.start for b in bb]

            if order == 0:
                # nearest-neighbor is a pure gather: do it on host in the
                # ORIGINAL dtype — exact for any integer width (the float
                # device path would silently round ids above 2^24), and a
                # gather is the one op the device is no better at anyway
                # scipy rounds half UP (floor(x + 0.5)); np.round would
                # round half to even and disagree on every .5 coordinate
                idx = np.floor(in_coords + 0.5).astype(np.int64)
                np.clip(idx, 0, np.asarray(in_shape)[:, None] - 1, out=idx)
                rd_lo, rd_hi = idx.min(axis=1), idx.max(axis=1) + 1
                src = tuple(slice(a, b) for a, b in zip(rd_lo, rd_hi))
                blockdata = np.asarray(inp[src])
                samples = blockdata[tuple(idx - rd_lo[:, None])]
                samples = np.where(
                    outside, np.asarray(fill_value, inp.dtype), samples
                ).reshape(out_block_shape)
                out[bb] = samples.astype(inp.dtype)
                return

            # trilinear: device gather over the static fill-padded buffer
            # (float32 on device — interpolated intensities, not ids)
            read_lo = np.maximum(lo, 0)
            read_hi = np.minimum(lo + np.asarray(buf_shape), in_shape)
            buf = np.full(buf_shape, fill_value, dtype=np.float32)
            if (read_hi > read_lo).all():
                src = tuple(slice(a, b) for a, b in zip(read_lo, read_hi))
                dst = tuple(
                    slice(a - l, a - l + (b - a))
                    for a, b, l in zip(read_lo, read_hi, lo)
                )
                buf[dst] = np.asarray(inp[src], np.float32)
            if n_vox < n_full:
                # pad edge blocks to the one static coords shape: a single
                # compile serves every block (extra samples are cropped)
                local = np.pad(local, ((0, 0), (0, n_full - n_vox)))
            samples = np.asarray(
                run(buf, local.astype(np.float32))
            )[:n_vox].reshape(out_block_shape)
            samples = np.where(outside.reshape(out_block_shape),
                               fill_value, samples)
            if np.issubdtype(inp.dtype, np.integer):
                info = np.iinfo(inp.dtype)
                samples = np.clip(np.round(samples), info.min, info.max)
            out[bb] = samples.astype(inp.dtype)

        n = self.host_block_map(
            block_ids, process,
            store_verify_fn=region_verifier(out), blocking=blocking,
        )
        return {"n_blocks": n, "out_shape": list(out_shape), "order": order}


class AffineTransformLocal(AffineTransformBase):
    target = "local"


class AffineTransformTPU(AffineTransformBase):
    target = "tpu"


class TransformationsWorkflow(WorkflowBase):
    task_name = "transformations_workflow"

    def requires(self):
        from . import transformations as tf_mod

        return [
            get_task_cls(tf_mod, "AffineTransform", self.target)(
                tmp_folder=self.tmp_folder,
                config_dir=self.config_dir,
                max_jobs=self.max_jobs,
                dependencies=self.dependencies,
                **self.params,
            )
        ]
