"""Blockwise distance-transform watershed tasks (single- and two-pass).

Re-design of the reference's ``cluster_tools/watershed/`` (SURVEY.md §2a
"watershed", §3.5): per-block DT watershed with halo, labels offset for
global uniqueness, and the two-pass checkerboard variant where pass-two
blocks seed from already-labeled pass-one neighbors — cross-block-consistent
labels without a separate stitching task.

TPU shape: the fused kernel (threshold -> EDT -> seeds -> watershed, one
compiled program) is vmapped over a block batch and sharded over the mesh by
the :class:`~cluster_tools_tpu.runtime.executor.BlockwiseExecutor`; the halo
comes from overlapping host reads at ingress (the mesh-resident sharded
variant lives in ``parallel/pipeline.py``).

Label encoding: ``global = block_id * (n_outer + 1) + local`` (uint64), where
``local`` is the kernel's flat-index label within the static outer block —
globally unique by construction, made dense by the relabel workflow.
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..ops.watershed import (
    distance_transform_watershed,
    dt_watershed_seeded,
    filter_small_segments,
)
from ..runtime.executor import (
    BlockwiseExecutor,
    region_verifier,
    validate_labels,
)
from ..runtime.task import BaseTask, WorkflowBase, get_task_cls
from ..utils.volume_utils import (
    Blocking,
    blocks_in_volume,
    file_reader,
    pad_block_to,
)

import jax
import jax.numpy as jnp


def _tiled_cap_knobs(cfg):
    """Config-set capacity knobs for the tiled kernels (None = omit, the
    ops-level defaults apply).  Only meaningful for ``impl != 'legacy'``;
    raise the reported-overflow one, rerun the failed blocks."""
    return {
        k: int(cfg[k])
        for k in ("exit_cap", "fill_cap", "adj_cap", "fill_rounds",
                  "seed_cap", "table_cap", "pair_cap", "edge_cap")
        if cfg.get(k) is not None
    }


def _outer_shape(block_shape, halo):
    return tuple(b + 2 * h for b, h in zip(block_shape, halo))


class _WsTaskBase(BaseTask):
    """Shared machinery for the watershed task family."""

    @staticmethod
    def default_task_config():
        return {
            "threads_per_job": 1,
            "device_batch": 1,
            "threshold": 0.25,
            "sigma_seeds": 0.0,
            "min_seed_distance": 0.0,
            "sampling": None,
            "size_filter": 0,
            # mean-boundary threshold for in-block fragment agglomeration
            # after the flood (reference: watershed/agglomerate.py); None
            # disables.  Fragments whose contact's size-weighted mean
            # boundary value is below the threshold merge (average linkage).
            "agglomerate_threshold": None,
            "two_d": False,
            "connectivity": 1,
            "halo": [4, 4, 4],
            # EDT cap in physical (sampling) units; None derives it from the
            # halo.  Uncapped, a >160-extent block selects the O(n^2)
            # broadcast min-plus and allocates an (.., n, n) intermediate —
            # the cap keeps the erosion cascade O(cap) per axis, and
            # distances beyond the halo scale are meaningless blockwise
            # anyway (SURVEY.md §7 hard part 5).
            "dt_max_distance": None,
            # watershed kernel: "auto" (two-level tile machinery — saddle-
            # union fill respects ridge heights; the synthetic-EM validation
            # measured 6.5% fragment impurity vs 35% for the legacy ring
            # fill, which can adopt labels THROUGH membranes), "legacy"
            # (round-2 dense fixpoint), or explicit "pallas"/"xla".  2-D
            # mode and connectivity != 1 always use legacy.  Honored by both
            # the single-pass and the two-pass (externally seeded) tasks.
            "impl": "auto",
            # tiled-kernel capacity knobs (None = the ops-level defaults;
            # ignored by the legacy kernel).  Raise on overflow reports:
            # exit/fill/adj govern the cross-tile exit and saddle-fill
            # buffers, seed_cap the sparse seed labeler (CT_SEED_CCL),
            # fill_rounds the Boruvka round count, table_cap the VMEM
            # remap tables, pair/edge_cap the seed CCL's face merge.
            "exit_cap": None,
            "fill_cap": None,
            "adj_cap": None,
            "fill_rounds": None,
            "seed_cap": None,
            "table_cap": None,
            "pair_cap": None,
            "edge_cap": None,
        }

    def _setup(self):
        from ..runtime import handoff

        cfg = self.get_config()
        # fusable input edge (inference -> watershed): a live in-memory
        # handoff from the producing task is consumed directly; otherwise
        # this is the plain storage dataset
        inp = handoff.resolve_dataset(cfg["input_path"], cfg["input_key"])
        shape = inp.shape
        block_shape = tuple(cfg["block_shape"])
        halo = tuple(cfg.get("halo") or [0] * len(shape))
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )
        # MemoryTarget output (docs/PERFORMANCE.md "Task-graph fusion"):
        # with memory_handoffs on, the label volume stays in host RAM for
        # the graph/features/write consumers, spilling to this storage
        # path under the degrade ladder; off, this IS the storage dataset
        out = self.handoff_dataset(
            cfg["output_path"], cfg["output_key"],
            shape=shape, chunks=block_shape, dtype="uint64",
        )
        mask_ds = None
        if cfg.get("mask_path"):
            mask_ds = file_reader(cfg["mask_path"])[cfg["mask_key"]]
        return cfg, inp, out, mask_ds, shape, block_shape, halo, blocking, block_ids

    def _kernel_params(self, cfg):
        sampling = cfg.get("sampling")
        dt_max = cfg.get("dt_max_distance")
        if dt_max is None:
            # halo-derived default with a floor of 8.  Trade-off: the capped
            # EDT saturates object interiors thicker than 2x the cap into
            # one constant plateau, so two thick bodies joined by an equally
            # thick neck collapse to a single seed (uncapped they could
            # separate).  Uncapped, a >160-extent block instead selects the
            # O(n^2) broadcast min-plus and allocates tens of GB.  Workloads
            # with very thick objects should set dt_max_distance explicitly
            # above the object radius.
            halo = cfg.get("halo") or [0]
            samp = sampling or [1.0] * len(halo)
            dt_max = max(8.0, max(float(h) * float(s) for h, s in zip(halo, samp)))
        return dict(
            threshold=float(cfg["threshold"]),
            sigma_seeds=float(cfg.get("sigma_seeds") or 0.0),
            min_seed_distance=float(cfg.get("min_seed_distance") or 0.0),
            sampling=None if sampling is None else tuple(sampling),
            connectivity=int(cfg.get("connectivity", 1)),
            dt_max_distance=float(dt_max),
        )

    @staticmethod
    def _agglomerate_block(lab: np.ndarray, bnd: np.ndarray, threshold: float):
        """In-block average-linkage merge of WS fragments (reference:
        ``watershed/agglomerate.py``): fragments whose contact's
        size-weighted mean boundary value is below ``threshold`` fuse.

        Runs on the padded-outer labels so halo context participates, like
        the reference's in-block agglomeration.  Isolated fragments (no RAG
        edge) keep distinct ids.  Single-pass blocks only: two-pass labels
        carry immutable external seed ids that must not merge blockwise.
        """
        from ..ops.agglomeration import average_agglomeration
        from ..ops.rag import block_rag

        lab = np.ascontiguousarray(lab)
        uv, sizes, feats = block_rag(lab.astype(np.uint64), bnd)
        if len(uv) == 0:
            return lab
        nodes = np.unique(uv).astype(np.int64)
        remap = np.zeros(int(nodes.max()) + 1, np.int64)
        remap[nodes] = np.arange(len(nodes))
        merged = average_agglomeration(
            len(nodes), remap[uv.astype(np.int64)], feats[:, 0], sizes, threshold
        )
        all_labels = np.unique(lab[lab > 0]).astype(np.int64)
        table = np.zeros(int(all_labels.max()) + 1, lab.dtype)
        table[nodes] = (merged + 1).astype(lab.dtype)
        iso = np.setdiff1d(all_labels, nodes, assume_unique=True)
        k = int(merged.max()) + 1 if len(merged) else 0
        table[iso] = (np.arange(len(iso)) + k + 1).astype(lab.dtype)
        return table[lab]

    def _store_labels(self, out, block, raw, n_outer, size_dtype=np.uint64):
        """Crop inner region from the padded-outer labels and globalize."""
        inner = raw[block.inner_in_outer_bb]
        glob = np.where(
            inner > 0,
            np.uint64(block.block_id) * np.uint64(n_outer + 1)
            + inner.astype(np.uint64),
            np.uint64(0),
        )
        out[block.bb] = glob
        return glob


class WatershedBase(_WsTaskBase):
    """Single-pass blockwise DT watershed (independent blocks).

    Params: ``input_path/input_key`` (boundary/height map), ``output_path/
    output_key``; kernel params per ``default_task_config``.  Optional
    ``pass_parity`` (0/1) restricts to checkerboard-even/odd blocks — used by
    the two-pass workflow for pass one.
    """

    task_name = "watershed"

    def run_impl(self):
        (
            cfg,
            inp,
            out,
            mask_ds,
            shape,
            block_shape,
            halo,
            blocking,
            block_ids,
        ) = self._setup()
        parity = cfg.get("pass_parity")
        if parity is not None:
            block_ids = [
                b
                for b in block_ids
                if sum(blocking.block_grid_position(b)) % 2 == int(parity)
            ]
        done = set(self.blocks_done())
        blocks_all = [blocking.get_block(b, halo) for b in block_ids]
        todo = [b for b in blocks_all if b.block_id not in done]
        outer = _outer_shape(block_shape, halo)
        n_outer = int(np.prod(outer))
        kp = self._kernel_params(cfg)
        two_d = bool(cfg.get("two_d", False))
        size_filter = int(cfg.get("size_filter") or 0)
        agg_thr = cfg.get("agglomerate_threshold")
        if agg_thr is not None and cfg.get("pass_parity") is not None:
            # pass one of the checkerboard: its labels seed pass two, which
            # cannot agglomerate (see TwoPassWatershedBase) — mixing would
            # desynchronize the shared label space
            raise NotImplementedError(
                "agglomerate_threshold is not supported with pass_parity "
                "(two-pass checkerboard)"
            )
        # boundary blocks stashed between load and store for the host-side
        # agglomeration (unique keys; dict ops are GIL-atomic across the IO
        # threads)
        bnd_stash = {}

        def load(block):
            data = inp[block.outer_bb].astype(np.float32)
            # pad with 1.0 (pure boundary) so basins don't leak off-volume
            data = pad_block_to(data, outer, constant_values=1.0)
            if agg_thr is not None:
                bnd_stash[block.block_id] = data
            if mask_ds is not None:
                m = mask_ds[block.outer_bb] > 0
                m = pad_block_to(m, outer)
            else:
                m = np.ones(outer, bool)
            return data, m

        impl = str(cfg.get("impl", "auto"))
        use_tiled = (
            impl != "legacy"
            and not two_d
            and int(kp.get("connectivity", 1)) == 1
            and len(outer) == 3
        )

        def kernel(b, m):
            if use_tiled:
                from ..ops.tile_ws import dt_watershed_tiled

                tk = {k: v for k, v in kp.items() if k != "connectivity"}
                tk.update(_tiled_cap_knobs(cfg))
                lab, ovf = dt_watershed_tiled(b, mask=m, impl=impl, **tk)
            else:
                lab = distance_transform_watershed(b, mask=m, two_d=two_d, **kp)
                ovf = jnp.zeros((), bool)
            if size_filter > 0:
                lab = filter_small_segments(
                    lab, b, jnp.int32(size_filter), connectivity=kp["connectivity"]
                )
            return lab, ovf

        overflow_blocks = set()

        def store(block, raw):
            lab, ovf = raw
            if bool(np.asarray(ovf)):
                # capacity-truncated labels are under-merged — record loudly
                overflow_blocks.add(block.block_id)
                self.logger.warning(
                    f"block {block.block_id} overflowed a tiled-watershed "
                    "capacity; labels may be under-merged (raise the caps "
                    "or use impl=legacy)"
                )
            lab = np.asarray(lab)
            if agg_thr is not None:
                # peek, don't pop: a store retry (including a post-store
                # integrity-verify retry) must find the stash intact — the
                # stash is released in block_done below
                lab = self._agglomerate_block(
                    lab, bnd_stash[block.block_id], float(agg_thr)
                )
            self._store_labels(out, block, lab, n_outer)

        def block_done(block):
            bnd_stash.pop(block.block_id, None)
            self.log_block_success(block.block_id)

        if impl == "host":
            # reference-style per-job scipy compute (ops/host.py): no
            # device, no jit — the executor's vmap+jit contract does not
            # apply, so run the blocks on a thread pool (scipy EDT /
            # watershed_ift release the GIL, so max_jobs threads really
            # overlap compute as well as IO)
            if two_d:
                raise NotImplementedError("impl='host' is 3-D only")
            if size_filter > 0 or agg_thr is not None:
                raise NotImplementedError(
                    "impl='host' does not support size_filter / "
                    "agglomerate_threshold — use the device impls"
                )
            # params the host kernel has no twin for must fail, not drift
            if float(kp.get("sigma_seeds") or 0.0) > 0:
                raise NotImplementedError(
                    "impl='host' does not support sigma_seeds"
                )
            if int(kp.get("connectivity", 1)) != 1:
                raise NotImplementedError(
                    "impl='host' supports connectivity=1 only"
                )
            from concurrent.futures import ThreadPoolExecutor

            from ..ops.host import host_dt_watershed

            def _host_block(block):
                b, m = load(block)
                lab = host_dt_watershed(
                    b,
                    threshold=float(kp["threshold"]),
                    dt_max_distance=kp.get("dt_max_distance"),
                    min_seed_distance=float(kp.get("min_seed_distance", 0.0)),
                    mask=m,
                    sampling=kp.get("sampling"),
                )
                store(block, (lab, False))
                self.log_block_success(block.block_id)

            with ThreadPoolExecutor(max(1, self.max_jobs)) as pool:
                # list() propagates the first worker exception
                list(pool.map(_host_block, todo))
        else:
            executor = BlockwiseExecutor(
                target=self.target,
                device_batch=int(cfg.get("device_batch", 1)),
                io_threads=int(cfg.get("io_threads") or max(1, self.max_jobs)),
                max_retries=int(cfg.get("io_retries", 2)),
                backoff_base=float(cfg.get("io_backoff_s", 0.05)),
            )
            executor.map_blocks(
                kernel,
                blocks_all,
                load,
                store,
                on_block_done=block_done,
                done_block_ids=done,
                validate_fn=validate_labels,
                failures_path=self.failures_path,
                task_name=self.uid,
                block_deadline_s=cfg.get("block_deadline_s"),
                watchdog_period_s=cfg.get("watchdog_period_s"),
                store_verify_fn=region_verifier(out),
                schedule=str(cfg.get("block_schedule") or "morton"),
                # one sharded program per Morton batch when the mesh/sweep
                # is big enough (docs/PERFORMANCE.md "Sharded sweeps");
                # bit-identical to per-block dispatch, which stays the
                # degrade fallback
                sweep_mode=str(cfg.get("sweep_mode") or "auto"),
                sharded_batch=cfg.get("sharded_batch"),
                # HBM-resident page pool for ragged sweeps: pages upload
                # once, re-address per batch (docs/PERFORMANCE.md
                # "Device-resident data plane")
                device_pool=str(cfg.get("device_pool") or "auto"),
                device_pool_bytes=cfg.get("device_pool_bytes"),
                # degrade policy: OOM/ENOSPC blocks wait for headroom and
                # re-execute instead of burning same-size retries.  NEVER
                # splittable: the label encoding (block_id * (n_outer+1) +
                # flat index in the STATIC outer block) depends on the outer
                # shape, so sub-block re-execution could not reproduce the
                # unsplit labels bit-identically.
                splittable=False,
                degrade_wait_s=float(cfg.get("degrade_wait_s", 5.0)),
                inflight_byte_budget=cfg.get("inflight_byte_budget"),
            )
        return {
            "n_blocks": len(block_ids),
            "n_outer": n_outer,
            "overflow_blocks": sorted(overflow_blocks),
        }


class WatershedLocal(WatershedBase):
    target = "local"


class WatershedTPU(WatershedBase):
    target = "tpu"


class TwoPassWatershedBase(_WsTaskBase):
    """Pass two of the checkerboard: odd blocks seed from even neighbors.

    Reads the boundary map *and* the pass-one labels in the halo region; the
    visible neighbor labels become external seeds (compressed to dense ids on
    host), so basins continue across block faces with identical global ids
    (SURVEY.md §3.5).
    """

    task_name = "two_pass_watershed"

    def run_impl(self):
        (
            cfg,
            inp,
            out,
            mask_ds,
            shape,
            block_shape,
            halo,
            blocking,
            block_ids,
        ) = self._setup()
        if all(h == 0 for h in halo):
            raise ValueError("two-pass watershed requires a nonzero halo")
        if cfg.get("agglomerate_threshold") is not None:
            # pass-two labels carry immutable external seed ids from pass
            # one; merging them blockwise would desynchronize the shared
            # label space — agglomerate on the single-pass task instead
            raise NotImplementedError(
                "agglomerate_threshold is not supported with the two-pass "
                "watershed"
            )
        if cfg.get("two_d"):
            # pass-one blocks would be segmented per-slice and pass-two in
            # 3-D: refuse the inconsistent hybrid instead of producing it
            raise NotImplementedError(
                "two_d=True is not supported for the two-pass watershed; "
                "use the single-pass watershed for per-slice segmentation"
            )
        block_ids = [
            b
            for b in block_ids
            if sum(blocking.block_grid_position(b)) % 2 == 1
        ]
        done = set(self.blocks_done())
        blocks_all = [blocking.get_block(b, halo) for b in block_ids]
        outer = _outer_shape(block_shape, halo)
        n_outer = int(np.prod(outer))
        kp = self._kernel_params(cfg)
        size_filter = int(cfg.get("size_filter") or 0)

        # per-block external-seed tables, keyed by block id (host side)
        tables = {}

        def load(block):
            data = pad_block_to(
                inp[block.outer_bb].astype(np.float32), outer, constant_values=1.0
            )
            prev = pad_block_to(out[block.outer_bb], outer)
            # keep only voxels owned by even-parity (pass-one) blocks: pass
            # one is a completed barrier, so those chunks are immutable here —
            # reading odd-parity neighbors' chunks would race with concurrent
            # pass-two stores, and diagonal odd blocks must not seed us anyway
            grids = np.ix_(
                *(
                    np.arange(b, b + o) // bs
                    for b, o, bs in zip(block.outer_begin, prev.shape, block_shape)
                )
            )
            parity = sum(grids) % 2
            prev = np.where(parity == 0, prev, np.uint64(0))
            ext_labels = np.unique(prev[prev > 0])
            dense = np.zeros(outer, np.int32)
            if len(ext_labels):
                dense = np.searchsorted(ext_labels, prev).astype(np.int32) + 1
                dense[prev == 0] = 0
            tables[block.block_id] = ext_labels
            if mask_ds is not None:
                m = pad_block_to(mask_ds[block.outer_bb] > 0, outer)
            else:
                m = np.ones(outer, bool)
            return data, dense, m

        impl = str(cfg.get("impl", "auto"))
        if impl == "host":
            # pass one would run scipy while this pass runs the seeded
            # device kernel — two different flood semantics stitched into
            # one label space.  Refuse the hybrid (same policy as two_d).
            raise NotImplementedError(
                "impl='host' is not supported for two-pass watershed — the "
                "seeded continuation only exists as a device kernel"
            )
        use_tiled = impl != "legacy" and int(kp.get("connectivity", 1)) == 1

        def kernel(b, ext, m):
            if use_tiled:
                from ..ops.tile_ws import dt_watershed_seeded_tiled

                tk = {k: v for k, v in kp.items() if k != "connectivity"}
                tk.update(_tiled_cap_knobs(cfg))
                lab, ovf = dt_watershed_seeded_tiled(
                    b, ext, mask=m, impl=impl, **tk
                )
            else:
                lab = dt_watershed_seeded(b, ext, mask=m, **kp)
                ovf = jnp.zeros((), bool)
            if size_filter > 0:
                # external ids live in (N, 2N]; widen the size-count domain
                lab = filter_small_segments(
                    lab,
                    b,
                    jnp.int32(size_filter),
                    connectivity=kp["connectivity"],
                    max_label=2 * n_outer,
                )
            return lab, ovf

        overflow_blocks = set()

        def store(block, raw):
            raw, ovf = raw
            if bool(np.asarray(ovf)):
                # same contract as the single-pass store: capacity
                # truncation means under-merged labels — never silent,
                # and recorded so the blocks can be rerun programmatically
                overflow_blocks.add(block.block_id)
                self.logger.warning(
                    f"block {block.block_id} overflowed a tiled-watershed "
                    "capacity; labels may be under-merged (raise the caps "
                    "or use impl=legacy)"
                )
            raw = np.asarray(raw)[block.inner_in_outer_bb]
            # peek, don't pop: a store retry must find the table intact
            ext_labels = tables[block.block_id]
            is_ext = raw > n_outer
            glob = np.zeros(raw.shape, np.uint64)
            if is_ext.any():
                glob[is_ext] = ext_labels[
                    np.clip(raw[is_ext] - n_outer - 1, 0, len(ext_labels) - 1)
                ]
            new = (raw > 0) & ~is_ext
            glob[new] = np.uint64(block.block_id) * np.uint64(n_outer + 1) + raw[
                new
            ].astype(np.uint64)
            out[block.bb] = glob

        def block_done(block):
            # release the seed table only once the block is fully stored
            # (a verify-triggered re-store must still find it)
            tables.pop(block.block_id, None)
            self.log_block_success(block.block_id)

        executor = BlockwiseExecutor(
            target=self.target,
            device_batch=int(cfg.get("device_batch", 1)),
            io_threads=int(cfg.get("io_threads") or max(1, self.max_jobs)),
            max_retries=int(cfg.get("io_retries", 2)),
            backoff_base=float(cfg.get("io_backoff_s", 0.05)),
        )
        executor.map_blocks(
            kernel,
            blocks_all,
            load,
            store,
            on_block_done=block_done,
            done_block_ids=done,
            validate_fn=validate_labels,
            failures_path=self.failures_path,
            task_name=self.uid,
            block_deadline_s=cfg.get("block_deadline_s"),
            watchdog_period_s=cfg.get("watchdog_period_s"),
            store_verify_fn=region_verifier(out),
            schedule=str(cfg.get("block_schedule") or "morton"),
            sweep_mode=str(cfg.get("sweep_mode") or "auto"),
            sharded_batch=cfg.get("sharded_batch"),
            device_pool=str(cfg.get("device_pool") or "auto"),
            device_pool_bytes=cfg.get("device_pool_bytes"),
            # same degrade policy as the single-pass task; never splittable
            # (outer-shape-dependent label encoding, see WatershedBase)
            splittable=False,
            degrade_wait_s=float(cfg.get("degrade_wait_s", 5.0)),
            inflight_byte_budget=cfg.get("inflight_byte_budget"),
        )
        return {
            "n_blocks": len(block_ids),
            "n_outer": n_outer,
            "overflow_blocks": sorted(overflow_blocks),
        }


class TwoPassWatershedLocal(TwoPassWatershedBase):
    target = "local"


class TwoPassWatershedTPU(TwoPassWatershedBase):
    target = "tpu"


class WatershedWorkflow(WorkflowBase):
    """Watershed workflow: single-pass, or two-pass checkerboard when
    ``two_pass=True`` (reference: ``WatershedWorkflow`` /
    ``TwoPassWatershed``)."""

    task_name = "watershed_workflow"

    def requires(self):
        from . import watershed as ws_mod

        p = dict(self.params)
        two_pass = bool(p.pop("two_pass", False))
        if two_pass and p.get("two_d"):
            # reject before pass one burns hours on even blocks — the
            # two-pass task would refuse anyway (see TwoPassWatershedBase)
            raise NotImplementedError(
                "two_d=True is not supported with two_pass=True"
            )
        if two_pass and p.get("agglomerate_threshold") is not None:
            # same altitude as the two_d guard: refuse before pass one runs
            # (and checkpoints) agglomerated even blocks that pass two would
            # then mix with un-agglomerated labels
            raise NotImplementedError(
                "agglomerate_threshold is not supported with two_pass=True"
            )
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        if not two_pass:
            return [
                get_task_cls(ws_mod, "Watershed", self.target)(
                    **common, dependencies=self.dependencies, **p
                )
            ]
        t1 = get_task_cls(ws_mod, "Watershed", self.target)(
            **common, dependencies=self.dependencies, pass_parity=0, **p
        )
        t2 = get_task_cls(ws_mod, "TwoPassWatershed", self.target)(
            **common, dependencies=[t1], **p
        )
        return [t2]

    def run_impl(self):
        return {}
