"""Generic blockwise assignment writer.

Reference: ``cluster_tools/write/`` — "apply node-assignment table to
segmentation, blockwise", the final step of nearly every labeling workflow
(SURVEY.md §2a).  The assignment is an ``npz`` with sorted ``keys`` (uint64
labels) and ``values`` (new labels); unmatched labels map to 0.  Pure host
work (a searchsorted per block is memory-bound), parallelized over an IO
thread pool.
"""

from __future__ import annotations

import os
import numpy as np

from ..runtime import handoff
from ..runtime.executor import region_verifier
from ..runtime.task import BaseTask
from ..utils.volume_utils import Blocking, blocks_in_volume, file_reader


def apply_assignment_np(
    labels: np.ndarray, keys: np.ndarray, values: np.ndarray
) -> np.ndarray:
    """Map ``labels`` through the (sorted keys -> values) table; 0 stays 0,
    labels missing from the table map to 0."""
    idx = np.searchsorted(keys, labels)
    idx = np.clip(idx, 0, max(len(keys) - 1, 0))
    if len(keys) == 0:
        return np.zeros_like(labels)
    matched = keys[idx] == labels
    out = np.where(matched & (labels != 0), values[idx], 0)
    return out.astype(values.dtype if len(values) else labels.dtype)


class WriteBase(BaseTask):
    """Params: ``input_path/input_key`` (labels to relabel),
    ``output_path/output_key`` (may equal input for in-place),
    ``assignment_path`` (npz with keys/values)."""

    task_name = "write"

    def run_impl(self):
        cfg = self.get_config()
        # fusable edges (watershed -> write, multicut -> write): labels and
        # the assignment table come from live in-memory handoffs when the
        # producers published them; the OUTPUT always goes to storage —
        # it is the workflow's product, not an intermediate
        inp = handoff.resolve_dataset(cfg["input_path"], cfg["input_key"])
        shape = inp.shape
        block_shape = tuple(cfg["block_shape"])
        f = handoff.load_arrays(cfg["assignment_path"])
        keys, values = f["keys"], f["values"]

        out_f = file_reader(cfg["output_path"])
        out = out_f.require_dataset(
            cfg["output_key"], shape=shape, chunks=block_shape, dtype="uint64"
        )
        blocking = Blocking(shape, block_shape)
        block_ids = blocks_in_volume(
            shape, block_shape, cfg.get("roi_begin"), cfg.get("roi_end")
        )

        def process(block_id):
            block = blocking.get_block(block_id)
            labels = inp[block.bb]
            out[block.bb] = apply_assignment_np(labels, keys, values)

        n = self.host_block_map(
            block_ids, process,
            store_verify_fn=region_verifier(out), blocking=blocking,
        )
        return {"n_blocks": n}


class WriteLocal(WriteBase):
    target = "local"


class WriteTPU(WriteBase):
    target = "tpu"
