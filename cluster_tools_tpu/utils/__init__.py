from . import volume_utils
from . import function_utils
from . import task_utils
from . import segmentation_utils
from . import parse_utils
