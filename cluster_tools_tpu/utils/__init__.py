from . import volume_utils
from . import function_utils
from . import task_utils
