"""Logging and success markers.

The reference coordinated completion through log files: workers wrote
``log_block_success`` / ``log_job_success`` lines that the driver's
``check_jobs`` grepped (SURVEY.md §2d, §5.5).  We keep the same two-level
success-marker contract (it is the resume mechanism), but markers are JSON
manifests rather than grep-able log lines.
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import sys
import threading
from typing import Iterable, List, Optional

_LOGGERS = {}
_LOCK = threading.Lock()


def get_logger(name: str = "cluster_tools_tpu", log_file: Optional[str] = None):
    with _LOCK:
        key = (name, log_file)
        if key in _LOGGERS:
            return _LOGGERS[key]
        logger = logging.getLogger(name if log_file is None else f"{name}:{log_file}")
        logger.setLevel(logging.INFO)
        logger.propagate = False
        fmt = logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        handler = (
            logging.FileHandler(log_file)
            if log_file
            else logging.StreamHandler(sys.stderr)
        )
        handler.setFormatter(fmt)
        logger.addHandler(handler)
        _LOGGERS[key] = logger
        return logger


def log(msg: str, log_file: Optional[str] = None):
    get_logger(log_file=log_file).info(msg)


def _marker_dir(tmp_folder: str, task_name: str) -> str:
    d = os.path.join(tmp_folder, "markers", task_name)
    os.makedirs(d, exist_ok=True)
    return d


def log_block_success(tmp_folder: str, task_name: str, block_id: int):
    """Record that one block of a task finished (block-level resume grain)."""
    path = os.path.join(_marker_dir(tmp_folder, task_name), f"block_{block_id}.json")
    with open(path, "w") as f:
        json.dump({"block_id": block_id, "time": _now()}, f)


def log_job_success(tmp_folder: str, task_name: str, job_id: int):
    path = os.path.join(_marker_dir(tmp_folder, task_name), f"job_{job_id}.json")
    with open(path, "w") as f:
        json.dump({"job_id": job_id, "time": _now()}, f)


def blocks_done(tmp_folder: str, task_name: str) -> List[int]:
    d = _marker_dir(tmp_folder, task_name)
    out = []
    for fname in os.listdir(d):
        if fname.startswith("block_") and fname.endswith(".json"):
            out.append(int(fname[len("block_"):-len(".json")]))
    return sorted(out)


def jobs_done(tmp_folder: str, task_name: str) -> List[int]:
    d = _marker_dir(tmp_folder, task_name)
    return sorted(
        int(f[len("job_"):-len(".json")])
        for f in os.listdir(d)
        if f.startswith("job_") and f.endswith(".json")
    )


def clean_up_for_retry(tmp_folder: str, task_name: str):
    """Drop job-level markers so a failed task re-checks its blocks."""
    d = _marker_dir(tmp_folder, task_name)
    for fname in os.listdir(d):
        if fname.startswith("job_"):
            os.remove(os.path.join(d, fname))


def _now() -> str:
    return datetime.datetime.now().isoformat()


def python_executable() -> str:
    """Interpreter for re-executing framework entry points in batch jobs."""
    return sys.executable
