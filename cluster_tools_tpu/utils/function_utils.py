"""Logging and success markers.

The reference coordinated completion through log files: workers wrote
``log_block_success`` / ``log_job_success`` lines that the driver's
``check_jobs`` grepped (SURVEY.md §2d, §5.5).  We keep the same two-level
success-marker contract (it is the resume mechanism), but markers are JSON
manifests rather than grep-able log lines.
"""

from __future__ import annotations

import contextlib
import datetime
import json
import logging
import os
import random
import sys
import threading
import time
from typing import Iterable, List, Optional

_LOGGERS = {}
_LOCK = threading.Lock()


def get_logger(name: str = "cluster_tools_tpu", log_file: Optional[str] = None):
    with _LOCK:
        key = (name, log_file)
        if key in _LOGGERS:
            return _LOGGERS[key]
        logger = logging.getLogger(name if log_file is None else f"{name}:{log_file}")
        logger.setLevel(logging.INFO)
        logger.propagate = False
        fmt = logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        handler = (
            logging.FileHandler(log_file)
            if log_file
            else logging.StreamHandler(sys.stderr)
        )
        handler.setFormatter(fmt)
        logger.addHandler(handler)
        _LOGGERS[key] = logger
        return logger


def log(msg: str, log_file: Optional[str] = None):
    get_logger(log_file=log_file).info(msg)


def atomic_write_json(path: str, doc, default=None) -> None:
    """Write JSON via a temp file + ``os.replace`` so readers never observe
    a torn document — a kill mid-write leaves the old file (or nothing),
    never half a manifest."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, default=default)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_json_if_valid(path: str):
    """Parse a JSON file; return None for missing or torn (unparseable)
    files — torn manifests are treated as not-done, never as fatal."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def backoff_delay(attempt: int, base: float, cap: float) -> float:
    """Capped exponential backoff with full jitter (0.5x-1x): the one
    retry-delay policy shared by the executor's per-block IO retries, the
    scheduler submit retries, and the task-level re-runs — jitter keeps N
    workers recovering from a shared outage from thundering-herd retrying
    at the same instant."""
    import random

    return min(cap, base * (2 ** attempt)) * (0.5 + 0.5 * random.random())


def cap_traceback(tb: str, max_chars: int = 2000) -> str:
    """Tail-capped traceback (the last lines carry the error) so failure
    manifests aggregating hundreds of blocks stay bounded."""
    if len(tb) <= max_chars:
        return tb
    return "... [truncated] ...\n" + tb[-max_chars:]


def failures_path(tmp_folder: str) -> str:
    """The per-run structured failure manifest (shared by all tasks)."""
    return os.path.join(tmp_folder, "failures.json")


def _hostname() -> str:
    global _HOSTNAME
    if _HOSTNAME is None:
        import socket

        _HOSTNAME = socket.gethostname()
    return _HOSTNAME


_HOSTNAME: Optional[str] = None


def _lock_holder_dead(lock: str) -> bool:
    """True when ``lock``'s token names a pid on THIS host that no longer
    exists — a SIGKILLed holder whose lock would otherwise pin every
    waiter for the full ``timeout_s``.  A token from another host (shared
    filesystem), an unparsable/torn token, or a live-or-unprobeable pid
    all answer False: the stale/timeout ladder handles those — pid reuse
    can only make a dead holder look alive (conservative), never a live
    holder look dead."""
    try:
        with open(lock) as f:
            token = f.read()
    except OSError:
        return False
    parts = token.split(":")
    if len(parts) != 4 or parts[0] != _hostname():
        return False
    try:
        pid = int(parts[1])
    except ValueError:
        return False
    if pid == os.getpid():
        return False  # another thread of this process: alive by definition
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return True
    except OSError:
        pass
    return False


@contextlib.contextmanager
def file_lock(path: str, timeout_s: float = 30.0, stale_s: float = 60.0):
    """Advisory cross-process lock via an ``O_CREAT|O_EXCL`` lock file
    (works on the shared filesystems cluster jobs coordinate over, where
    ``fcntl`` locks are unreliable).  A lock whose same-host holder pid is
    dead is broken immediately (:func:`_lock_holder_dead` — a SIGKILLed
    holder must not make its adopter wait out the full timeout); a lock
    older than ``stale_s`` is broken (its cross-host holder died between
    create and unlink); after ``timeout_s`` the lock is stolen rather than
    raising — the callers guard best-effort bookkeeping on failure paths,
    where blocking forever or raising would mask the real error."""
    lock = path + ".lock"
    # unique ownership token: release must only unlink OUR lock file — a
    # holder whose lock was stolen (timeout/stale break) must not remove
    # the thief's lock and cascade the loss of mutual exclusion.  The
    # host:pid prefix is what the dead-holder probe parses.
    token = (
        f"{_hostname()}:{os.getpid()}:{threading.get_ident()}"
        f":{random.random()}"
    )
    deadline = time.time() + float(timeout_s)
    while True:
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, token.encode())
            os.close(fd)
            break
        except FileExistsError:
            try:
                stale = time.time() - os.path.getmtime(lock) > float(stale_s)
            except OSError:
                continue  # holder released between exists-check and stat
            if stale or time.time() > deadline or _lock_holder_dead(lock):
                # atomic steal: rename first — exactly one of N waiters
                # wins the rename, so two waiters can never both break the
                # same lock and then break each other's fresh locks
                grave = f"{lock}.stolen.{os.getpid()}.{threading.get_ident()}"
                try:
                    os.rename(lock, grave)
                    os.unlink(grave)
                except OSError:
                    pass  # another waiter stole it first; re-acquire
                continue
            time.sleep(0.005 + 0.01 * random.random())
    try:
        yield
    finally:
        try:
            with open(lock) as f:
                if f.read() == token:
                    os.unlink(lock)
        except OSError:
            pass


#: failures.json record schema: 2 adds per-record ``schema_version`` /
#: ``hostname`` / ``pid`` (records merged from concurrent cluster jobs stay
#: attributable to the process that wrote them) and the optional
#: ``resolution`` / ``resource`` degradation fields (docs/ROBUSTNESS.md).
FAILURES_SCHEMA_VERSION = 2


def record_failures(path: str, task_name: str, records) -> None:
    """Merge block-failure records into ``failures.json`` (atomic).

    Schema: ``{"version": 2, "records": [{"task", "block_id",
    "sites": {site: attempts}, "error", "quarantined", "resolved",
    "schema_version", "hostname", "pid", ...}]}`` (optional fields:
    ``resolution``, ``resource``, ``job_id``/``job_ids``, ``duplicate``).
    Records are keyed by (task, block_id): a resumed run's record replaces
    the stale one from before the restart.  Each record is stamped with the
    recording process's hostname + pid, so records merged from concurrent
    cluster jobs stay attributable.  The read-modify-write runs under a
    lock file so two cluster jobs recording failures at the same moment
    cannot drop each other's records.
    """
    import socket

    host, pid = socket.gethostname(), os.getpid()
    with file_lock(path):
        doc = read_json_if_valid(path) or {}
        existing = {
            (r.get("task"), r.get("block_id")): r
            for r in doc.get("records", [])
        }
        for rec in records:
            rec = dict(rec)
            rec["task"] = task_name
            rec.setdefault("schema_version", FAILURES_SCHEMA_VERSION)
            rec.setdefault("hostname", host)
            rec.setdefault("pid", pid)
            existing[(task_name, rec.get("block_id"))] = rec
        merged = sorted(
            existing.values(),
            key=lambda r: (str(r.get("task")), r.get("block_id") or 0),
        )
        atomic_write_json(
            path, {"version": FAILURES_SCHEMA_VERSION, "records": merged}
        )


def io_metrics_path(tmp_folder: str) -> str:
    """The per-run chunk-IO metrics manifest, next to ``failures.json``."""
    return os.path.join(tmp_folder, "io_metrics.json")


def record_io_metrics(path: str, task_name: str, metrics) -> None:
    """Merge one task's chunk-IO counter deltas into ``io_metrics.json``.

    Schema: ``{"version": 2, "tasks": {uid: {counter: total, ...}},
    "provenance": {uid: {"host:pid": {"host", "pid", "last_updated",
    "merges", "counters"}}}}``.  Counters merge *additively* per task uid —
    a resumed run's second pass, or concurrent cluster job processes
    writing over the shared filesystem, accumulate into one total (same
    file-lock discipline as :func:`record_failures`).  The additive merge
    alone makes a cluster worker's delta indistinguishable from the
    submitter's, so every merge also stamps a **provenance** entry for the
    writing process: which host:pid contributed, when it last wrote, how
    many times it merged, and which counter keys it moved — multi-process
    runs stay attributable per contributor.  Derived figures (hit rate,
    bytes saved) are computed at render time by
    ``scripts/failures_report.py``, never stored.
    """
    import socket

    with file_lock(path):
        doc = read_json_if_valid(path) or {}
        # version 2 = the provenance map; the tasks schema is unchanged,
        # so version-1 readers keep working
        doc["version"] = max(2, int(doc.get("version") or 1))
        tasks = doc.setdefault("tasks", {})
        cur = dict(tasks.get(task_name) or {})
        moved = []
        for k, v in dict(metrics).items():
            if isinstance(v, (int, float)) and isinstance(
                cur.get(k), (int, float)
            ):
                cur[k] = cur[k] + v
            else:
                cur[k] = v
            if not isinstance(v, (int, float)) or v:
                moved.append(str(k))
        tasks[task_name] = cur
        host, pid = socket.gethostname(), os.getpid()
        prov = doc.setdefault("provenance", {}).setdefault(task_name, {})
        entry = dict(prov.get(f"{host}:{pid}") or {})
        entry.update({
            "host": host,
            "pid": pid,
            "last_updated": _now(),
            "merges": int(entry.get("merges", 0)) + 1,
            "counters": sorted(set(entry.get("counters") or []) | set(moved)),
        })
        prov[f"{host}:{pid}"] = entry
        atomic_write_json(path, doc)


def _marker_dir(tmp_folder: str, task_name: str) -> str:
    d = os.path.join(tmp_folder, "markers", task_name)
    os.makedirs(d, exist_ok=True)
    return d


def log_block_success(tmp_folder: str, task_name: str, block_id: int):
    """Record that one block of a task finished (block-level resume grain).
    Atomic: a kill mid-write must not leave a torn marker that a resumed
    run would count as done."""
    path = os.path.join(_marker_dir(tmp_folder, task_name), f"block_{block_id}.json")
    atomic_write_json(path, {"block_id": block_id, "time": _now()})


def log_job_success(tmp_folder: str, task_name: str, job_id: int):
    path = os.path.join(_marker_dir(tmp_folder, task_name), f"job_{job_id}.json")
    atomic_write_json(path, {"job_id": job_id, "time": _now()})


def blocks_done(tmp_folder: str, task_name: str) -> List[int]:
    """Block ids with a *valid* success marker.  Torn markers (partial
    writes from a kill predating atomic markers, or filesystem damage) are
    pruned and reported as not-done so the block re-runs."""
    d = _marker_dir(tmp_folder, task_name)
    out = []
    for fname in os.listdir(d):
        if fname.startswith("block_") and fname.endswith(".json"):
            block_id = int(fname[len("block_"):-len(".json")])
            if read_json_if_valid(os.path.join(d, fname)) is None:
                try:
                    os.remove(os.path.join(d, fname))
                except OSError:
                    pass
                continue
            out.append(block_id)
    return sorted(out)


def jobs_done(tmp_folder: str, task_name: str) -> List[int]:
    d = _marker_dir(tmp_folder, task_name)
    return sorted(
        int(f[len("job_"):-len(".json")])
        for f in os.listdir(d)
        if f.startswith("job_") and f.endswith(".json")
    )


def clean_up_for_retry(tmp_folder: str, task_name: str):
    """Drop job-level markers so a failed task re-checks its blocks."""
    d = _marker_dir(tmp_folder, task_name)
    for fname in os.listdir(d):
        if fname.startswith("job_"):
            os.remove(os.path.join(d, fname))


def clear_block_markers(tmp_folder: str, task_name: str):
    """Drop ALL of a task's markers — block grain included.

    Used when the data the markers describe no longer exists: an in-memory
    handoff output (docs/PERFORMANCE.md "Task-graph fusion") dies with its
    process, so markers a previous process wrote would make a resumed run
    skip blocks whose results were never stored anywhere.
    """
    d = _marker_dir(tmp_folder, task_name)
    for fname in os.listdir(d):
        if fname.startswith(("block_", "job_")):
            try:
                os.remove(os.path.join(d, fname))
            except OSError:
                pass


def _now() -> str:
    return datetime.datetime.now().isoformat()


def python_executable() -> str:
    """Interpreter for re-executing framework entry points in batch jobs."""
    return sys.executable
