"""Parse run artifacts into runtime/throughput reports.

Re-design of the reference's ``cluster_tools/utils/parse_utils.py``
(SURVEY.md §2a "Utils": "parse job logs -> runtimes"; §5.1 tracing).  The
rebuild's tasks write structured success manifests (``<uid>.success.json``
with ``runtime_s``) and per-block JSON markers with timestamps, so the
report comes from parsing those instead of grepping free-form log lines.

``parse_runtimes`` -> per-task wall-clock table; ``parse_block_timeline``
-> per-block completion times (for stragglers); ``report`` -> a printable
summary with voxels/sec when a volume size is given.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional


def parse_runtimes(tmp_folder: str) -> Dict[str, Dict]:
    """Per-task entries from every success manifest in ``tmp_folder``:
    {uid: {task, runtime_s, target, ...extra manifest fields}}."""
    out: Dict[str, Dict] = {}
    for path in sorted(glob.glob(os.path.join(tmp_folder, "*.success.json"))):
        uid = os.path.basename(path)[: -len(".success.json")]
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        doc["task"] = uid.rsplit(".", 1)[0]
        out[uid] = doc
    return out


def parse_block_timeline(tmp_folder: str, uid: str) -> List[Dict]:
    """Per-block completion records of one task (sorted by time); useful
    for straggler analysis (the reference's per-job runtime parsing)."""
    d = os.path.join(tmp_folder, "markers", uid)
    if not os.path.isdir(d):
        return []
    records = []
    for fname in os.listdir(d):
        if not (fname.startswith("block_") and fname.endswith(".json")):
            continue
        try:
            with open(os.path.join(d, fname)) as f:
                records.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    return sorted(records, key=lambda r: r.get("time", ""))


def report(tmp_folder: str, n_voxels: Optional[int] = None) -> str:
    """Printable per-task runtime summary, slowest first; with ``n_voxels``
    adds voxels/sec per blockwise task."""
    rows = parse_runtimes(tmp_folder)
    lines = [f"{'task':40s} {'runtime_s':>10s} {'voxels/s':>12s}"]
    for uid, doc in sorted(
        rows.items(), key=lambda kv: -kv[1].get("runtime_s", 0.0)
    ):
        rt = doc.get("runtime_s", 0.0)
        vps = (
            f"{n_voxels / rt:12.3g}"
            if n_voxels and rt > 0 and doc.get("n_blocks")
            else f"{'-':>12s}"
        )
        lines.append(f"{doc['task']:40s} {rt:10.2f} {vps}")
    total = sum(d.get("runtime_s", 0.0) for d in rows.values())
    lines.append(f"{'TOTAL':40s} {total:10.2f}")
    return "\n".join(lines)
