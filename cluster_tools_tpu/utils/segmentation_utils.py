"""Segmentation helpers: the multicut-solver registry.

Mirrors the reference's ``cluster_tools/utils/segmentation_utils.py``
(SURVEY.md §2a "Utils"), whose ``key_to_agglomerator`` mapped solver names
(kernighan-lin, greedy-additive, fusion-moves, ...) to nifty C++ solvers.
Here every key maps to its faithful counterpart in :mod:`..ops.multicut`:
GAEC, true Kernighan-Lin (gain sequences + joins), fusion moves, and the
attractive-component decomposition solver — plus the round-based parallel
engine of :mod:`..ops.contraction` as ``gaec_parallel`` /
``average_parallel``, the vectorized path for RAG-scale problems.
"""

from __future__ import annotations

import numpy as np

from ..ops.contraction import average_parallel, gaec_parallel
from ..ops.multicut import (
    decompose_solve,
    fusion_moves,
    greedy_additive,
    greedy_node_moves,
    kernighan_lin,
)


def _solve_greedy(n_nodes, edges, costs, **kw):
    return greedy_additive(n_nodes, edges, costs, **kw)


def _solve_kl(n_nodes, edges, costs, **kw):
    return kernighan_lin(n_nodes, edges, costs, **kw)


def _solve_fm(n_nodes, edges, costs, **kw):
    return fusion_moves(n_nodes, edges, costs, **kw)


def _solve_decompose(n_nodes, edges, costs, **kw):
    return decompose_solve(n_nodes, edges, costs, **kw)


def _solve_node_moves(n_nodes, edges, costs, **kw):
    return greedy_node_moves(n_nodes, edges, costs, **kw)


def _solve_gaec_parallel(n_nodes, edges, costs, **kw):
    return gaec_parallel(n_nodes, edges, costs, **kw)


def _solve_average_parallel(n_nodes, edges, costs, **kw):
    # registry solvers speak signed costs; invert the probs_to_costs
    # transform (beta = 0.5) so the linkage engine sees probabilities —
    # cost 0 maps to p = 0.5, the default merge threshold.  The inversion
    # assumes UNWEIGHTED beta=0.5 costs: under weighting_scheme='size' (or
    # beta != 0.5) the recovered pseudo-probabilities are distorted toward
    # 0.5 for small-contact edges — pair this solver with unweighted costs,
    # or call average_parallel directly with the raw probabilities
    probs = 1.0 / (1.0 + np.exp(np.asarray(costs, np.float64)))
    return average_parallel(n_nodes, edges, probs, **kw)


# solvers that take a SolverCheckpoint (ops.multicut.SolverCheckpoint) and
# persist their partition between outer sweeps — the task layer passes one
# for the global solve so preemption resumes mid-solve (SURVEY.md §5.3)
_solve_kl.supports_checkpoint = True


key_to_agglomerator = {
    "greedy-additive": _solve_greedy,
    "kernighan-lin": _solve_kl,
    "fusion-moves": _solve_fm,
    "decomposition": _solve_decompose,
    "greedy-node-moves": _solve_node_moves,
    "gaec_parallel": _solve_gaec_parallel,
    "average_parallel": _solve_average_parallel,
}


def get_multicut_solver(key: str):
    try:
        return key_to_agglomerator[key]
    except KeyError:
        raise ValueError(
            f"unknown multicut solver {key!r}; "
            f"available: {sorted(key_to_agglomerator)}"
        )


def apply_size_filter(
    ids: np.ndarray, sizes: np.ndarray, size_threshold: int
) -> np.ndarray:
    """Mask of segment ids whose size is below ``size_threshold``."""
    return ids[sizes < size_threshold]
