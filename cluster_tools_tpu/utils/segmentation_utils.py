"""Segmentation helpers: the multicut-solver registry.

Mirrors the reference's ``cluster_tools/utils/segmentation_utils.py``
(SURVEY.md §2a "Utils"), whose ``key_to_agglomerator`` mapped solver names
(kernighan-lin, greedy-additive, fusion-moves, ...) to nifty C++ solvers.
Here the solvers live in :mod:`..ops.multicut`; 'fusion-moves' maps to the
strongest available pipeline (GAEC + KL refinement with restarts) rather
than a faithful FM implementation.
"""

from __future__ import annotations

import numpy as np

from ..ops.multicut import greedy_additive, kernighan_lin


def _solve_greedy(n_nodes, edges, costs, **kw):
    return greedy_additive(n_nodes, edges, costs, **kw)


def _solve_kl(n_nodes, edges, costs, **kw):
    return kernighan_lin(n_nodes, edges, costs, **kw)


def _solve_strong(n_nodes, edges, costs, **kw):
    """GAEC init + KL refinement; the default 'quality' solver."""
    init = greedy_additive(n_nodes, edges, costs)
    return kernighan_lin(n_nodes, edges, costs, init_labels=init, **kw)


key_to_agglomerator = {
    "greedy-additive": _solve_greedy,
    "kernighan-lin": _solve_kl,
    "decomposition": _solve_strong,
    "fusion-moves": _solve_strong,
}


def get_multicut_solver(key: str):
    try:
        return key_to_agglomerator[key]
    except KeyError:
        raise ValueError(
            f"unknown multicut solver {key!r}; "
            f"available: {sorted(key_to_agglomerator)}"
        )


def apply_size_filter(
    ids: np.ndarray, sizes: np.ndarray, size_threshold: int
) -> np.ndarray:
    """Mask of segment ids whose size is below ``size_threshold``."""
    return ids[sizes < size_threshold]
