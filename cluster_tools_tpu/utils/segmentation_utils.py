"""Segmentation helpers: the multicut-solver registry.

Mirrors the reference's ``cluster_tools/utils/segmentation_utils.py``
(SURVEY.md §2a "Utils"), whose ``key_to_agglomerator`` mapped solver names
(kernighan-lin, greedy-additive, fusion-moves, ...) to nifty C++ solvers.
Here every key maps to its faithful counterpart in :mod:`..ops.multicut`:
GAEC, true Kernighan-Lin (gain sequences + joins), fusion moves, and the
attractive-component decomposition solver.
"""

from __future__ import annotations

import numpy as np

from ..ops.multicut import (
    decompose_solve,
    fusion_moves,
    greedy_additive,
    greedy_node_moves,
    kernighan_lin,
)


def _solve_greedy(n_nodes, edges, costs, **kw):
    return greedy_additive(n_nodes, edges, costs, **kw)


def _solve_kl(n_nodes, edges, costs, **kw):
    return kernighan_lin(n_nodes, edges, costs, **kw)


def _solve_fm(n_nodes, edges, costs, **kw):
    return fusion_moves(n_nodes, edges, costs, **kw)


def _solve_decompose(n_nodes, edges, costs, **kw):
    return decompose_solve(n_nodes, edges, costs, **kw)


def _solve_node_moves(n_nodes, edges, costs, **kw):
    return greedy_node_moves(n_nodes, edges, costs, **kw)


# solvers that take a SolverCheckpoint (ops.multicut.SolverCheckpoint) and
# persist their partition between outer sweeps — the task layer passes one
# for the global solve so preemption resumes mid-solve (SURVEY.md §5.3)
_solve_kl.supports_checkpoint = True


key_to_agglomerator = {
    "greedy-additive": _solve_greedy,
    "kernighan-lin": _solve_kl,
    "fusion-moves": _solve_fm,
    "decomposition": _solve_decompose,
    "greedy-node-moves": _solve_node_moves,
}


def get_multicut_solver(key: str):
    try:
        return key_to_agglomerator[key]
    except KeyError:
        raise ValueError(
            f"unknown multicut solver {key!r}; "
            f"available: {sorted(key_to_agglomerator)}"
        )


def apply_size_filter(
    ids: np.ndarray, sizes: np.ndarray, size_threshold: int
) -> np.ndarray:
    """Mask of segment ids whose size is below ``size_threshold``."""
    return ids[sizes < size_threshold]
