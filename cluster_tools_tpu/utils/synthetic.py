"""Deterministic synthetic EM volumes with exact ground truth.

The reference's test strategy is anchored on a CREMI-derived EM crop
(SURVEY.md §4): anisotropic sampling (40, 4, 4) nm, cell-body objects with
membrane boundaries, an ignore mask.  No real data ships with this repo, so
this generator produces the same *shape* of problem with a known answer:

- ground truth = anisotropic Voronoi cells of Poisson-sampled centers
  (convex-ish polyhedra, columnar under the z-anisotropy — the right
  geometry class for sectioned EM at this scale),
- boundary map = exponential falloff from the inter-cell interfaces with
  optional smoothing and additive noise (membrane-like ridges),
- mask = inscribed ellipsoid (the "bounding nucleus / padding" pattern).

Everything derives from one rng seed; the GT is exact by construction, so
end-to-end segmentation quality (VI / adapted-RAND vs GT) is a meaningful
assertion rather than a smoke check.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def synthetic_em_volume(
    shape: Tuple[int, int, int] = (24, 96, 96),
    n_objects: int = 12,
    sampling: Sequence[float] = (40.0, 4.0, 4.0),
    boundary_width: float = 2.0,
    noise: float = 0.05,
    smooth: float = 0.7,
    with_mask: bool = True,
    seed: int = 0,
):
    """Returns ``(boundaries float32 [0,1], gt uint64, mask bool)``.

    ``boundary_width`` is the membrane falloff scale in (in-plane) voxel
    units.  Labels are 1..n_objects, 0 only outside the mask.
    """
    rng = np.random.default_rng(seed)
    shape = tuple(int(s) for s in shape)
    samp = np.asarray(sampling, np.float64)

    # Poisson-sampled centers in physical coordinates
    phys = np.array(shape) * samp
    centers = rng.random((n_objects, 3)) * phys

    zz, yy, xx = np.meshgrid(
        np.arange(shape[0]) * samp[0],
        np.arange(shape[1]) * samp[1],
        np.arange(shape[2]) * samp[2],
        indexing="ij",
    )
    coords = np.stack([zz, yy, xx], axis=-1)  # (z, y, x, 3) physical

    # nearest-center distances -> GT cells (anisotropic Voronoi)
    d = np.full(shape, np.inf)
    gt = np.zeros(shape, np.uint64)
    for i, c in enumerate(centers):
        di = np.sqrt(((coords - c) ** 2).sum(-1))
        closer = di < d
        d = np.where(closer, di, d)
        gt[closer] = i + 1

    # membrane map: voxel-space falloff from the exact GT interfaces (a
    # physical-metric falloff would fade z-interfaces by the anisotropy —
    # the nearest voxel to a z-interface sits half a 40nm step away)
    from scipy import ndimage

    # single-sided marking: membranes are ONE voxel thick (the lower-index
    # voxel of each differing pair) — hole-free for 6-connected paths, and
    # thin membranes keep the ambiguous-ownership band small relative to the
    # cells (the quality metrics are computed over every voxel)
    interfaces = np.zeros(shape, bool)
    for axis in range(3):
        a = [slice(None)] * 3
        b = [slice(None)] * 3
        a[axis] = slice(0, -1)
        b[axis] = slice(1, None)
        diff = gt[tuple(a)] != gt[tuple(b)]
        interfaces[tuple(a)] |= diff
    # mild z-weighting keeps membranes one-ish section thick, as in
    # section-imaged EM
    vox_dist = ndimage.distance_transform_edt(~interfaces, sampling=(2.0, 1.0, 1.0))
    boundaries = np.exp(-vox_dist / max(boundary_width, 1e-6))
    if smooth > 0:
        boundaries = ndimage.gaussian_filter(boundaries, smooth)
    if noise > 0:
        boundaries = boundaries + rng.normal(0, noise, shape)
    boundaries = np.clip(boundaries, 0.0, 1.0).astype(np.float32)

    if with_mask:
        rel = (np.stack([zz, yy, xx], -1) / phys) * 2.0 - 1.0
        mask = (rel**2).sum(-1) <= 1.0
    else:
        mask = np.ones(shape, bool)
    gt = np.where(mask, gt, 0).astype(np.uint64)
    return boundaries, gt, mask


def grid_rag(
    g: int = 16, seed: int = 0, mu: float = 0.2, sigma: float = 1.0
) -> Tuple[int, np.ndarray, np.ndarray]:
    """Grid-adjacency RAG (the shape of watershed-fragment graphs) with
    noisy signed costs: mostly-attractive with repulsive salt, nothing
    planted — the adversarial regime for greedy-order differences between
    agglomeration solvers.  Returns ``(n_nodes, edges [m, 2], costs [m])``.
    Shared by the contraction oracle tests and bench's solver-scale record
    so both measure the same instance family."""
    rng = np.random.default_rng(seed)
    n = g**3
    ids = np.arange(n).reshape(g, g, g)
    parts = []
    for ax in range(3):
        a = np.moveaxis(ids, ax, 0)[:-1].ravel()
        b = np.moveaxis(ids, ax, 0)[1:].ravel()
        parts.append(np.stack([a, b], 1))
    edges = np.concatenate(parts)
    costs = rng.normal(mu, sigma, len(edges))
    return n, edges, costs
