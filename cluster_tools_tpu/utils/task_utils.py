"""Config serialization helpers (reference: ``utils/task_utils.py``).

The reference's config system is JSON files in a ``config_dir``: one
``global.config`` plus one ``<task_name>.config`` per task, with defaults from
``<Task>.default_task_config()`` (SURVEY.md §5.6).  Same contract here.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np


def _default(o):
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not json-serializable: {type(o)}")


def dump_config(path: str, config: Dict[str, Any]):
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # atomic (docs/ANALYSIS.md CT002): configs live in a shared config_dir
    # read by concurrent cluster jobs — a kill mid-write must leave the old
    # config or nothing, never half a JSON document
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(config, f, indent=2, sort_keys=True, default=_default)
    os.replace(tmp, path)


def load_config(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def load_task_config(
    config_dir: str, task_name: str, defaults: Optional[Dict[str, Any]] = None
) -> Dict[str, Any]:
    """Defaults <- global.config <- <task_name>.config, later wins."""
    config = dict(defaults or {})
    for fname in ("global.config", f"{task_name}.config"):
        path = os.path.join(config_dir, fname)
        if os.path.exists(path):
            config.update(load_config(path))
    return config
