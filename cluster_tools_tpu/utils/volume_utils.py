"""Block-grid math and bounding-box helpers.

TPU-native replacement for the reference's ``cluster_tools/utils/volume_utils.py``
(which wrapped ``nifty.tools.blocking`` — C++ — for block-grid math and z5py /
h5py for IO; see SURVEY.md §2a "Utils").  Here the blocking math is pure
Python/NumPy (it is driver-side control logic, never hot), and chunked-array IO
lives in :mod:`cluster_tools_tpu.io` on tensorstore (C++ under the hood).

A "block" is an axis-aligned box of the volume.  Kernels read blocks *with a
halo* (clipped at the volume border) and write only the *inner* block, so all
writes are disjoint — the reference's central correctness-by-construction
invariant (SURVEY.md §5.2) which we preserve.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

Coord = Tuple[int, ...]
BoundingBox = Tuple[slice, ...]


@dataclass(frozen=True)
class Block:
    """One block of a :class:`Blocking` grid.

    ``begin``/``end`` delimit the inner block; ``outer_begin``/``outer_end``
    the halo-extended (border-clipped) region actually read by kernels.
    """

    block_id: int
    begin: Coord
    end: Coord
    outer_begin: Coord
    outer_end: Coord

    @property
    def shape(self) -> Coord:
        return tuple(e - b for b, e in zip(self.begin, self.end))

    @property
    def outer_shape(self) -> Coord:
        return tuple(e - b for b, e in zip(self.outer_begin, self.outer_end))

    @property
    def bb(self) -> BoundingBox:
        return tuple(slice(b, e) for b, e in zip(self.begin, self.end))

    @property
    def outer_bb(self) -> BoundingBox:
        return tuple(slice(b, e) for b, e in zip(self.outer_begin, self.outer_end))

    @property
    def inner_in_outer_bb(self) -> BoundingBox:
        """Slice selecting the inner block out of the outer (halo) block."""
        return tuple(
            slice(b - ob, e - ob)
            for b, e, ob in zip(self.begin, self.end, self.outer_begin)
        )


class Blocking:
    """Regular block decomposition of an N-D volume.

    Replacement for ``nifty.tools.blocking`` used throughout the reference's
    ``BaseClusterTask`` to compute the block grid (SURVEY.md §2a "Task
    runtime").
    """

    def __init__(self, shape: Sequence[int], block_shape: Sequence[int]):
        if len(shape) != len(block_shape):
            raise ValueError(
                f"shape {shape} and block_shape {block_shape} must have the same rank"
            )
        if any(b <= 0 for b in block_shape):
            raise ValueError(f"invalid block_shape {block_shape}")
        self.shape = tuple(int(s) for s in shape)
        self.block_shape = tuple(int(b) for b in block_shape)
        self.grid_shape = tuple(
            max(1, math.ceil(s / b)) for s, b in zip(self.shape, self.block_shape)
        )
        self.n_blocks = int(np.prod(self.grid_shape))

    def block_grid_position(self, block_id: int) -> Coord:
        if not 0 <= block_id < self.n_blocks:
            raise IndexError(f"block_id {block_id} out of range [0, {self.n_blocks})")
        return tuple(np.unravel_index(block_id, self.grid_shape))

    def grid_position_to_id(self, pos: Sequence[int]) -> int:
        return int(np.ravel_multi_index(tuple(pos), self.grid_shape))

    def get_block(self, block_id: int, halo: Optional[Sequence[int]] = None) -> Block:
        pos = self.block_grid_position(block_id)
        begin = tuple(p * b for p, b in zip(pos, self.block_shape))
        end = tuple(
            min((p + 1) * b, s) for p, b, s in zip(pos, self.block_shape, self.shape)
        )
        if halo is None:
            outer_begin, outer_end = begin, end
        else:
            if len(halo) != len(self.shape):
                raise ValueError(f"halo {halo} has wrong rank for shape {self.shape}")
            outer_begin = tuple(max(0, b - h) for b, h in zip(begin, halo))
            outer_end = tuple(min(s, e + h) for e, h, s in zip(end, halo, self.shape))
        return Block(block_id, begin, end, outer_begin, outer_end)

    def neighbor_id(self, block_id: int, axis: int, direction: int) -> Optional[int]:
        """Grid neighbor of ``block_id`` along ``axis`` (+1/-1), or None at the edge."""
        offset = [0] * len(self.shape)
        offset[axis] = direction
        return self.neighbor_id_offset(block_id, offset)

    def neighbor_id_offset(
        self, block_id: int, offset: Sequence[int]
    ) -> Optional[int]:
        """Grid neighbor at a per-axis offset (diagonals included), or None.

        The general form of :meth:`neighbor_id` needed for connectivity>1
        stitching, where edge-/corner-adjacent blocks also share label
        equivalences.
        """
        pos = [
            p + int(o) for p, o in zip(self.block_grid_position(block_id), offset)
        ]
        if any(not 0 <= p < g for p, g in zip(pos, self.grid_shape)):
            return None
        return self.grid_position_to_id(pos)

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"Blocking(shape={self.shape}, block_shape={self.block_shape}, "
            f"grid={self.grid_shape}, n_blocks={self.n_blocks})"
        )


def blocks_in_volume(
    shape: Sequence[int],
    block_shape: Sequence[int],
    roi_begin: Optional[Sequence[int]] = None,
    roi_end: Optional[Sequence[int]] = None,
) -> List[int]:
    """IDs of all blocks intersecting the ROI (whole volume if no ROI).

    Mirrors the reference's ``vu.blocks_in_volume`` driver helper.
    """
    blocking = Blocking(shape, block_shape)
    if roi_begin is None and roi_end is None:
        return list(range(blocking.n_blocks))
    roi_begin = tuple(0 if b is None else int(b) for b in (roi_begin or [None] * len(shape)))
    roi_end = tuple(
        s if e is None else int(e)
        for e, s in zip(roi_end or [None] * len(shape), shape)
    )
    # grid-aligned range of block positions overlapping the roi
    lo = [rb // bs for rb, bs in zip(roi_begin, block_shape)]
    hi = [
        min(gs, math.ceil(re / bs))
        for re, bs, gs in zip(roi_end, block_shape, blocking.grid_shape)
    ]
    ids = []
    for pos in np.ndindex(*[h - l for l, h in zip(lo, hi)]):
        ids.append(blocking.grid_position_to_id([p + l for p, l in zip(pos, lo)]))
    return ids


def bb_from_roi(roi_begin: Sequence[int], roi_end: Sequence[int]) -> BoundingBox:
    return tuple(slice(int(b), int(e)) for b, e in zip(roi_begin, roi_end))


def pad_block_to(
    data: np.ndarray, target_shape: Sequence[int], mode: str = "constant", **kwargs
) -> np.ndarray:
    """Pad a border-clipped block up to ``target_shape`` (for static-shape jit).

    XLA requires static shapes, so edge blocks (smaller after clipping) are
    padded up to the full halo shape before entering the device batch; kernels
    receive a validity mask instead of a dynamic shape.
    """
    pad = [(0, t - s) for s, t in zip(data.shape, target_shape)]
    if any(p[1] < 0 for p in pad):
        raise ValueError(f"block {data.shape} larger than target {target_shape}")
    if all(p[1] == 0 for p in pad):
        return data
    return np.pad(data, pad, mode=mode, **kwargs)


def normalize(data: np.ndarray, eps: float = 1e-6) -> np.ndarray:
    """Min/max normalize to [0, 1] float32 (reference: ``vu.normalize``)."""
    data = data.astype(np.float32)
    lo, hi = float(data.min()), float(data.max())
    return (data - lo) / max(hi - lo, eps)


def file_reader(path: str, mode: str = "a"):
    """Open a chunked container by extension (reference: ``vu.file_reader``).

    ``.n5`` / ``.zarr`` / ``.zr`` -> tensorstore-backed container;
    ``.h5`` / ``.hdf5`` / ``.hdf`` -> h5py.  Returned objects share a small
    dict-like API: ``f[key]`` -> dataset with ``shape/dtype/chunks``, numpy
    ``__getitem__`` / ``__setitem__``, and ``create_dataset``.
    """
    from ..io import open_container

    return open_container(path, mode=mode)


def get_shape(path: str, key: str) -> Tuple[int, ...]:
    with file_reader(path, "r") as f:
        return tuple(f[key].shape)
