"""End-to-end segmentation workflows.

Re-design of the reference's ``cluster_tools/workflows.py`` (SURVEY.md §2a
"Workflows", §3.3): the flagship ``MulticutSegmentationWorkflow`` chains

    watershed (supervoxels) -> graph -> edge features -> costs
    -> hierarchical multicut -> write

with each stage the task family from :mod:`.tasks`.  Workflow classes follow
the reference's pattern: one class per pipeline, ``target=`` selecting the
backend trio member, parameters forwarded to the stage tasks, and
``get_config()`` aggregating every stage's defaults for the config_dir.
"""

from __future__ import annotations

import os
from typing import Any, Dict

from .runtime.task import WorkflowBase, get_task_cls
from .tasks import costs as costs_mod
from .tasks import features as feat_mod
from .tasks import graph as graph_mod
from .tasks import multicut as mc_mod
from .tasks import watershed as ws_mod
from .tasks import write as write_mod
from .tasks.multicut import assignments_path


def _pick(p: Dict[str, Any], *names: str) -> Dict[str, Any]:
    return {k: p[k] for k in names if k in p}


class MulticutSegmentationWorkflow(WorkflowBase):
    """boundary map -> supervoxels -> RAG -> features -> costs -> multicut
    -> segmentation.

    Params:
      ``input_path/input_key``    boundary/affinity map (float),
      ``ws_path/ws_key``          supervoxel dataset (created unless
                                  ``skip_ws``),
      ``output_path/output_key``  final segmentation,
      ``skip_ws``                 use an existing supervoxel dataset,
      ``two_pass_ws``             checkerboard two-pass watershed,
      watershed params (``threshold``, ``sigma_seeds``, ``halo``, ...),
      ``channel``                 boundary-map channel selector for features,
      ``beta``/``weighting_scheme`` cost transform,
      ``n_scales``                subproblem levels,
      ``agglomerator``            solver key for subproblems + global solve.
    """

    task_name = "multicut_segmentation_workflow"

    def requires(self):
        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        ws_path, ws_key = p["ws_path"], p["ws_key"]
        deps = list(self.dependencies)

        if not p.get("skip_ws", False):
            ws = ws_mod.WatershedWorkflow(
                **common,
                target=self.target,
                dependencies=deps,
                input_path=p["input_path"],
                input_key=p["input_key"],
                output_path=ws_path,
                output_key=ws_key,
                two_pass=p.get("two_pass_ws", False),
                **_pick(
                    p,
                    "threshold",
                    "sigma_seeds",
                    "min_seed_distance",
                    "sampling",
                    "size_filter",
                    "two_d",
                    "halo",
                    "block_shape",
                    "mask_path",
                    "mask_key",
                ),
            )
            deps = [ws]

        grid = _pick(p, "block_shape", "roi_begin", "roi_end")
        g = graph_mod.GraphWorkflow(
            **common,
            target=self.target,
            dependencies=deps,
            input_path=ws_path,
            input_key=ws_key,
            **grid,
        )
        feats = feat_mod.EdgeFeaturesWorkflow(
            **common,
            target=self.target,
            dependencies=[g],
            input_path=p["input_path"],
            input_key=p["input_key"],
            labels_path=ws_path,
            labels_key=ws_key,
            **_pick(p, "channel"),
            **grid,
        )
        costs = get_task_cls(costs_mod, "ProbsToCosts", self.target)(
            **common,
            dependencies=[feats],
            **_pick(p, "beta", "weighting_scheme", "weighting_exponent"),
        )
        mc = mc_mod.MulticutWorkflow(
            **common,
            target=self.target,
            dependencies=[costs],
            input_path=ws_path,
            input_key=ws_key,
            **_pick(
                p, "n_scales", "agglomerator",
                "solver_shards", "reduce_fanout", "solver_workers",
            ),
            **grid,
        )
        write = get_task_cls(write_mod, "Write", self.target)(
            **common,
            dependencies=[mc],
            input_path=ws_path,
            input_key=ws_key,
            output_path=p["output_path"],
            output_key=p["output_key"],
            assignment_path=assignments_path(self.tmp_folder),
            **_pick(p, "block_shape"),
        )
        return [write]

    @staticmethod
    def get_config() -> Dict[str, Dict[str, Any]]:
        """Aggregated per-task default configs (reference pattern: workflows
        expose ``get_config()`` so users can materialize + edit the JSONs)."""
        return {
            "global": WorkflowBase.default_global_config(),
            "watershed": ws_mod.WatershedBase.default_task_config(),
            "two_pass_watershed": ws_mod.TwoPassWatershedBase.default_task_config(),
            "initial_sub_graphs": graph_mod.InitialSubGraphsBase.default_task_config(),
            "block_edge_features": feat_mod.BlockEdgeFeaturesBase.default_task_config(),
            "probs_to_costs": costs_mod.ProbsToCostsBase.default_task_config(),
            "solve_subproblems": mc_mod.SolveSubproblemsBase.default_task_config(),
            "solve_global": mc_mod.SolveGlobalBase.default_task_config(),
        }


class AgglomerativeClusteringWorkflow(WorkflowBase):
    """boundary map -> supervoxels -> RAG -> features -> average-linkage
    agglomeration -> segmentation (reference:
    ``AgglomerativeClusteringWorkflow``).

    Same parameters as :class:`MulticutSegmentationWorkflow` minus the
    multicut ones, plus ``agglomeration_threshold`` (merge edges while the
    mean boundary probability is below it)."""

    task_name = "agglomerative_clustering_workflow"

    def requires(self):
        from .tasks import agglomerative_clustering as ac_mod
        from .tasks.agglomerative_clustering import agglomerative_assignments_path

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        ws_path, ws_key = p["ws_path"], p["ws_key"]
        deps = list(self.dependencies)
        if not p.get("skip_ws", False):
            ws = ws_mod.WatershedWorkflow(
                **common,
                target=self.target,
                dependencies=deps,
                input_path=p["input_path"],
                input_key=p["input_key"],
                output_path=ws_path,
                output_key=ws_key,
                two_pass=p.get("two_pass_ws", False),
                **_pick(
                    p,
                    "threshold",
                    "sigma_seeds",
                    "min_seed_distance",
                    "sampling",
                    "size_filter",
                    "two_d",
                    "halo",
                    "block_shape",
                    "mask_path",
                    "mask_key",
                ),
            )
            deps = [ws]
        grid = _pick(p, "block_shape", "roi_begin", "roi_end")
        g = graph_mod.GraphWorkflow(
            **common,
            target=self.target,
            dependencies=deps,
            input_path=ws_path,
            input_key=ws_key,
            **grid,
        )
        feats = feat_mod.EdgeFeaturesWorkflow(
            **common,
            target=self.target,
            dependencies=[g],
            input_path=p["input_path"],
            input_key=p["input_key"],
            labels_path=ws_path,
            labels_key=ws_key,
            **_pick(p, "channel"),
            **grid,
        )
        ac = get_task_cls(ac_mod, "AgglomerativeClustering", self.target)(
            **common,
            dependencies=[feats],
            threshold=p.get("agglomeration_threshold", 0.5),
        )
        write = get_task_cls(write_mod, "Write", self.target)(
            **common,
            dependencies=[ac],
            input_path=ws_path,
            input_key=ws_key,
            output_path=p["output_path"],
            output_key=p["output_key"],
            assignment_path=agglomerative_assignments_path(self.tmp_folder),
            **_pick(p, "block_shape"),
        )
        return [write]


    @staticmethod
    def get_config() -> Dict[str, Dict[str, Any]]:
        """Aggregated per-task default configs (reference pattern)."""
        from .tasks import agglomerative_clustering as ac_mod

        return {
            "global": WorkflowBase.default_global_config(),
            "watershed": ws_mod.WatershedBase.default_task_config(),
            "initial_sub_graphs": graph_mod.InitialSubGraphsBase.default_task_config(),
            "block_edge_features": feat_mod.BlockEdgeFeaturesBase.default_task_config(),
            "agglomerative_clustering":
                ac_mod.AgglomerativeClusteringBase.default_task_config(),
        }


class LiftedMulticutSegmentationWorkflow(WorkflowBase):
    """Lifted multicut segmentation (reference:
    ``LiftedMulticutSegmentationWorkflow``): the multicut chain plus a
    node-label attribution that induces sparse lifted edges —

        ws -> graph -> features -> costs
           -> node_labels (overlap with ``labels_path/labels_key``, e.g. a
              nucleus or semantic segmentation)
           -> sparse lifted neighborhood -> lifted costs
           -> hierarchical lifted multicut -> write

    Extra params over :class:`MulticutSegmentationWorkflow`:
    ``labels_path/labels_key`` (the attribution volume),
    ``max_graph_distance``, ``w_attractive``/``w_repulsive``."""

    task_name = "lifted_multicut_segmentation_workflow"

    def requires(self):
        from .tasks import lifted_features as lf_mod
        from .tasks import lifted_multicut as lmc_mod
        from .tasks import node_labels as nl_mod
        from .tasks.lifted_multicut import lmc_assignments_path

        p = self.params
        common = dict(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
        )
        ws_path, ws_key = p["ws_path"], p["ws_key"]
        deps = list(self.dependencies)
        if not p.get("skip_ws", False):
            ws = ws_mod.WatershedWorkflow(
                **common,
                target=self.target,
                dependencies=deps,
                input_path=p["input_path"],
                input_key=p["input_key"],
                output_path=ws_path,
                output_key=ws_key,
                two_pass=p.get("two_pass_ws", False),
                **_pick(
                    p,
                    "threshold",
                    "sigma_seeds",
                    "min_seed_distance",
                    "sampling",
                    "size_filter",
                    "two_d",
                    "halo",
                    "block_shape",
                    "mask_path",
                    "mask_key",
                ),
            )
            deps = [ws]
        grid = _pick(p, "block_shape", "roi_begin", "roi_end")
        g = graph_mod.GraphWorkflow(
            **common,
            target=self.target,
            dependencies=deps,
            input_path=ws_path,
            input_key=ws_key,
            **grid,
        )
        feats = feat_mod.EdgeFeaturesWorkflow(
            **common,
            target=self.target,
            dependencies=[g],
            input_path=p["input_path"],
            input_key=p["input_key"],
            labels_path=ws_path,
            labels_key=ws_key,
            **_pick(p, "channel"),
            **grid,
        )
        costs = get_task_cls(costs_mod, "ProbsToCosts", self.target)(
            **common,
            dependencies=[feats],
            **_pick(p, "beta", "weighting_scheme", "weighting_exponent"),
        )
        nl = nl_mod.NodeLabelWorkflow(
            **common,
            target=self.target,
            dependencies=[g],
            input_path=ws_path,
            input_key=ws_key,
            labels_path=p["labels_path"],
            labels_key=p["labels_key"],
            **grid,
        )
        lifted_nh = get_task_cls(
            lf_mod, "SparseLiftedNeighborhood", self.target
        )(
            **common,
            dependencies=[g],
            **_pick(p, "max_graph_distance"),
        )
        lifted_costs = get_task_cls(lf_mod, "CostsFromNodeLabels", self.target)(
            **common,
            dependencies=[nl, lifted_nh],
            **_pick(p, "w_attractive", "w_repulsive"),
        )
        lmc = lmc_mod.LiftedMulticutWorkflow(
            **common,
            target=self.target,
            dependencies=[costs, lifted_costs],
            input_path=ws_path,
            input_key=ws_key,
            **_pick(
                p, "n_scales",
                "solver_shards", "reduce_fanout", "solver_workers",
            ),
            **grid,
        )
        write = get_task_cls(write_mod, "Write", self.target)(
            **common,
            dependencies=[lmc],
            input_path=ws_path,
            input_key=ws_key,
            output_path=p["output_path"],
            output_key=p["output_key"],
            assignment_path=lmc_assignments_path(self.tmp_folder),
            **_pick(p, "block_shape"),
        )
        return [write]

    @staticmethod
    def get_config() -> Dict[str, Dict[str, Any]]:
        """Aggregated per-task default configs (reference pattern)."""
        from .tasks import lifted_features as lf_mod
        from .tasks import lifted_multicut as lmc_mod
        from .tasks import node_labels as nl_mod

        return {
            "global": WorkflowBase.default_global_config(),
            "watershed": ws_mod.WatershedBase.default_task_config(),
            "initial_sub_graphs": graph_mod.InitialSubGraphsBase.default_task_config(),
            "block_edge_features": feat_mod.BlockEdgeFeaturesBase.default_task_config(),
            "probs_to_costs": costs_mod.ProbsToCostsBase.default_task_config(),
            "block_node_labels": nl_mod.BlockNodeLabelsBase.default_task_config(),
            "sparse_lifted_neighborhood":
                lf_mod.SparseLiftedNeighborhoodBase.default_task_config(),
            "costs_from_node_labels":
                lf_mod.CostsFromNodeLabelsBase.default_task_config(),
            "solve_lifted_subproblems":
                lmc_mod.SolveLiftedSubproblemsBase.default_task_config(),
            "solve_lifted_global":
                lmc_mod.SolveLiftedGlobalBase.default_task_config(),
        }
