// Native runtime kernels for cluster_tools_tpu.
//
// The reference framework outsourced its host-side merge hot spots to C++
// (nifty.ufd union-find, nifty multicut solvers — SURVEY.md §2b).  The
// rebuild keeps the device path in JAX/XLA and provides these C++ kernels
// for the host-side merge/solver stages, loaded via ctypes
// (cluster_tools_tpu/native.py) with pure-Python fallbacks.
//
// C ABI only — no pybind11 (not in the image); arrays are passed as raw
// pointers from numpy via ctypes.

#include <cstdint>
#include <cstring>
#include <queue>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// path-halving find over an int64 parent array
inline int64_t find_root(std::vector<int64_t>& parent, int64_t x) {
  while (parent[x] != x) {
    parent[x] = parent[parent[x]];
    x = parent[x];
  }
  return x;
}

}  // namespace

extern "C" {

// Union-find over equivalence pairs; writes, for every label in
// [0, n_labels), the minimum label of its component — the same contract as
// the Python union_find_host.  Returns 0 on success.
int ct_union_find(const int64_t* pairs, int64_t n_pairs, int64_t n_labels,
                  int64_t* out_roots) {
  std::vector<int64_t> parent(n_labels);
  for (int64_t i = 0; i < n_labels; ++i) parent[i] = i;
  for (int64_t i = 0; i < n_pairs; ++i) {
    int64_t u = pairs[2 * i], v = pairs[2 * i + 1];
    if (u < 0 || v < 0 || u >= n_labels || v >= n_labels) continue;
    int64_t ru = find_root(parent, u), rv = find_root(parent, v);
    if (ru == rv) continue;
    // union by min so roots are component minima without a second pass
    if (ru < rv)
      parent[rv] = ru;
    else
      parent[ru] = rv;
  }
  for (int64_t i = 0; i < n_labels; ++i) out_roots[i] = find_root(parent, i);
  return 0;
}

// Greedy additive edge contraction (GAEC).  edges: [n_edges, 2] int64,
// costs: [n_edges] double.  Writes consecutive labels 0..k-1 to out_labels
// [n_nodes].  Matches the Python greedy_additive (ops/multicut.py) —
// contract the highest-cost edge while > stop_cost, parallel edges add.
int ct_greedy_additive(int64_t n_nodes, const int64_t* edges,
                       const double* costs, int64_t n_edges, double stop_cost,
                       int64_t* out_labels) {
  std::vector<int64_t> parent(n_nodes);
  for (int64_t i = 0; i < n_nodes; ++i) parent[i] = i;
  std::vector<std::unordered_map<int64_t, double>> nbrs(n_nodes);
  for (int64_t i = 0; i < n_edges; ++i) {
    int64_t u = edges[2 * i], v = edges[2 * i + 1];
    if (u == v || u < 0 || v < 0 || u >= n_nodes || v >= n_nodes) continue;
    nbrs[u][v] += costs[i];
    nbrs[v][u] = nbrs[u][v];
  }
  struct Entry {
    double w;
    int64_t u, v;
    bool operator<(const Entry& o) const { return w < o.w; }
  };
  std::priority_queue<Entry> heap;
  for (int64_t u = 0; u < n_nodes; ++u)
    for (auto& kv : nbrs[u])
      if (u < kv.first) heap.push({kv.second, u, kv.first});

  while (!heap.empty()) {
    Entry e = heap.top();
    heap.pop();
    if (e.w <= stop_cost) break;
    int64_t ru = find_root(parent, e.u), rv = find_root(parent, e.v);
    if (ru == rv) continue;
    auto it = nbrs[ru].find(rv);
    if (it == nbrs[ru].end() || it->second != e.w) continue;  // stale
    if (nbrs[ru].size() < nbrs[rv].size()) std::swap(ru, rv);
    parent[rv] = ru;
    nbrs[ru].erase(rv);
    for (auto& kv : nbrs[rv]) {
      int64_t x = kv.first;
      if (x == ru) continue;
      double nw = nbrs[ru][x] + kv.second;  // default 0.0 + w
      nbrs[ru][x] = nw;
      nbrs[x][ru] = nw;
      nbrs[x].erase(rv);
      if (nw > stop_cost) heap.push({nw, ru, x});
    }
    nbrs[rv].clear();
  }

  // consecutive relabeling of roots, ordered by root id (matches
  // np.unique(roots, return_inverse=True))
  std::vector<int64_t> roots(n_nodes);
  for (int64_t i = 0; i < n_nodes; ++i) roots[i] = find_root(parent, i);
  std::vector<int64_t> sorted_roots;
  sorted_roots.reserve(n_nodes);
  {
    std::vector<bool> is_root(n_nodes, false);
    for (int64_t i = 0; i < n_nodes; ++i) is_root[roots[i]] = true;
    for (int64_t i = 0; i < n_nodes; ++i)
      if (is_root[i]) sorted_roots.push_back(i);
  }
  std::unordered_map<int64_t, int64_t> dense;
  dense.reserve(sorted_roots.size() * 2);
  for (size_t i = 0; i < sorted_roots.size(); ++i)
    dense[sorted_roots[i]] = static_cast<int64_t>(i);
  for (int64_t i = 0; i < n_nodes; ++i) out_labels[i] = dense[roots[i]];
  return 0;
}

// Merge per-block edge features onto a global lexsorted edge table.
// pairs: [m, 2] uint64 (lo, hi); feats: [m, 4] double rows
// (mean, min, max, count); table: [k, 2] uint64 lexsorted unique edges.
// Accumulates count-weighted mean sums, min of mins, max of maxs, and
// count sums — the merge_feature_lists contract.  Returns the number of
// pairs not found in the table.
int64_t ct_merge_edge_features(const uint64_t* pairs, const double* feats,
                               int64_t m, const uint64_t* table, int64_t k,
                               double* wsums, double* mins, double* maxs,
                               double* counts) {
  int64_t unmatched = 0;
  for (int64_t i = 0; i < m; ++i) {
    uint64_t lo = pairs[2 * i], hi = pairs[2 * i + 1];
    int64_t a = 0, b = k;
    while (a < b) {
      int64_t mid = (a + b) / 2;
      uint64_t tl = table[2 * mid], th = table[2 * mid + 1];
      if (tl < lo || (tl == lo && th < hi))
        a = mid + 1;
      else
        b = mid;
    }
    if (a >= k || table[2 * a] != lo || table[2 * a + 1] != hi) {
      ++unmatched;
      continue;
    }
    double mean = feats[4 * i], mn = feats[4 * i + 1], mx = feats[4 * i + 2],
           cnt = feats[4 * i + 3];
    wsums[a] += mean * cnt;
    if (mn < mins[a]) mins[a] = mn;
    if (mx > maxs[a]) maxs[a] = mx;
    counts[a] += cnt;
  }
  return unmatched;
}

// Mutex watershed constraint loop (Wolf et al.; the affogato capability,
// SURVEY.md §2b).  Edges arrive PRE-SORTED by decreasing priority via
// `order` (numpy argsort on the host — the regular, vectorizable part).
// Attractive edges union their endpoint clusters unless a mutex forbids
// it; repulsive edges install a mutex between the clusters.  Mutex sets
// merge small-into-large.  Writes per-node component roots to out_roots.
int ct_mutex_watershed(int64_t n_nodes, const int64_t* u, const int64_t* v,
                       const uint8_t* is_attractive, const int64_t* order,
                       int64_t n_edges, int64_t* out_roots) {
  std::vector<int64_t> parent(n_nodes);
  std::vector<int8_t> rank(n_nodes, 0);
  for (int64_t i = 0; i < n_nodes; ++i) parent[i] = i;
  // per-root mutex partners; roots without constraints hold no entry
  std::unordered_map<int64_t, std::unordered_set<int64_t>> mutexes;

  auto has_mutex = [&](int64_t ra, int64_t rb) {
    auto it = mutexes.find(ra);
    return it != mutexes.end() && it->second.count(rb) > 0;
  };

  for (int64_t k = 0; k < n_edges; ++k) {
    const int64_t e = order[k];
    int64_t ru = find_root(parent, u[e]);
    int64_t rv = find_root(parent, v[e]);
    if (ru == rv) continue;
    if (is_attractive[e]) {
      // check against the smaller mutex set
      auto iu = mutexes.find(ru), iv = mutexes.find(rv);
      size_t su = iu == mutexes.end() ? 0 : iu->second.size();
      size_t sv = iv == mutexes.end() ? 0 : iv->second.size();
      if (su <= sv ? has_mutex(ru, rv) : has_mutex(rv, ru)) continue;
      // union by rank
      if (rank[ru] < rank[rv]) std::swap(ru, rv);
      else if (rank[ru] == rank[rv]) ++rank[ru];
      parent[rv] = ru;
      // fold rv's mutex set into ru's (small set moves), updating partners
      auto ib = mutexes.find(rv);
      if (ib != mutexes.end()) {
        auto moved = std::move(ib->second);
        mutexes.erase(ib);
        auto& ma = mutexes[ru];
        for (int64_t x : moved) {
          auto ix = mutexes.find(x);
          if (ix != mutexes.end()) {
            ix->second.erase(rv);
            ix->second.insert(ru);
          }
          ma.insert(x);
        }
      }
    } else {
      mutexes[ru].insert(rv);
      mutexes[rv].insert(ru);
    }
  }
  for (int64_t i = 0; i < n_nodes; ++i) out_roots[i] = find_root(parent, i);
  return 0;
}

}  // extern "C"
